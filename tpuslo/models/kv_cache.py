"""KV-cache representations: dense bf16 and int8-quantized.

Decode at batch > 1 or long context is KV-bandwidth-bound on TPU: every
step re-reads the whole cache, so halving KV bytes (int8) halves that
traffic and doubles the contexts/batch that fit a chip's HBM — the two
deferred items VERDICT r02 ranked highest for serving perf.

Representation is polymorphic at trace time (the branch is on pytree
structure, not data):

* dense — a ``(..., S, KV, HD)`` bf16 array, exactly the round-2 cache;
* int8 — ``{"q": int8 (..., S, KV, HD), "s": f32 (..., S, KV)}`` with
  one symmetric scale per (position, kv_head), amax over the head dim.

Reads go through :func:`kv_load`, which dequantizes ``q * s`` on the
fly; XLA fuses the upcast into the attention einsum so HBM sees int8
reads.  Writes go through :func:`kv_write_seq` (contiguous chunk at a
scalar start — prefill/verify) or :func:`kv_write_rows` (one slot per
row at per-row positions — batched decode), which quantize the incoming
bf16 slab when the cache is quantized.  ``lax.scan`` slices dict leaves
along the layer axis like any pytree, so the layer-stacked cache layout
and donation discipline are unchanged.

The reference has no KV cache at all (llama.cpp owns serving,
``/root/reference/demo/llama-cpp/README.md:22-24``); this module is
TPU-native serving surface the reference could not express.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any

KV_DTYPES = ("bf16", "int8")


def validate_kv_dtype(kv_dtype: str) -> str:
    """One source of truth for the engine constructors' dtype guard."""
    if kv_dtype not in KV_DTYPES:
        raise ValueError(
            f"kv_dtype must be one of {KV_DTYPES}, got {kv_dtype!r}"
        )
    return kv_dtype


def quantize_kv(x: jax.Array) -> dict:
    """bf16 ``(..., KV, HD)`` -> {"q": int8, "s": f32 over HD}."""
    x32 = x.astype(jnp.float32)
    s = jnp.max(jnp.abs(x32), axis=-1) / 127.0
    s = jnp.maximum(s, 1e-12)
    q = jnp.clip(jnp.round(x32 / s[..., None]), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s}


def kv_load(kv, dtype=jnp.bfloat16) -> jax.Array:
    """Materialize a cache operand for attention (dequantizing if
    needed).  Under jit the dequant fuses into the consuming einsum."""
    if isinstance(kv, dict):
        return (kv["q"].astype(jnp.float32) * kv["s"][..., None]).astype(dtype)
    return kv


def kv_write_seq(kv, new: jax.Array, start) -> PyTree:
    """Write a contiguous ``(B, K, KV, HD)`` chunk at position ``start``
    into a ``(B, S, KV, HD)``-shaped cache (either representation)."""
    if isinstance(kv, dict):
        qs = quantize_kv(new)
        return {
            "q": lax.dynamic_update_slice(kv["q"], qs["q"], (0, start, 0, 0)),
            "s": lax.dynamic_update_slice(kv["s"], qs["s"], (0, start, 0)),
        }
    return lax.dynamic_update_slice(kv, new, (0, start, 0, 0))


def kv_write_stacked(kv, new: jax.Array) -> PyTree:
    """Write a layer-stacked ``(L, B, K, KV, HD)`` slab at position 0
    (the prefill path: the scan emits all layers' KV at once)."""
    if isinstance(kv, dict):
        qs = quantize_kv(new)
        return {
            "q": lax.dynamic_update_slice(kv["q"], qs["q"], (0, 0, 0, 0, 0)),
            "s": lax.dynamic_update_slice(kv["s"], qs["s"], (0, 0, 0, 0)),
        }
    return lax.dynamic_update_slice(kv, new, (0, 0, 0, 0, 0))


def kv_write_rows(kv, new: jax.Array, rows: jax.Array, pos: jax.Array) -> PyTree:
    """Scatter one ``(B, KV, HD)`` slot per row at per-row positions
    (the vector-length batched decode path)."""
    if isinstance(kv, dict):
        qs = quantize_kv(new)
        return {
            "q": kv["q"].at[rows, pos].set(qs["q"]),
            "s": kv["s"].at[rows, pos].set(qs["s"]),
        }
    return kv.at[rows, pos].set(new)


def kv_write_rows_seq(
    kv, new: jax.Array, rows: jax.Array, pos: jax.Array
) -> PyTree:
    """Scatter a ``(B, K, KV, HD)`` chunk per row starting at per-row
    positions (batched speculative verify: every row scores K
    positions from its own cache frontier)."""
    K = new.shape[1]
    idx = pos[:, None] + jnp.arange(K)[None, :]  # (B, K)
    if isinstance(kv, dict):
        qs = quantize_kv(new)
        return {
            "q": kv["q"].at[rows[:, None], idx].set(qs["q"]),
            "s": kv["s"].at[rows[:, None], idx].set(qs["s"]),
        }
    return kv.at[rows[:, None], idx].set(new)


def init_kv(shape: tuple[int, ...], dtype, kv_dtype: str) -> PyTree:
    """One cache side (k or v) of logical shape ``(..., S, KV, HD)``."""
    if validate_kv_dtype(kv_dtype) == "int8":
        return {
            "q": jnp.zeros(shape, jnp.int8),
            "s": jnp.zeros(shape[:-1], jnp.float32),
        }
    return jnp.zeros(shape, dtype)


def kv_bytes(shape: tuple[int, ...], dtype, kv_dtype: str) -> int:
    """HBM bytes for one cache side — the capacity arithmetic behind
    the int8 claim (2 bytes/elt -> 1 + 4/HD for scales)."""
    import math

    n = math.prod(shape)
    if kv_dtype == "int8":
        return n + 4 * (n // shape[-1])
    return n * jnp.dtype(dtype).itemsize


def kv_map(fn, kv):
    """Apply an array->array fn to each buffer of either representation
    (clone, repeat-along-batch, device_put...)."""
    if isinstance(kv, dict):
        return {"q": fn(kv["q"]), "s": fn(kv["s"])}
    return fn(kv)
