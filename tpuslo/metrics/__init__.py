from tpuslo.metrics.registry import AgentMetrics, start_metrics_server
from tpuslo.schema.fastpath import VALIDATION_COUNTERS, ValidationCounters

__all__ = [
    "AgentMetrics",
    "start_metrics_server",
    "VALIDATION_COUNTERS",
    "ValidationCounters",
]
