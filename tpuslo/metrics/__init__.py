from tpuslo.metrics.registry import (
    AgentMetrics,
    Readiness,
    start_metrics_server,
)
from tpuslo.metrics.rejections import REJECTION_COUNTERS, RejectionCounters
from tpuslo.schema.fastpath import VALIDATION_COUNTERS, ValidationCounters

__all__ = [
    "AgentMetrics",
    "Readiness",
    "start_metrics_server",
    "REJECTION_COUNTERS",
    "RejectionCounters",
    "VALIDATION_COUNTERS",
    "ValidationCounters",
]
