from tpuslo.metrics.registry import AgentMetrics, start_metrics_server

__all__ = ["AgentMetrics", "start_metrics_server"]
