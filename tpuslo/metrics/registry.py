"""Agent Prometheus metrics + /metrics //healthz //readyz server.

Reference: ``cmd/agent/main.go:154-249`` — heartbeat, up,
cpu_overhead_pct, event-kind / capability / signal-enabled one-hot
gauges, dropped-by-reason counter, DNS latency histogram, probe-event
counter.  The TPU-native build adds a TPU-signal counter and an
hbm-utilization gauge so dashboards can chart device pressure directly
from the agent.
"""

from __future__ import annotations

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)
from prometheus_client.exposition import CONTENT_TYPE_LATEST

from tpuslo.signals import ALL_SIGNALS, TPU_SIGNALS


class AgentMetrics:
    """Registry of the node agent's operational series."""

    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        self.heartbeat = Gauge(
            "llm_slo_agent_heartbeat_timestamp_seconds",
            "Unix time of the agent's last emit cycle",
            registry=self.registry,
        )
        self.up = Gauge(
            "llm_slo_agent_up", "1 while the agent loop is running",
            registry=self.registry,
        )
        self.cpu_overhead_pct = Gauge(
            "llm_slo_agent_cpu_overhead_pct",
            "Agent self-measured CPU overhead percent",
            registry=self.registry,
        )
        self.event_kind = Gauge(
            "llm_slo_agent_event_kind",
            "One-hot event kind selector",
            ["kind"],
            registry=self.registry,
        )
        self.capability_mode = Gauge(
            "llm_slo_agent_capability_mode",
            "One-hot capability mode",
            ["mode"],
            registry=self.registry,
        )
        self.signal_enabled = Gauge(
            "llm_slo_agent_signal_enabled",
            "1 when a signal probe is enabled",
            ["signal"],
            registry=self.registry,
        )
        self.dropped = Counter(
            "llm_slo_agent_events_dropped_total",
            "Events dropped by reason",
            ["reason"],
            registry=self.registry,
        )
        self.slo_events = Counter(
            "llm_slo_agent_slo_events_total",
            "SLO events emitted",
            registry=self.registry,
        )
        self.probe_events = Counter(
            "llm_slo_agent_probe_events_total",
            "Probe events emitted",
            ["signal"],
            registry=self.registry,
        )
        self.dns_latency_ms = Histogram(
            "llm_slo_agent_dns_latency_ms",
            "Observed DNS latency signal values",
            buckets=(5, 10, 25, 50, 100, 200, 400, 800),
            registry=self.registry,
        )
        self.hbm_utilization_pct = Gauge(
            "llm_tpu_agent_hbm_utilization_pct",
            "Latest observed HBM utilization percent",
            registry=self.registry,
        )
        self.ici_collective_ms = Histogram(
            "llm_tpu_agent_ici_collective_ms",
            "Observed ICI collective latency signal values "
            "(passive uprobe or active icibench prober)",
            buckets=(0.5, 1, 2.5, 5, 10, 20, 40, 80),
            registry=self.registry,
        )
        self.tpu_events = Counter(
            "llm_tpu_agent_probe_events_total",
            "TPU-side probe events emitted",
            registry=self.registry,
        )
        self.webhook_sent = Counter(
            "llm_slo_agent_webhook_deliveries_total",
            "Webhook deliveries by outcome",
            ["outcome"],
            registry=self.registry,
        )
        # ---- resilient-delivery series (tpuslo.delivery) -------------
        self.delivery_queue_depth = Gauge(
            "llm_slo_agent_delivery_queue_depth",
            "Batches queued in memory for a sink (incl. in-flight)",
            ["sink"],
            registry=self.registry,
        )
        self.delivery_spool_bytes = Gauge(
            "llm_slo_agent_delivery_spool_bytes",
            "Bytes spooled to disk awaiting replay, per sink",
            ["sink"],
            registry=self.registry,
        )
        self.delivery_breaker_state = Gauge(
            "llm_slo_agent_delivery_breaker_state",
            "Circuit-breaker state per sink (0=closed 1=half-open 2=open)",
            ["sink"],
            registry=self.registry,
        )
        self.delivery_breaker_transitions = Counter(
            "llm_slo_agent_delivery_breaker_transitions_total",
            "Circuit-breaker state transitions per sink, by entered state",
            ["sink", "state"],
            registry=self.registry,
        )
        self.delivery_delivered = Counter(
            "llm_slo_agent_delivery_delivered_events_total",
            "Events delivered to a sink (live + replayed)",
            ["sink"],
            registry=self.registry,
        )
        self.delivery_retries = Counter(
            "llm_slo_agent_delivery_retries_total",
            "Sink send retries",
            ["sink"],
            registry=self.registry,
        )
        self.delivery_spooled = Counter(
            "llm_slo_agent_delivery_spooled_events_total",
            "Events written to the disk spool (not drops: replay pending)",
            ["sink"],
            registry=self.registry,
        )
        self.delivery_replayed = Counter(
            "llm_slo_agent_delivery_replayed_events_total",
            "Spooled events successfully replayed to a sink",
            ["sink"],
            registry=self.registry,
        )
        self.delivery_dead_letters = Counter(
            "llm_slo_agent_delivery_dead_letter_events_total",
            "Events written to the dead-letter file, by reason class",
            ["sink", "reason"],
            registry=self.registry,
        )
        self.delivery_truncated = Counter(
            "llm_slo_agent_delivery_spool_truncated_batches_total",
            "Spooled batches evicted by the size/age caps (lost evidence)",
            ["sink"],
            registry=self.registry,
        )
        self.signals_restored = Counter(
            "llm_slo_agent_signals_restored_total",
            "Shed probe signals re-enabled after sustained under-budget "
            "guard cycles",
            ["signal"],
            registry=self.registry,
        )
        # ---- ingest-gate series (tpuslo.ingest) ----------------------
        self.ingest_admitted = Counter(
            "llm_slo_agent_ingest_admitted_events_total",
            "Events admitted through the telemetry gate in order",
            registry=self.registry,
        )
        self.ingest_duplicates = Counter(
            "llm_slo_agent_ingest_duplicate_events_total",
            "Events suppressed by the gate's dedup window",
            registry=self.registry,
        )
        self.ingest_quarantined = Counter(
            "llm_slo_agent_ingest_quarantined_events_total",
            "Malformed events quarantined by the gate, by reason class",
            ["reason"],
            registry=self.registry,
        )
        self.ingest_late_admitted = Counter(
            "llm_slo_agent_ingest_late_admitted_events_total",
            "Events admitted behind the watermark (low-confidence path)",
            registry=self.registry,
        )
        self.ingest_clock_skew_ms = Gauge(
            "llm_slo_agent_ingest_clock_skew_ms",
            "Estimated per-node clock offset vs the coordinator host",
            ["node"],
            registry=self.registry,
        )
        self.ingest_watermark_lag_ms = Gauge(
            "llm_slo_agent_ingest_watermark_lag_ms",
            "Lag of the most recent event behind the stream head",
            registry=self.registry,
        )
        # ---- crash-safe runtime series (tpuslo.runtime) --------------
        self.runtime_snapshot_age_seconds = Gauge(
            "llm_slo_agent_runtime_snapshot_age_seconds",
            "Seconds since the last durable state snapshot was written",
            registry=self.registry,
        )
        self.runtime_snapshot_bytes = Gauge(
            "llm_slo_agent_runtime_snapshot_bytes",
            "Size of the last durable state snapshot",
            registry=self.registry,
        )
        self.runtime_snapshot_saves = Counter(
            "llm_slo_agent_runtime_snapshot_saves_total",
            "Durable state snapshot writes, by outcome",
            ["outcome"],
            registry=self.registry,
        )
        self.runtime_snapshot_restores = Counter(
            "llm_slo_agent_runtime_snapshot_restores_total",
            "Startup snapshot restore attempts, by outcome "
            "(restored/cold/stale/corrupt/version/forced_cold)",
            ["outcome"],
            registry=self.registry,
        )
        self.runtime_probe_restarts = Counter(
            "llm_slo_agent_runtime_probe_restarts_total",
            "Dead probes restarted by the supervisor",
            ["signal"],
            registry=self.registry,
        )
        self.runtime_flap_sheds = Counter(
            "llm_slo_agent_runtime_flap_sheds_total",
            "Signals shed by the supervisor for restart flapping",
            ["signal"],
            registry=self.registry,
        )
        self.runtime_drains = Counter(
            "llm_slo_agent_runtime_drains_total",
            "Graceful drain sequences, by outcome "
            "(clean/deadline_exceeded/step_error)",
            ["outcome"],
            registry=self.registry,
        )
        self.runtime_drain_duration_seconds = Gauge(
            "llm_slo_agent_runtime_drain_duration_seconds",
            "Wall time of the last graceful drain sequence",
            registry=self.registry,
        )
        # ---- error-budget / burn-rate series (tpuslo.sloengine) ------
        self.slo_request_outcomes = Counter(
            "llm_slo_agent_slo_request_outcomes_total",
            "Request outcomes folded into the burn engine's SLI stream",
            ["tenant", "status"],
            registry=self.registry,
        )
        self.slo_budget_remaining = Gauge(
            "llm_slo_agent_slo_budget_remaining",
            "Fraction of the error budget left over the budget window, "
            "per tenant and objective (availability/ttft/tpot)",
            ["tenant", "objective"],
            registry=self.registry,
        )
        self.slo_burn_rate = Gauge(
            "llm_slo_agent_slo_burn_rate",
            "Error-budget burn rate per sliding window "
            "(1.0 = spending exactly the whole budget over the window)",
            ["tenant", "objective", "window"],
            registry=self.registry,
        )
        self.slo_alert_state = Gauge(
            "llm_slo_agent_slo_alert_state",
            "Burn alert state per tenant/objective "
            "(0=ok 1=slow_burn 2=fast_burn)",
            ["tenant", "objective"],
            registry=self.registry,
        )
        self.slo_alert_transitions = Counter(
            "llm_slo_agent_slo_alert_transitions_total",
            "Burn alert state transitions by severity "
            "(page/ticket/resolve) — one per sustained burn, not one "
            "per evaluation cycle",
            ["tenant", "objective", "severity"],
            registry=self.registry,
        )
        # ---- fleet observability plane (tpuslo.fleet) ----------------
        self.fleet_ingested_events = Counter(
            "llm_slo_fleet_ingested_events_total",
            "Columnar probe events ingested by an aggregator shard "
            "(decode -> merge -> gate path), per shard",
            ["shard"],
            registry=self.registry,
        )
        self.fleet_rollup_latency_ms = Histogram(
            "llm_slo_fleet_rollup_latency_ms",
            "Latency of one fleet rollup pass (window close + "
            "attribution + cross-node collapse)",
            buckets=(1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500),
            registry=self.registry,
        )
        self.fleet_incidents_open = Gauge(
            "llm_slo_fleet_incidents_open",
            "Fleet incidents currently open, by blast radius "
            "(pod/node/slice/fleet)",
            ["blast_radius"],
            registry=self.registry,
        )
        self.fleet_nodes_reporting = Gauge(
            "llm_slo_fleet_nodes_reporting",
            "Nodes whose stream head is within the staleness bound "
            "of the fleet head",
            registry=self.registry,
        )
        self.fleet_nodes_stale = Gauge(
            "llm_slo_fleet_nodes_stale",
            "Nodes aged out of the watermark min (stopped shipping)",
            registry=self.registry,
        )
        self.fleet_ring_rebalances = Counter(
            "llm_slo_fleet_ring_rebalances_total",
            "Hash-ring membership changes (shard added or removed; "
            "each re-homes only the changed shard's arcs)",
            registry=self.registry,
        )
        # ---- federation plane (tpuslo.federation) --------------------
        self.federation_region_ingested = Counter(
            "llm_slo_fleet_federation_region_ingested_incidents_total",
            "Node incidents ingested by the region aggregator, per "
            "source cluster (the cluster->region envelope hop)",
            ["cluster"],
            registry=self.registry,
        )
        self.federation_backpressure_level = Gauge(
            "llm_slo_fleet_federation_backpressure_level",
            "Current ingest-degradation level per aggregator "
            "(0 none, 1 coarse batches, 2 sample low-severity, "
            "3 aggressive sampling)",
            ["source"],
            registry=self.registry,
        )
        self.federation_sampled_rows = Counter(
            "llm_slo_fleet_federation_sampled_rows_total",
            "Low-severity rows sampled out under backpressure, by "
            "the degradation level that dropped them (gated fault "
            "evidence is structurally never sampled)",
            ["level"],
            registry=self.registry,
        )
        self.federation_churn_rebalances = Counter(
            "llm_slo_fleet_federation_churn_rebalances_total",
            "Online ring rebalances under churn, by kind "
            "(shard_join/shard_leave); each re-homes only the moved "
            "arcs with in-flight window handoff",
            ["kind"],
            registry=self.registry,
        )
        self.federation_incident_staleness_ms = Histogram(
            "llm_slo_fleet_federation_incident_staleness_ms",
            "How far the region head had advanced past an emitted "
            "incident's window end — the resolution cost of "
            "saturation-induced coarsening/sampling",
            buckets=(
                100, 250, 500, 1000, 2500, 5000, 10000, 20000,
                30000, 60000,
            ),
            registry=self.registry,
        )
        # ---- global tier (tpuslo.federation.global_tier) -------------
        self.global_region_ingested = Counter(
            "llm_slo_global_region_ingested_incidents_total",
            "Fleet pages ingested by the global tier, per source "
            "region (the region->global envelope hop)",
            ["region"],
            registry=self.registry,
        )
        self.global_pages = Counter(
            "llm_slo_global_pages_total",
            "Global incidents emitted, by scope (single_region / "
            "multi_region / partition_scoped — the last means some "
            "region was unreachable and a peer may hold the rest)",
            ["scope"],
            registry=self.registry,
        )
        self.global_duplicates_suppressed = Counter(
            "llm_slo_global_duplicates_suppressed_total",
            "Duplicates the global tier absorbed, by reason "
            "(seq_replay: WAN replay of an already-accepted "
            "envelope; emitted_window: a healed peer already paged "
            "this session window)",
            ["reason"],
            registry=self.registry,
        )
        self.global_region_reachable = Gauge(
            "llm_slo_global_region_reachable",
            "1 while the region's stream head is within the "
            "staleness bound of the fleet head, 0 once it has aged "
            "out (partitioned or dark)",
            ["region"],
            registry=self.registry,
        )
        # ---- global peer mesh (symmetric root, PR 19) -----------------
        self.global_peer_epoch = Gauge(
            "llm_slo_global_peer_epoch",
            "This peer's election epoch — the fence every emitted "
            "page carries; a deposed root's pages at a lower epoch "
            "are rejected mesh-wide",
            ["peer"],
            registry=self.registry,
        )
        self.global_peer_elections = Counter(
            "llm_slo_global_peer_elections_total",
            "Leadership takes by this peer (bully by stable rank "
            "over gossiped liveness; each take bumps the epoch past "
            "everything seen)",
            ["peer"],
            registry=self.registry,
        )
        self.global_peer_gossip_rounds = Counter(
            "llm_slo_global_peer_gossip_rounds_total",
            "Anti-entropy gossip rounds this peer initiated (one per "
            "round, not per remote peer)",
            ["peer"],
            registry=self.registry,
        )
        self.global_peer_reachable = Gauge(
            "llm_slo_global_peer_reachable",
            "1 while the remote mesh peer was heard (directly or "
            "transitively) within the peer staleness bound, 0 once "
            "it has aged out — the liveness the bully rule elects on",
            ["peer"],
            registry=self.registry,
        )
        # ---- auto-remediation series (tpuslo.remediation) ------------
        self.remediation_actions_applied = Counter(
            "llm_slo_agent_remediation_actions_applied_total",
            "Remediation actions applied, by action kind "
            "(probe_shed/breaker_trip/drain_snapshot/cordon_node/"
            "rehome_slice/demote_tenant)",
            ["action"],
            registry=self.registry,
        )
        self.remediation_actions_rolled_back = Counter(
            "llm_slo_agent_remediation_actions_rolled_back_total",
            "Remediation actions rolled back (verify failed or apply "
            "was interrupted by a restart), by action kind",
            ["action"],
            registry=self.registry,
        )
        self.remediation_verify_outcomes = Counter(
            "llm_slo_agent_remediation_verify_outcomes_total",
            "Verify-or-rollback verdicts (confirmed/rollback)",
            ["outcome"],
            registry=self.registry,
        )
        self.remediation_actions_in_flight = Gauge(
            "llm_slo_agent_remediation_actions_in_flight",
            "Remediation actions currently applying or verifying "
            "(bounded by the global concurrent-actions budget)",
            registry=self.registry,
        )
        self.remediation_refusals = Counter(
            "llm_slo_agent_remediation_refusals_total",
            "Attributions the policy declined to act on, by reason "
            "(no_rule/low_confidence/not_burning/cooldown/"
            "rate_limited/budget/no_target/disabled) — the precision "
            "evidence",
            ["reason"],
            registry=self.registry,
        )
        # ---- self-observability series (tpuslo.obs) ------------------
        self.cycle_stage_ms = Histogram(
            "llm_slo_agent_cycle_stage_ms",
            "Per-stage latency of the agent's own pipeline cycle "
            "(generate/ingest_gate/validate/correlate/attribute/"
            "deliver/snapshot); exemplars carry the cycle trace_id",
            ["stage"],
            buckets=(0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250),
            registry=self.registry,
        )
        self.cycle_ms = Histogram(
            "llm_slo_agent_cycle_ms",
            "End-to-end latency of one agent emit cycle",
            buckets=(0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000),
            registry=self.registry,
        )
        self.trace_cycles = Counter(
            "llm_slo_agent_trace_cycles_total",
            "Self-traced cycles by tail-sampling verdict "
            "(kept_slow/kept_error/kept_probabilistic/dropped)",
            ["verdict"],
            registry=self.registry,
        )
        self.trace_spans_exported = Counter(
            "llm_slo_agent_trace_spans_exported_total",
            "Self-tracing spans handed to the export path",
            registry=self.registry,
        )
        self.trace_overhead_pct = Gauge(
            "llm_slo_agent_trace_overhead_pct",
            "Measured self-tracing overhead as percent of cycle time "
            "(EMA; the tracer degrades to metrics-only past its budget)",
            registry=self.registry,
        )
        # ---- device-plane ledger series (tpuslo.deviceplane) ----------
        self.deviceplane_device_time_ms = Counter(
            "llm_slo_deviceplane_device_time_ms_total",
            "Device time folded by the per-launch ledger, by bucket "
            "(joined/helper/compile/idle_gap/unexplained) — the five "
            "buckets sum to total observed device time",
            ["bucket"],
            registry=self.registry,
        )
        self.deviceplane_launches = Counter(
            "llm_slo_deviceplane_launches_total",
            "Module launches attributed by the ledger, by join tier "
            "(identity/lane_window/compile_event/frame)",
            ["tier"],
            registry=self.registry,
        )
        self.deviceplane_join_rate = Gauge(
            "llm_slo_deviceplane_join_rate",
            "Launch->signal join rate from the last ledger fold, by "
            "kind (raw = exact identity over ALL launches, reported "
            "only; substantive = tiered rate over ops-bearing "
            "launches, gated >= 0.9)",
            ["kind"],
            registry=self.registry,
        )
        self.deviceplane_unexplained_share = Gauge(
            "llm_slo_deviceplane_unexplained_share",
            "Share of device time the ledger could not attribute "
            "(gated <= 0.1 on the synthetic lane)",
            registry=self.registry,
        )
        self.deviceplane_dispatch_device_wait_ms = Histogram(
            "llm_slo_deviceplane_dispatch_device_wait_ms",
            "Per-dispatch device-busy proxy from the serving front "
            "door (fused-read wait time)",
            buckets=(0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500),
            registry=self.registry,
        )
        self.deviceplane_roofline_verdicts = Counter(
            "llm_slo_deviceplane_roofline_verdicts_total",
            "Roofline verdicts attached to serving-path attributions, "
            "by verdict (memory_bound/compute_bound)",
            ["verdict"],
            registry=self.registry,
        )
        # ---- continuous profiler (tpuslo.deviceplane.profiler) --------
        self.profiler_windows = Counter(
            "llm_slo_profiler_windows_total",
            "Profiler capture windows folded through the ledger, by "
            "kind (captured = every window; forced = taken mid-stride "
            "on an eviction notice; eviction = windows carrying at "
            "least one eviction event)",
            ["kind"],
            registry=self.registry,
        )
        self.profiler_capture_overhead_pct = Gauge(
            "llm_slo_profiler_capture_overhead_pct",
            "Measured capture+parse+fold cost as percent of the cycle "
            "budget, amortized over the stride (EMA; the governor "
            "degrades to a longer stride past its budget)",
            registry=self.registry,
        )
        self.profiler_governor_transitions = Counter(
            "llm_slo_profiler_governor_transitions_total",
            "Overhead-governor state changes, by transition (degraded "
            "= stride lengthened past the overhead budget; reengaged "
            "= base stride restored on sustained headroom)",
            ["transition"],
            registry=self.registry,
        )
        self.profiler_stride_cycles = Gauge(
            "llm_slo_profiler_stride_cycles",
            "Current capture stride in agent cycles (base when "
            "healthy, doubled per degradation up to the cap)",
            registry=self.registry,
        )
        self.profiler_idle_gap_ms = Histogram(
            "llm_slo_profiler_idle_gap_ms",
            "Per-window device idle gap from the profiler's ledger "
            "fold (preemptions surface here as outlier windows)",
            buckets=(0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000),
            registry=self.registry,
        )
        self.profiler_window_mfu_pct = Gauge(
            "llm_slo_profiler_window_mfu_pct",
            "Roofline MFU of the most recent capture window (-1 when "
            "the window carried no cost model)",
            registry=self.registry,
        )
        self.profiler_window_unexplained_share = Gauge(
            "llm_slo_profiler_window_unexplained_share",
            "Unexplained device-time share of the most recent capture "
            "window (same fold the device_unexplained_share probe "
            "signal is emitted from)",
            registry=self.registry,
        )
        # ---- serving front door (tpuslo.models.frontdoor) -------------
        # The engine's admission counters were internal-only (stats()
        # dicts); these export them live through the FrontDoorObserver
        # hooks so shed/preempt pressure shows up on the error-budget
        # board next to the burn it causes.
        self.frontdoor_admitted = Counter(
            "llm_slo_frontdoor_admitted_total",
            "Requests admitted into front-door decode slots, by "
            "engine and tenant",
            ["engine", "tenant"],
            registry=self.registry,
        )
        self.frontdoor_shed = Counter(
            "llm_slo_frontdoor_shed_total",
            "Requests refused by SLO-aware admission, by engine, "
            "tenant and reason "
            "(queue_full/displaced/queue_full_burning)",
            ["engine", "tenant", "reason"],
            registry=self.registry,
        )
        self.frontdoor_preemptions = Counter(
            "llm_slo_frontdoor_preemptions_total",
            "Running slots parked to make room for higher-priority "
            "work, by engine and tenant",
            ["engine", "tenant"],
            registry=self.registry,
        )
        self.frontdoor_resumes = Counter(
            "llm_slo_frontdoor_resumes_total",
            "Parked/teacher-forced streams resumed into a slot, by "
            "engine and tenant",
            ["engine", "tenant"],
            registry=self.registry,
        )
        self.frontdoor_completed_tokens = Counter(
            "llm_slo_frontdoor_completed_tokens_total",
            "Tokens emitted by completed front-door requests, by "
            "engine and tenant",
            ["engine", "tenant"],
            registry=self.registry,
        )

        # ---- live deployment plane (tpuslo.livenet) --------------------
        # The socket transport's health surface: a partition shows up
        # here first — connected_peers drops, reconnects and spool
        # replays climb on heal (docs/runbooks/live-deployment.md).
        self.livenet_connected_peers = Gauge(
            "llm_slo_livenet_connected_peers",
            "Open peer connections on a live listener, by listener",
            ["listener"],
            registry=self.registry,
        )
        self.livenet_reconnects = Counter(
            "llm_slo_livenet_reconnects_total",
            "Upstream socket reconnections by a sending client, "
            "by peer",
            ["peer"],
            registry=self.registry,
        )
        self.livenet_spool_replayed = Counter(
            "llm_slo_livenet_spool_replayed_frames_total",
            "Spooled frames redelivered upstream after an outage, "
            "by peer",
            ["peer"],
            registry=self.registry,
        )
        self.livenet_pressure_level = Gauge(
            "llm_slo_livenet_upstream_pressure_level",
            "Latest ack-carried upstream pressure level (0-3) seen "
            "by a sending client, by peer",
            ["peer"],
            registry=self.registry,
        )
        self.livenet_frames_rejected = Counter(
            "llm_slo_livenet_frames_rejected_total",
            "Inbound frames refused by a live listener, by listener "
            "and reason (framing/contract)",
            ["listener", "reason"],
            registry=self.registry,
        )

    def set_enabled_signals(self, enabled: list[str]) -> None:
        enabled_set = set(enabled)
        for signal in ALL_SIGNALS:
            self.signal_enabled.labels(signal=signal).set(
                1.0 if signal in enabled_set else 0.0
            )

    def observe_probe(self, signal: str, value: float) -> None:
        self.probe_events.labels(signal=signal).inc()
        if signal == "dns_latency_ms":
            self.dns_latency_ms.observe(value)
        if signal == "hbm_utilization_pct":
            self.hbm_utilization_pct.set(value)
        if signal == "ici_collective_latency_ms":
            self.ici_collective_ms.observe(value)
        if signal in TPU_SIGNALS:
            self.tpu_events.inc()

    def mark_cycle(self, duration_ms: float | None = None) -> None:
        """Heartbeat plus (when known) the cycle-duration observation —
        the stats line and dashboards read the same histogram, so the
        two can no longer drift apart."""
        self.heartbeat.set(time.time())
        if duration_ms is not None:
            self.cycle_ms.observe(duration_ms)

    def stage_quantiles(
        self, quantiles: tuple[float, ...] = (0.5, 0.99)
    ) -> dict[str, dict[str, float]]:
        """Per-stage latency quantiles estimated from the
        ``cycle_stage_ms`` histogram buckets (linear interpolation —
        the same estimate PromQL's histogram_quantile produces).

        Returns ``{stage: {"p50": ..., "p99": ..., "count": ...}}`` for
        stages with at least one observation.
        """
        # stage -> sorted [(le, cumulative_count)]
        buckets: dict[str, list[tuple[float, float]]] = {}
        for metric in self.cycle_stage_ms.collect():
            for sample in metric.samples:
                if not sample.name.endswith("_bucket"):
                    continue
                stage = sample.labels.get("stage", "")
                le = float(sample.labels.get("le", "inf").replace("+Inf", "inf"))
                buckets.setdefault(stage, []).append((le, sample.value))
        out: dict[str, dict[str, float]] = {}
        for stage, rows in buckets.items():
            rows.sort(key=lambda r: r[0])
            total = rows[-1][1] if rows else 0.0
            if total <= 0:
                continue
            est = {"count": total}
            for q in quantiles:
                rank = q * total
                lo_bound, lo_count = 0.0, 0.0
                value = rows[-1][0]
                for le, cum in rows:
                    if cum >= rank:
                        if le == float("inf"):
                            value = lo_bound
                        elif cum == lo_count:
                            value = le
                        else:
                            value = lo_bound + (le - lo_bound) * (
                                (rank - lo_count) / (cum - lo_count)
                            )
                        break
                    lo_bound, lo_count = le, cum
                est[f"p{int(q * 100)}"] = value
            out[stage] = est
        return out

    def delivery_observer(self, sink: str) -> "_PromDeliveryObserver":
        """Observer adapter wiring one DeliveryChannel to this registry
        (duck-typed against tpuslo.delivery.DeliveryObserver)."""
        return _PromDeliveryObserver(self, sink)

    def ingest_observer(self) -> "_PromIngestObserver":
        """Observer adapter wiring a TelemetryGate to this registry
        (duck-typed against tpuslo.ingest.GateObserver)."""
        return _PromIngestObserver(self)

    def runtime_observer(self) -> "_PromRuntimeObserver":
        """Observer adapter wiring the crash-safe runtime to this
        registry (duck-typed against tpuslo.runtime.RuntimeObserver)."""
        return _PromRuntimeObserver(self)

    def trace_observer(self) -> "_PromTraceObserver":
        """Observer adapter wiring a SelfTracer to this registry
        (duck-typed against tpuslo.obs.TraceObserver)."""
        return _PromTraceObserver(self)

    def slo_observer(self) -> "_PromSLOObserver":
        """Observer adapter wiring a BurnEngine to this registry
        (duck-typed against tpuslo.sloengine.SLOObserver)."""
        return _PromSLOObserver(self)

    def fleet_observer(self) -> "_PromFleetObserver":
        """Observer adapter wiring aggregator shards / the fleet
        simulator to this registry (duck-typed against
        tpuslo.fleet.FleetObserver)."""
        return _PromFleetObserver(self)

    def federation_observer(self) -> "_PromFederationObserver":
        """Observer adapter wiring the federation tree (region +
        cluster aggregators, backpressure loop, churn rebalancer) to
        this registry (duck-typed against
        tpuslo.federation.FederationObserver)."""
        return _PromFederationObserver(self)

    def global_observer(self) -> "_PromGlobalObserver":
        """Observer adapter wiring the global tier (gap-tolerant
        dedup, partition-aware emission) to this registry (duck-typed
        against tpuslo.federation.GlobalObserver)."""
        return _PromGlobalObserver(self)

    def remediation_observer(self) -> "_PromRemediationObserver":
        """Observer adapter wiring a RemediationEngine to this registry
        (duck-typed against tpuslo.remediation.RemediationObserver)."""
        return _PromRemediationObserver(self)

    def profiler_observer(self) -> "_PromProfilerObserver":
        """Observer for the continuous device profiler
        (``ContinuousProfiler(observer=...)``)."""
        return _PromProfilerObserver(self)

    def deviceplane_observer(self) -> "_PromDeviceplaneObserver":
        """Observer adapter wiring device-plane ledger folds, serving
        dispatches, and roofline attachments to this registry."""
        return _PromDeviceplaneObserver(self)

    def frontdoor_observer(self, engine: str = "0") -> "_PromFrontDoorObserver":
        """Observer adapter wiring ONE serving front door's admission
        lifecycle to this registry (duck-typed against
        tpuslo.models.frontdoor.FrontDoorObserver); ``engine`` labels
        the replica under an SLORouter fleet."""
        return _PromFrontDoorObserver(self, engine)

    def livenet_observer(self) -> "_PromLivenetObserver":
        """Observer adapter wiring live listeners and reconnecting
        clients to this registry (duck-typed against
        tpuslo.livenet.LivenetObserver)."""
        return _PromLivenetObserver(self)


_BREAKER_STATE_VALUES = {"closed": 0, "half_open": 1, "open": 2}


class _PromDeliveryObserver:
    """Per-sink bridge from delivery-channel callbacks to Prometheus."""

    def __init__(self, metrics: AgentMetrics, sink: str):
        self._m = metrics
        self._sink = sink
        # Touch the per-sink series so dashboards see explicit zeros.
        metrics.delivery_queue_depth.labels(sink=sink).set(0)
        metrics.delivery_spool_bytes.labels(sink=sink).set(0)
        metrics.delivery_breaker_state.labels(sink=sink).set(0)

    def queue_depth(self, depth: int) -> None:
        self._m.delivery_queue_depth.labels(sink=self._sink).set(depth)

    def spool_bytes(self, n: int) -> None:
        self._m.delivery_spool_bytes.labels(sink=self._sink).set(n)

    def breaker_state(self, state: str) -> None:
        self._m.delivery_breaker_state.labels(sink=self._sink).set(
            _BREAKER_STATE_VALUES.get(state, 2)
        )
        self._m.delivery_breaker_transitions.labels(
            sink=self._sink, state=state
        ).inc()

    def delivered(self, kind: str, events: int) -> None:
        self._m.delivery_delivered.labels(sink=self._sink).inc(events)

    def retried(self, events: int) -> None:
        self._m.delivery_retries.labels(sink=self._sink).inc()

    def spooled(self, kind: str, events: int) -> None:
        self._m.delivery_spooled.labels(sink=self._sink).inc(events)

    def replayed(self, events: int) -> None:
        self._m.delivery_replayed.labels(sink=self._sink).inc(events)

    def dead_lettered(self, kind: str, events: int, reason: str) -> None:
        self._m.delivery_dead_letters.labels(
            sink=self._sink, reason=reason
        ).inc(events)

    def truncated(self, batches: int) -> None:
        self._m.delivery_truncated.labels(sink=self._sink).inc(batches)


class _PromIngestObserver:
    """Bridge from telemetry-gate callbacks to Prometheus."""

    def __init__(self, metrics: AgentMetrics):
        self._m = metrics
        # Touch the scalar series so dashboards see explicit zeros.
        metrics.ingest_watermark_lag_ms.set(0)

    def admitted(self) -> None:
        self._m.ingest_admitted.inc()

    def duplicate(self) -> None:
        self._m.ingest_duplicates.inc()

    def quarantined(self, reason: str) -> None:
        self._m.ingest_quarantined.labels(reason=reason).inc()

    def late(self, lag_ns: int) -> None:
        self._m.ingest_late_admitted.inc()

    def skew_offsets(self, offsets_ms: dict[str, float]) -> None:
        for node, offset_ms in offsets_ms.items():
            self._m.ingest_clock_skew_ms.labels(node=node).set(offset_ms)

    def watermark_lag_ms(self, lag_ms: float) -> None:
        self._m.ingest_watermark_lag_ms.set(lag_ms)


class _PromRuntimeObserver:
    """Bridge from crash-safe runtime callbacks to Prometheus."""

    def __init__(self, metrics: AgentMetrics):
        self._m = metrics
        metrics.runtime_snapshot_age_seconds.set(0)
        metrics.runtime_snapshot_bytes.set(0)

    def snapshot_saved(self, size_bytes: int) -> None:
        self._m.runtime_snapshot_saves.labels(outcome="ok").inc()
        self._m.runtime_snapshot_bytes.set(size_bytes)
        self._m.runtime_snapshot_age_seconds.set(0)

    def snapshot_save_failed(self) -> None:
        self._m.runtime_snapshot_saves.labels(outcome="error").inc()

    def snapshot_restored(self, outcome: str, age_s: float) -> None:
        self._m.runtime_snapshot_restores.labels(outcome=outcome).inc()

    def probe_restarted(self, signal: str) -> None:
        self._m.runtime_probe_restarts.labels(signal=signal).inc()

    def flap_shed(self, signal: str) -> None:
        self._m.runtime_flap_sheds.labels(signal=signal).inc()

    def drain(self, outcome: str, duration_s: float) -> None:
        self._m.runtime_drains.labels(outcome=outcome).inc()
        self._m.runtime_drain_duration_seconds.set(duration_s)


class _PromFleetObserver:
    """Bridge from fleet-plane callbacks to Prometheus.

    Per-shard counter children are cached: the aggregator calls
    ``ingested`` once per merged drain (tens of thousands of events),
    so a ``labels()`` dict lookup per call would be pure waste.
    """

    def __init__(self, metrics: AgentMetrics):
        self._m = metrics
        self._ingest_children: dict[str, object] = {}
        metrics.fleet_nodes_reporting.set(0)
        metrics.fleet_nodes_stale.set(0)
        for radius in ("pod", "node", "slice", "fleet"):
            metrics.fleet_incidents_open.labels(blast_radius=radius).set(0)

    def ingested(self, shard: str, events: int) -> None:
        child = self._ingest_children.get(shard)
        if child is None:
            child = self._m.fleet_ingested_events.labels(shard=shard)
            self._ingest_children[shard] = child
        child.inc(events)

    def rollup_latency_ms(self, ms: float) -> None:
        self._m.fleet_rollup_latency_ms.observe(ms)

    def incidents_open(self, blast_radius: str, count: int) -> None:
        self._m.fleet_incidents_open.labels(
            blast_radius=blast_radius
        ).set(count)

    def nodes(self, reporting: int, stale: int) -> None:
        self._m.fleet_nodes_reporting.set(reporting)
        self._m.fleet_nodes_stale.set(stale)

    def rebalance(self) -> None:
        self._m.fleet_ring_rebalances.inc()


class _PromFederationObserver:
    """Bridge from federation-tree callbacks to Prometheus.

    Per-cluster counter children are cached like the fleet observer's:
    region ingest fires once per envelope, sampling once per degraded
    batch — a ``labels()`` dict lookup per call is avoidable waste.
    """

    def __init__(self, metrics: AgentMetrics):
        self._m = metrics
        self._ingest_children: dict[str, object] = {}
        self._sampled_children: dict[int, object] = {}

    def region_ingested(self, cluster: str, incidents: int) -> None:
        child = self._ingest_children.get(cluster)
        if child is None:
            child = self._m.federation_region_ingested.labels(
                cluster=cluster
            )
            self._ingest_children[cluster] = child
        child.inc(incidents)

    def backpressure_level(self, source: str, level: int) -> None:
        self._m.federation_backpressure_level.labels(
            source=source
        ).set(level)

    def sampled_rows(self, level: int, rows: int) -> None:
        child = self._sampled_children.get(level)
        if child is None:
            child = self._m.federation_sampled_rows.labels(
                level=str(level)
            )
            self._sampled_children[level] = child
        child.inc(rows)

    def churn_rebalance(self, kind: str, moved: int) -> None:
        self._m.federation_churn_rebalances.labels(kind=kind).inc()

    def incident_staleness_ms(self, ms: float) -> None:
        self._m.federation_incident_staleness_ms.observe(ms)


class _PromGlobalObserver:
    """Bridge from global-tier callbacks to Prometheus.

    Per-region children are cached like the federation observer's;
    ``region_reachable`` fires for every region on every watermark
    read, so the gauge child lookup is the hot one.
    """

    def __init__(self, metrics: AgentMetrics):
        self._m = metrics
        self._ingest_children: dict[str, object] = {}
        self._reachable_children: dict[str, object] = {}
        self._peer_reach_children: dict[str, object] = {}

    def global_ingested(self, region: str, incidents: int) -> None:
        child = self._ingest_children.get(region)
        if child is None:
            child = self._m.global_region_ingested.labels(
                region=region
            )
            self._ingest_children[region] = child
        child.inc(incidents)

    def global_page(self, scope: str) -> None:
        self._m.global_pages.labels(scope=scope).inc()

    def global_duplicate(self, reason: str) -> None:
        self._m.global_duplicates_suppressed.labels(
            reason=reason
        ).inc()

    def region_reachable(self, region: str, reachable: int) -> None:
        child = self._reachable_children.get(region)
        if child is None:
            child = self._m.global_region_reachable.labels(
                region=region
            )
            self._reachable_children[region] = child
        child.set(reachable)

    # ---- peer mesh (symmetric root) --------------------------------

    def peer_epoch(self, peer: str, epoch: int) -> None:
        self._m.global_peer_epoch.labels(peer=peer).set(epoch)

    def peer_election(self, peer: str) -> None:
        self._m.global_peer_elections.labels(peer=peer).inc()

    def peer_gossip_round(self, peer: str) -> None:
        self._m.global_peer_gossip_rounds.labels(peer=peer).inc()

    def peer_reachable(self, peer: str, reachable: int) -> None:
        child = self._peer_reach_children.get(peer)
        if child is None:
            child = self._m.global_peer_reachable.labels(peer=peer)
            self._peer_reach_children[peer] = child
        child.set(reachable)


class _PromTraceObserver:
    """Bridge from self-tracer callbacks to Prometheus.

    One batched callback per cycle: histogram children are cached (a
    ``labels()`` lookup costs microseconds) and exemplars — which cost
    another few microseconds per observation — are attached only for
    cycles the tail sampler kept, i.e. exactly the ones whose trace_id
    actually resolves to an exported trace.  Dropped cycles still feed
    every histogram, so p50/p99 stay unbiased at any sample rate.
    """

    def __init__(self, metrics: AgentMetrics):
        self._m = metrics
        self._children: dict[str, object] = {}
        metrics.trace_overhead_pct.set(0)

    @staticmethod
    def _observe(histogram, ms: float, trace_id: str) -> None:
        try:
            histogram.observe(ms, exemplar={"trace_id": trace_id})
        except (TypeError, ValueError):
            # Exemplar-less prometheus_client, or an exemplar the
            # client rejects: the observation must still land.
            histogram.observe(ms)

    def _stage_child(self, stage: str):
        child = self._children.get(stage)
        if child is None:
            child = self._m.cycle_stage_ms.labels(stage=stage)
            self._children[stage] = child
        return child

    def cycle_complete(
        self, root, stage_spans, verdict: str, observe_stages: bool = True
    ) -> None:
        kept = verdict != "dropped"
        trace_id = root.trace_id
        if observe_stages:
            for span in stage_spans:
                child = self._stage_child(span.name)
                if kept:
                    self._observe(child, span.duration_ms, trace_id)
                else:
                    child.observe(span.duration_ms)
            if kept:
                self._observe(
                    self._m.cycle_ms, root.duration_ms, trace_id
                )
            else:
                self._m.cycle_ms.observe(root.duration_ms)
        counter = self._children.get(verdict)
        if counter is None:
            counter = self._m.trace_cycles.labels(verdict=verdict)
            self._children[verdict] = counter
        counter.inc()

    def spans_exported(self, count: int) -> None:
        # Fired by the tracer only when a batch actually reached the
        # export callback: a kept-but-exporterless cycle must not show
        # a healthy span-export rate on the dashboard.
        self._m.trace_spans_exported.inc(count)

    def overhead_pct(self, pct: float) -> None:
        self._m.trace_overhead_pct.set(pct)


class _PromSLOObserver:
    """Bridge from burn-engine callbacks to Prometheus.

    ``outcome`` runs once per request on the engine's record path, so
    its labelled child is cached — a ``labels()`` lookup per request
    is the kind of cost the TPL120 manifest exists to keep out.
    """

    def __init__(self, metrics: AgentMetrics):
        self._m = metrics
        self._outcome_children: dict[tuple[str, str], object] = {}

    def outcome(self, tenant: str, status: str) -> None:
        key = (tenant, status)
        child = self._outcome_children.get(key)
        if child is None:
            child = self._m.slo_request_outcomes.labels(
                tenant=tenant, status=status
            )
            self._outcome_children[key] = child
        child.inc()

    def burn_rate(
        self, tenant: str, objective: str, window: str, rate: float
    ) -> None:
        self._m.slo_burn_rate.labels(
            tenant=tenant, objective=objective, window=window
        ).set(rate)

    def budget_remaining(
        self, tenant: str, objective: str, remaining: float
    ) -> None:
        self._m.slo_budget_remaining.labels(
            tenant=tenant, objective=objective
        ).set(remaining)

    def alert_state(
        self, tenant: str, objective: str, level: int
    ) -> None:
        self._m.slo_alert_state.labels(
            tenant=tenant, objective=objective
        ).set(level)

    def transition(
        self, tenant: str, objective: str, severity: str
    ) -> None:
        self._m.slo_alert_transitions.labels(
            tenant=tenant, objective=objective, severity=severity
        ).inc()


class _PromRemediationObserver:
    """Bridge from remediation-engine callbacks to Prometheus."""

    def __init__(self, metrics: AgentMetrics):
        self._m = metrics

    def applied(self, action: str) -> None:
        self._m.remediation_actions_applied.labels(action=action).inc()

    def rolled_back(self, action: str) -> None:
        self._m.remediation_actions_rolled_back.labels(
            action=action
        ).inc()

    def verify_outcome(self, outcome: str) -> None:
        self._m.remediation_verify_outcomes.labels(
            outcome=outcome
        ).inc()

    def in_flight(self, count: int) -> None:
        self._m.remediation_actions_in_flight.set(count)

    def refused(self, reason: str) -> None:
        self._m.remediation_refusals.labels(reason=reason).inc()


class _PromDeviceplaneObserver:
    """Bridge from device-plane ledger folds to Prometheus."""

    def __init__(self, metrics: AgentMetrics):
        self._m = metrics

    def ledger_folded(self, ledger) -> None:
        """Publish one :class:`tpuslo.deviceplane.DeviceLedger` fold."""
        for bucket, us in ledger.buckets_us.items():
            self._m.deviceplane_device_time_ms.labels(bucket=bucket).inc(
                us / 1000.0
            )
        for tier, count in ledger.tier_counts.items():
            self._m.deviceplane_launches.labels(tier=tier).inc(count)
        self._m.deviceplane_join_rate.labels(kind="raw").set(
            ledger.raw_join_rate
        )
        self._m.deviceplane_join_rate.labels(kind="substantive").set(
            ledger.substantive_join_rate
        )
        self._m.deviceplane_unexplained_share.set(
            ledger.unexplained_share
        )

    def dispatch_observed(self, device_wait_ms: float) -> None:
        self._m.deviceplane_dispatch_device_wait_ms.observe(
            device_wait_ms
        )

    def roofline_attached(self, verdict: str) -> None:
        self._m.deviceplane_roofline_verdicts.labels(
            verdict=verdict
        ).inc()


class _PromProfilerObserver:
    """Bridge from continuous-profiler callbacks to Prometheus
    (the profiler observer contract: window/degraded/reengaged)."""

    def __init__(self, metrics: AgentMetrics):
        self._m = metrics

    def window(self, window, ema_pct: float) -> None:
        """Publish one :class:`ProfilerWindow` fold plus the
        governor's current overhead EMA."""
        self._m.profiler_windows.labels(kind="captured").inc()
        if window.forced:
            self._m.profiler_windows.labels(kind="forced").inc()
        if window.eviction_events > 0:
            self._m.profiler_windows.labels(kind="eviction").inc()
        self._m.profiler_capture_overhead_pct.set(ema_pct)
        self._m.profiler_stride_cycles.set(window.stride_cycles)
        self._m.profiler_idle_gap_ms.observe(window.idle_gap_ms)
        self._m.profiler_window_mfu_pct.set(window.mfu_pct)
        self._m.profiler_window_unexplained_share.set(
            window.unexplained_share
        )

    def degraded(self, stride: int) -> None:
        self._m.profiler_governor_transitions.labels(
            transition="degraded"
        ).inc()
        self._m.profiler_stride_cycles.set(stride)

    def reengaged(self, stride: int) -> None:
        self._m.profiler_governor_transitions.labels(
            transition="reengaged"
        ).inc()
        self._m.profiler_stride_cycles.set(stride)


class _PromFrontDoorObserver:
    """Per-engine bridge from front-door admission callbacks to
    Prometheus (the FrontDoorObserver contract: admitted/shed/
    preempted/resumed/completed)."""

    def __init__(self, metrics: AgentMetrics, engine: str):
        self._m = metrics
        self._engine = str(engine)

    def admitted(self, tenant: str) -> None:
        self._m.frontdoor_admitted.labels(
            engine=self._engine, tenant=tenant
        ).inc()

    def shed(self, tenant: str, reason: str) -> None:
        self._m.frontdoor_shed.labels(
            engine=self._engine, tenant=tenant, reason=reason
        ).inc()

    def preempted(self, tenant: str) -> None:
        self._m.frontdoor_preemptions.labels(
            engine=self._engine, tenant=tenant
        ).inc()

    def resumed(self, tenant: str) -> None:
        self._m.frontdoor_resumes.labels(
            engine=self._engine, tenant=tenant
        ).inc()

    def completed(self, tenant: str, tokens: int) -> None:
        self._m.frontdoor_completed_tokens.labels(
            engine=self._engine, tenant=tenant
        ).inc(tokens)


class _PromLivenetObserver:
    """Bridge from livenet listener/client callbacks to Prometheus
    (the LivenetObserver contract: peers/frame_rejected/reconnected/
    spool_replayed/pressure_level)."""

    def __init__(self, metrics: AgentMetrics):
        self._m = metrics

    def peers(self, listener: str, connected: int) -> None:
        self._m.livenet_connected_peers.labels(
            listener=listener
        ).set(connected)

    def frame_rejected(self, listener: str, reason: str) -> None:
        self._m.livenet_frames_rejected.labels(
            listener=listener, reason=reason
        ).inc()

    def reconnected(self, peer: str) -> None:
        self._m.livenet_reconnects.labels(peer=peer).inc()

    def spool_replayed(self, peer: str, frames: int) -> None:
        self._m.livenet_spool_replayed.labels(peer=peer).inc(frames)

    def pressure_level(self, peer: str, level: int) -> None:
        self._m.livenet_pressure_level.labels(peer=peer).set(level)


class Readiness:
    """Aggregated readiness for ``/readyz``: every registered check must
    pass, and failures explain themselves in the response body.

    Checks are callables returning ``(ok, detail)``; a check that
    raises counts as not-ready with the exception as the detail (a
    broken check must fail loud, not report ready).
    """

    def __init__(self):
        self._checks: list[tuple[str, object]] = []
        self._lock = threading.Lock()

    def add_check(self, name: str, fn) -> None:
        with self._lock:
            self._checks.append((name, fn))

    def evaluate(self) -> tuple[bool, str]:
        reasons = []
        with self._lock:
            checks = list(self._checks)
        for name, fn in checks:
            try:
                ok, detail = fn()
            except Exception as exc:  # noqa: BLE001 — see class docstring
                ok, detail = False, f"check raised {exc!r}"
            if not ok:
                reasons.append(f"{name}: {detail}")
        if reasons:
            return False, "; ".join(reasons)
        return True, "ok"


def start_metrics_server(
    metrics: AgentMetrics,
    port: int,
    host: str = "0.0.0.0",
    readiness: Readiness | None = None,
) -> ThreadingHTTPServer:
    """Serve /metrics, /healthz, /readyz on a daemon thread.

    ``/healthz`` is liveness: 200 while the process serves requests.
    ``/readyz`` is readiness: with a :class:`Readiness` wired in it
    returns 503 + the failing reasons (drain in progress, all breakers
    open, stale snapshot) instead of the unconditional 200 a load
    balancer would happily route traffic at.
    """

    registry = metrics.registry

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.startswith("/metrics"):
                body = generate_latest(registry)
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE_LATEST)
                self.end_headers()
                self.wfile.write(body)
            elif self.path == "/healthz":
                self._plain(200, "ok\n")
            elif self.path == "/readyz":
                if readiness is None:
                    self._plain(200, "ok\n")
                    return
                ready, reason = readiness.evaluate()
                self._plain(200 if ready else 503, reason + "\n")
            else:
                self.send_response(404)
                self.end_headers()

        def _plain(self, code: int, body: str) -> None:
            payload = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *args):
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server
