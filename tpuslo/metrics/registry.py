"""Agent Prometheus metrics + /metrics //healthz //readyz server.

Reference: ``cmd/agent/main.go:154-249`` — heartbeat, up,
cpu_overhead_pct, event-kind / capability / signal-enabled one-hot
gauges, dropped-by-reason counter, DNS latency histogram, probe-event
counter.  The TPU-native build adds a TPU-signal counter and an
hbm-utilization gauge so dashboards can chart device pressure directly
from the agent.
"""

from __future__ import annotations

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)
from prometheus_client.exposition import CONTENT_TYPE_LATEST

from tpuslo.signals import ALL_SIGNALS, TPU_SIGNALS


class AgentMetrics:
    """Registry of the node agent's operational series."""

    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        self.heartbeat = Gauge(
            "llm_slo_agent_heartbeat_timestamp_seconds",
            "Unix time of the agent's last emit cycle",
            registry=self.registry,
        )
        self.up = Gauge(
            "llm_slo_agent_up", "1 while the agent loop is running",
            registry=self.registry,
        )
        self.cpu_overhead_pct = Gauge(
            "llm_slo_agent_cpu_overhead_pct",
            "Agent self-measured CPU overhead percent",
            registry=self.registry,
        )
        self.event_kind = Gauge(
            "llm_slo_agent_event_kind",
            "One-hot event kind selector",
            ["kind"],
            registry=self.registry,
        )
        self.capability_mode = Gauge(
            "llm_slo_agent_capability_mode",
            "One-hot capability mode",
            ["mode"],
            registry=self.registry,
        )
        self.signal_enabled = Gauge(
            "llm_slo_agent_signal_enabled",
            "1 when a signal probe is enabled",
            ["signal"],
            registry=self.registry,
        )
        self.dropped = Counter(
            "llm_slo_agent_events_dropped_total",
            "Events dropped by reason",
            ["reason"],
            registry=self.registry,
        )
        self.slo_events = Counter(
            "llm_slo_agent_slo_events_total",
            "SLO events emitted",
            registry=self.registry,
        )
        self.probe_events = Counter(
            "llm_slo_agent_probe_events_total",
            "Probe events emitted",
            ["signal"],
            registry=self.registry,
        )
        self.dns_latency_ms = Histogram(
            "llm_slo_agent_dns_latency_ms",
            "Observed DNS latency signal values",
            buckets=(5, 10, 25, 50, 100, 200, 400, 800),
            registry=self.registry,
        )
        self.hbm_utilization_pct = Gauge(
            "llm_tpu_agent_hbm_utilization_pct",
            "Latest observed HBM utilization percent",
            registry=self.registry,
        )
        self.ici_collective_ms = Histogram(
            "llm_tpu_agent_ici_collective_ms",
            "Observed ICI collective latency signal values "
            "(passive uprobe or active icibench prober)",
            buckets=(0.5, 1, 2.5, 5, 10, 20, 40, 80),
            registry=self.registry,
        )
        self.tpu_events = Counter(
            "llm_tpu_agent_probe_events_total",
            "TPU-side probe events emitted",
            registry=self.registry,
        )
        self.webhook_sent = Counter(
            "llm_slo_agent_webhook_deliveries_total",
            "Webhook deliveries by outcome",
            ["outcome"],
            registry=self.registry,
        )

    def set_enabled_signals(self, enabled: list[str]) -> None:
        enabled_set = set(enabled)
        for signal in ALL_SIGNALS:
            self.signal_enabled.labels(signal=signal).set(
                1.0 if signal in enabled_set else 0.0
            )

    def observe_probe(self, signal: str, value: float) -> None:
        self.probe_events.labels(signal=signal).inc()
        if signal == "dns_latency_ms":
            self.dns_latency_ms.observe(value)
        if signal == "hbm_utilization_pct":
            self.hbm_utilization_pct.set(value)
        if signal == "ici_collective_latency_ms":
            self.ici_collective_ms.observe(value)
        if signal in TPU_SIGNALS:
            self.tpu_events.inc()

    def mark_cycle(self) -> None:
        self.heartbeat.set(time.time())


def start_metrics_server(
    metrics: AgentMetrics, port: int, host: str = "0.0.0.0"
) -> ThreadingHTTPServer:
    """Serve /metrics, /healthz, /readyz on a daemon thread."""

    registry = metrics.registry

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.startswith("/metrics"):
                body = generate_latest(registry)
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE_LATEST)
                self.end_headers()
                self.wfile.write(body)
            elif self.path in ("/healthz", "/readyz"):
                self.send_response(200)
                self.end_headers()
                self.wfile.write(b"ok\n")
            else:
                self.send_response(404)
                self.end_headers()

        def log_message(self, *args):
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server
