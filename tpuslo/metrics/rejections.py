"""Process-wide reason-classed rejection counters.

Every stage that discards or refuses an input event used to do so
silently (``matcher._ts`` swallowing unparseable timestamps,
``SliceJoiner.add`` returning ``False`` on missing fields).  A silent
drop on the telemetry plane is indistinguishable from a healthy quiet
stream — exactly the failure mode that turns a clock-skewed or corrupt
DaemonSet feed into confident mis-attribution.  These counters make
every rejection observable without coupling the correlation layer to
Prometheus: plain ints guarded only by the GIL (same contract as
:class:`tpuslo.schema.fastpath.ValidationCounters` — a lost increment
under contention is acceptable for diagnostics, a lock on the hot path
is not).

The agent surfaces a snapshot in its periodic stats line; ``slicecorr``
folds the joiner's share into its summary JSON.
"""

from __future__ import annotations


class RejectionCounters:
    """Tallies of rejected inputs keyed by ``(stage, reason)``."""

    def __init__(self) -> None:
        self._counts: dict[tuple[str, str], int] = {}

    def note(self, stage: str, reason: str, n: int = 1) -> None:
        key = (stage, reason)
        self._counts[key] = self._counts.get(key, 0) + n

    def total(self, stage: str | None = None) -> int:
        return sum(
            count
            for (s, _), count in self._counts.items()
            if stage is None or s == stage
        )

    def snapshot(self, stage: str | None = None) -> dict[str, int]:
        """``{"stage.reason": count}`` map, optionally stage-filtered."""
        return {
            f"{s}.{reason}": count
            for (s, reason), count in sorted(self._counts.items())
            if stage is None or s == stage
        }

    def reset(self) -> None:
        self._counts.clear()


REJECTION_COUNTERS = RejectionCounters()
