"""L3 safety governor: overhead guard + rate limiter + shed recovery."""

from tpuslo.safety.overhead_guard import (
    CPUSample,
    CPUSampler,
    OverheadGuard,
    OverheadResult,
    ProcCPUSampler,
)
from tpuslo.safety.rate_limiter import RateLimiter
from tpuslo.safety.recovery import (
    OWNER_GUARD,
    OWNER_REMEDIATION,
    ShedOwnership,
    ShedRecoveryPolicy,
)

__all__ = [
    "CPUSample",
    "CPUSampler",
    "OverheadGuard",
    "OverheadResult",
    "OWNER_GUARD",
    "OWNER_REMEDIATION",
    "ProcCPUSampler",
    "RateLimiter",
    "ShedOwnership",
    "ShedRecoveryPolicy",
]
