"""L3 safety governor: overhead guard + rate limiter."""

from tpuslo.safety.overhead_guard import (
    CPUSample,
    CPUSampler,
    OverheadGuard,
    OverheadResult,
    ProcCPUSampler,
)
from tpuslo.safety.rate_limiter import RateLimiter

__all__ = [
    "CPUSample",
    "CPUSampler",
    "OverheadGuard",
    "OverheadResult",
    "ProcCPUSampler",
    "RateLimiter",
]
