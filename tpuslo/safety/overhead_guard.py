"""Self-protection: CPU overhead governor.

Reference: ``pkg/safety/overhead_guard.go:19-158`` — delta-ticks CPU
percentage ``(Δproc / Δtotal) · 100 · num_cpus`` compared against a
budget; a pluggable sampler seam keeps it unit-testable without /proc.
The agent sheds probes in cost order while the guard reports breaches.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Protocol


@dataclass
class CPUSample:
    """One (process ticks, total ticks) observation."""

    proc_ticks: float
    total_ticks: float


class CPUSampler(Protocol):
    def sample(self) -> CPUSample: ...


class ProcCPUSampler:
    """Reads process and machine tick counters from /proc."""

    def __init__(self, proc_root: str = "/proc", pid: int | None = None):
        self._proc_root = proc_root
        self._pid = pid if pid is not None else os.getpid()

    def sample(self) -> CPUSample:
        return CPUSample(
            proc_ticks=self._read_proc_ticks(),
            total_ticks=self._read_total_ticks(),
        )

    def _read_total_ticks(self) -> float:
        with open(os.path.join(self._proc_root, "stat"), encoding="utf-8") as f:
            first = f.readline()
        fields = first.split()
        if not fields or fields[0] != "cpu":
            raise ValueError("unexpected /proc/stat format")
        return float(sum(int(v) for v in fields[1:]))

    def _read_proc_ticks(self) -> float:
        path = os.path.join(self._proc_root, str(self._pid), "stat")
        with open(path, encoding="utf-8") as f:
            content = f.read()
        # utime and stime are fields 14 and 15 (1-indexed) after the
        # parenthesised comm, which may itself contain spaces.
        rest = content.rsplit(")", 1)[1].split()
        utime, stime = int(rest[11]), int(rest[12])
        return float(utime + stime)


@dataclass
class OverheadResult:
    cpu_pct: float
    budget_pct: float
    over_budget: bool
    valid: bool


class OverheadGuard:
    """Delta-based CPU overhead evaluation against a budget.

    The first :meth:`evaluate` call primes the baseline and reports an
    invalid (non-actionable) result, mirroring the reference guard.
    """

    def __init__(
        self,
        budget_pct: float,
        sampler: CPUSampler | None = None,
        num_cpus: int | None = None,
    ):
        if budget_pct <= 0:
            raise ValueError("budget_pct must be > 0")
        self._budget_pct = budget_pct
        self._sampler = sampler or ProcCPUSampler()
        self._num_cpus = num_cpus or os.cpu_count() or 1
        self._last: CPUSample | None = None

    @property
    def budget_pct(self) -> float:
        return self._budget_pct

    def evaluate(self) -> OverheadResult:
        current = self._sampler.sample()
        last, self._last = self._last, current
        if last is None:
            return OverheadResult(0.0, self._budget_pct, False, valid=False)

        delta_total = current.total_ticks - last.total_ticks
        delta_proc = current.proc_ticks - last.proc_ticks
        if delta_total <= 0 or delta_proc < 0:
            return OverheadResult(0.0, self._budget_pct, False, valid=False)

        cpu_pct = (delta_proc / delta_total) * 100.0 * self._num_cpus
        return OverheadResult(
            cpu_pct=cpu_pct,
            budget_pct=self._budget_pct,
            over_budget=cpu_pct > self._budget_pct,
            valid=True,
        )
