"""Shed recovery: makes overhead-driven degradation two-way.

The overhead guard sheds probes when the agent busts its CPU budget,
but the reference design never re-enables them — one transient spike
permanently blinds the costliest signals.  This policy watches guard
results and, after N *consecutive* cycles comfortably under budget
(budget × headroom_factor, so recovery doesn't flap against the shed
threshold), authorizes re-enabling one shed signal.  Callers restore in
reverse shed order (cheapest first) and the streak restarts after every
restore, ramping probes back one at a time.
"""

from __future__ import annotations

from tpuslo.safety.overhead_guard import OverheadResult


class ShedRecoveryPolicy:
    """Counts consecutive under-budget guard cycles with hysteresis."""

    def __init__(self, cycles: int = 30, headroom_factor: float = 0.8):
        if cycles < 1:
            raise ValueError("cycles must be >= 1")
        if not 0 < headroom_factor <= 1:
            raise ValueError("headroom_factor must be in (0, 1]")
        self.cycles = cycles
        self.headroom_factor = headroom_factor
        self._streak = 0

    @property
    def streak(self) -> int:
        return self._streak

    def reset(self) -> None:
        self._streak = 0

    def note(self, result: OverheadResult) -> bool:
        """Feed one guard evaluation; True authorizes one restore.

        Invalid samples (first cycle, counter resets) neither extend
        nor break the streak — they carry no overhead signal.
        """
        if not result.valid:
            return False
        if (
            result.over_budget
            or result.cpu_pct > result.budget_pct * self.headroom_factor
        ):
            self._streak = 0
            return False
        self._streak += 1
        if self._streak >= self.cycles:
            self._streak = 0
            return True
        return False
