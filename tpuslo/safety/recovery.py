"""Shed recovery: makes overhead-driven degradation two-way.

The overhead guard sheds probes when the agent busts its CPU budget,
but the reference design never re-enables them — one transient spike
permanently blinds the costliest signals.  This policy watches guard
results and, after N *consecutive* cycles comfortably under budget
(budget × headroom_factor, so recovery doesn't flap against the shed
threshold), authorizes re-enabling one shed signal.  Callers restore in
reverse shed order (cheapest first) and the streak restarts after every
restore, ramping probes back one at a time.
"""

from __future__ import annotations

from typing import Any

from tpuslo.safety.overhead_guard import OverheadResult

#: Shed owners, in the order their claims arrive.  The supervisor's
#: flap hold-down is not an owner here — it is a separate veto that
#: outranks every owner (see :meth:`ShedOwnership.may_restore`).
OWNER_GUARD = "guard"
OWNER_REMEDIATION = "remediation"


class ShedOwnership:
    """Who shed each probe signal, and who may restore it.

    Three policies can shed (and want to restore) the same probe: the
    overhead guard + :class:`ShedRecoveryPolicy`, the supervisor's
    flap-shed, and the auto-remediation engine.  Without an explicit
    owner they tug-of-war — the recovery streak re-enables a probe
    remediation just shed, remediation rolls back a shed the guard
    still needs — so every shed carries an ownership tag and only the
    owner (or nobody, for legacy untagged sheds) may restore it.  The
    supervisor's flap hold-down additionally vetoes *every* restore:
    N quiet CPU cycles or a remediation rollback say nothing about why
    a probe was flapping.
    """

    def __init__(self):
        self._owners: dict[str, str] = {}

    def claim(self, signal: str, owner: str) -> bool:
        """Tag one shed; False when another owner already holds it
        (the first shed's reason wins — a second policy must not
        silently adopt, then restore, someone else's shed)."""
        current = self._owners.get(signal)
        if current is not None and current != owner:
            return False
        self._owners[signal] = owner
        return True

    def release(self, signal: str, owner: str) -> bool:
        """Drop a tag; only the owner may release its own claim."""
        if self._owners.get(signal) != owner:
            return False
        del self._owners[signal]
        return True

    def owner_of(self, signal: str) -> str:
        """The claiming owner, or "" for an untagged shed."""
        return self._owners.get(signal, "")

    def may_restore(
        self, signal: str, requestor: str, supervisor: Any = None
    ) -> bool:
        """True when ``requestor`` may restore this signal now.

        The supervisor hold-down (duck-typed ``may_restore(signal)``)
        outranks ownership in both directions: a flap-shed probe stays
        down for everyone.  Past that veto, a signal may be restored by
        its owner or — when untagged — by anyone (the pre-ownership
        behavior, so existing guard-shed flows are unchanged).
        """
        if supervisor is not None and not supervisor.may_restore(signal):
            return False
        owner = self._owners.get(signal)
        return owner is None or owner == requestor

    # ---- snapshot hooks (tpuslo.runtime.StateStore) -------------------

    def export_state(self) -> dict[str, Any]:
        return {"owners": dict(self._owners)}

    def restore_state(self, state: dict[str, Any]) -> None:
        self._owners = {
            str(signal): str(owner)
            for signal, owner in (state.get("owners") or {}).items()
        }


class ShedRecoveryPolicy:
    """Counts consecutive under-budget guard cycles with hysteresis."""

    def __init__(self, cycles: int = 30, headroom_factor: float = 0.8):
        if cycles < 1:
            raise ValueError("cycles must be >= 1")
        if not 0 < headroom_factor <= 1:
            raise ValueError("headroom_factor must be in (0, 1]")
        self.cycles = cycles
        self.headroom_factor = headroom_factor
        self._streak = 0

    @property
    def streak(self) -> int:
        return self._streak

    def reset(self) -> None:
        self._streak = 0

    def note(self, result: OverheadResult) -> bool:
        """Feed one guard evaluation; True authorizes one restore.

        Invalid samples (first cycle, counter resets) neither extend
        nor break the streak — they carry no overhead signal.
        """
        if not result.valid:
            return False
        if (
            result.over_budget
            or result.cpu_pct > result.budget_pct * self.headroom_factor
        ):
            self._streak = 0
            return False
        self._streak += 1
        if self._streak >= self.cycles:
            self._streak = 0
            return True
        return False
