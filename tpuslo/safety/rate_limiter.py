"""Event-rate limiter for agent emit paths.

Reference: ``pkg/safety/rate_limiter.go:9-39`` (per-second window).
Implemented as a token bucket — identical steady-state behaviour with a
configurable burst, and deterministic under an injected clock.
"""

from __future__ import annotations

import time
from typing import Callable


class RateLimiter:
    """Token bucket: ``events_per_second`` refill, ``burst`` capacity."""

    def __init__(
        self,
        events_per_second: int,
        burst: int | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if events_per_second < 1:
            raise ValueError("events_per_second must be >= 1")
        self._rate = float(events_per_second)
        self._capacity = float(burst if burst and burst > 0 else events_per_second)
        self._clock = clock
        self._tokens = self._capacity
        self._last = clock()

    def allow(self, n: int = 1) -> bool:
        """Consume ``n`` tokens if available; False means drop the event."""
        now = self._clock()
        elapsed = max(0.0, now - self._last)
        self._last = now
        self._tokens = min(self._capacity, self._tokens + elapsed * self._rate)
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    @property
    def tokens(self) -> float:
        return self._tokens

    # ---- snapshot hooks (tpuslo.runtime.StateStore) -------------------

    def export_state(self) -> dict:
        return {"tokens": self._tokens}

    def restore_state(self, state: dict) -> None:
        """Resume the previous incarnation's budget (clamped).

        Without this a crash-looping agent gets a full burst allowance
        on every restart — the restart loop itself would defeat the
        limiter.  Restoring the spent budget keeps the token bucket an
        invariant of the *node*, not the process.
        """
        try:
            tokens = float(state.get("tokens", self._capacity))
        except (TypeError, ValueError):
            return
        self._tokens = min(self._capacity, max(0.0, tokens))
        self._last = self._clock()
