"""Workload-identity metadata attached to probe events.

Reference: ``pkg/signals/metadata.go:10-118`` — a Metadata struct plus
enrichers: a static enricher for synthetic runs and a /proc-based
enricher that recovers pod/container identity from the cgroup path.  The
TPU-native build adds accelerator identity (chip, slice, host index, XLA
program) and a TPU enricher that discovers ``/dev/accel*`` and the
slice topology from the TPU-VM environment.
"""

from __future__ import annotations

import glob
import os
import re
from dataclasses import dataclass, replace
from typing import Protocol


@dataclass
class Metadata:
    """Identity attached to every probe event."""

    node: str = ""
    namespace: str = ""
    pod: str = ""
    container: str = ""
    pid: int = 0
    tid: int = 0
    trace_id: str = ""
    span_id: str = ""
    # TPU-native identity.
    tpu_chip: str = ""
    slice_id: str = ""
    host_index: int = 0
    xla_program_id: str = ""


class MetadataEnricher(Protocol):
    def enrich(self, meta: Metadata) -> Metadata: ...


class StaticMetadataEnricher:
    """Fills blanks from a fixed template (synthetic/agent default)."""

    def __init__(self, template: Metadata):
        self._template = template

    def enrich(self, meta: Metadata) -> Metadata:
        t = self._template
        return replace(
            meta,
            node=meta.node or t.node,
            namespace=meta.namespace or t.namespace,
            pod=meta.pod or t.pod,
            container=meta.container or t.container,
            pid=meta.pid or t.pid,
            tid=meta.tid or t.tid,
            tpu_chip=meta.tpu_chip or t.tpu_chip,
            slice_id=meta.slice_id or t.slice_id,
            host_index=meta.host_index or t.host_index,
            xla_program_id=meta.xla_program_id or t.xla_program_id,
        )


# kubepods cgroup leaf: .../kubepods<...>/pod<uid>/<container-id>
_POD_RE = re.compile(r"kubepods[^/]*/(?:[^/]+/)*pod([0-9a-f-]+)")
# Final path segment, optionally runtime-prefixed: ".../<id>",
# ".../docker-<id>.scope", ".../cri-containerd-<id>.scope".
_CONTAINER_RE = re.compile(r"(?:/|-)([0-9a-f]{12,64})(?:\.scope)?$")


class ProcMetadataEnricher:
    """Recovers pod/container identity from ``/proc/<pid>/cgroup``.

    Reference: ``pkg/signals/metadata.go:74-118``.
    """

    def __init__(self, proc_root: str = "/proc"):
        self._proc_root = proc_root

    def enrich(self, meta: Metadata) -> Metadata:
        if meta.pid <= 0 or (meta.pod and meta.container):
            return meta
        path = os.path.join(self._proc_root, str(meta.pid), "cgroup")
        try:
            content = open(path, encoding="utf-8").read()
        except OSError:
            return meta
        pod, container = parse_cgroup_identity(content)
        return replace(
            meta,
            pod=meta.pod or pod,
            container=meta.container or container,
        )


def parse_cgroup_identity(content: str) -> tuple[str, str]:
    """Extract (pod-uid, container-id) from cgroup file content."""
    pod = ""
    container = ""
    for line in content.splitlines():
        path = line.rsplit(":", 1)[-1]
        if not pod:
            m = _POD_RE.search(path)
            if m:
                pod = m.group(1)
        if not container:
            m = _CONTAINER_RE.search(path)
            if m:
                container = m.group(1)
        if pod and container:
            break
    return pod, container


class TPUMetadataEnricher:
    """Discovers accelerator identity on a TPU-VM host.

    Chip comes from the first ``/dev/accel*`` node; slice/host identity
    from the TPU-VM runtime environment (``TPU_WORKER_ID`` /
    ``MEGASCALE_SLICE_ID`` or their CLOUD_TPU equivalents).
    """

    def __init__(self, dev_glob: str = "/dev/accel*", env: dict[str, str] | None = None):
        self._dev_glob = dev_glob
        self._env = env if env is not None else dict(os.environ)

    def discover_chips(self) -> list[str]:
        return sorted(os.path.basename(p) for p in glob.glob(self._dev_glob))

    def enrich(self, meta: Metadata) -> Metadata:
        chips = self.discover_chips()
        chip = meta.tpu_chip or (chips[0] if chips else "")
        slice_id = meta.slice_id or self._env.get(
            "MEGASCALE_SLICE_ID", self._env.get("TPU_SLICE_ID", "")
        )
        host_raw = self._env.get(
            "TPU_WORKER_ID", self._env.get("CLOUD_TPU_TASK_ID", "")
        )
        try:
            host_index = int(host_raw)
        except (TypeError, ValueError):
            host_index = meta.host_index
        return replace(
            meta,
            tpu_chip=chip,
            slice_id=slice_id,
            host_index=host_index if host_raw else meta.host_index,
        )
