"""Per-fault synthetic signal profiles and probe-event fan-out.

Reference: ``pkg/signals/generator.go`` — a capability-filtered generator
expands one request sample into one normalized probe event per enabled
signal, with values drawn from a fault-label → signal-profile table and
statuses from per-signal warn/error thresholds
(``generator.go:203-289``).  The TPU-native build extends both tables
with the six accelerator signals and stamps TPU events with accelerator
identity (:class:`tpuslo.schema.TPURef`) so the XLA correlation tier can
join them to spans.
"""

from __future__ import annotations

import re
import threading
from typing import Iterable

from tpuslo.collector.synthetic import RawSample
from tpuslo.schema import ConnTuple, ProbeEventV1, TPURef
from tpuslo.signals import constants as sig
from tpuslo.signals.metadata import Metadata, MetadataEnricher

# Per-signal (warning, error) status thresholds.
# CPU rows: reference ``generator.go:203-242``; TPU rows: designed from
# v5e serving envelopes (a >2s compile or >20ms HBM stall is pathological).
SIGNAL_THRESHOLDS: dict[str, tuple[float, float]] = {
    sig.SIGNAL_DNS_LATENCY_MS: (40, 120),
    sig.SIGNAL_TCP_RETRANSMITS: (2, 5),
    sig.SIGNAL_RUNQUEUE_DELAY_MS: (10, 25),
    sig.SIGNAL_CONNECT_LATENCY_MS: (80, 180),
    sig.SIGNAL_CONNECT_ERRORS: (1, 3),
    sig.SIGNAL_TLS_HANDSHAKE_MS: (60, 160),
    sig.SIGNAL_TLS_HANDSHAKE_FAILS: (1, 3),
    sig.SIGNAL_CPU_STEAL_PCT: (2, 8),
    sig.SIGNAL_CFS_THROTTLED_MS: (40, 120),
    sig.SIGNAL_MEM_RECLAIM_LATENCY_MS: (5, 20),
    sig.SIGNAL_DISK_IO_LATENCY_MS: (10, 50),
    sig.SIGNAL_SYSCALL_LATENCY_MS: (50, 200),
    sig.SIGNAL_XLA_COMPILE_MS: (500, 2000),
    sig.SIGNAL_HBM_ALLOC_STALL_MS: (5, 20),
    sig.SIGNAL_HBM_UTILIZATION_PCT: (85, 95),
    sig.SIGNAL_ICI_LINK_RETRIES: (5, 20),
    sig.SIGNAL_ICI_COLLECTIVE_MS: (10, 30),
    sig.SIGNAL_HOST_OFFLOAD_STALL_MS: (20, 80),
    sig.SIGNAL_DCN_TRANSFER_MS: (25, 80),
    sig.SIGNAL_DEVICE_IDLE_GAP_MS: (25, 100),
    sig.SIGNAL_DEVICE_EVICTION_EVENTS: (1, 3),
    sig.SIGNAL_DEVICE_UNEXPLAINED_SHARE: (0.10, 0.25),
    # MFU is LOW-is-bad and often meaningless (memory-bound decode);
    # the high-is-bad ladder must never fire on it, so both thresholds
    # sit above the 100% ceiling and the status is always "ok".  The
    # profiler's roofline verdict carries the interpretation.
    sig.SIGNAL_DEVICE_MFU_PCT: (101.0, 101.0),
}

SIGNAL_UNITS: dict[str, str] = {
    sig.SIGNAL_DNS_LATENCY_MS: "ms",
    sig.SIGNAL_TCP_RETRANSMITS: "count",
    sig.SIGNAL_RUNQUEUE_DELAY_MS: "ms",
    sig.SIGNAL_CONNECT_LATENCY_MS: "ms",
    sig.SIGNAL_CONNECT_ERRORS: "count",
    sig.SIGNAL_TLS_HANDSHAKE_MS: "ms",
    sig.SIGNAL_TLS_HANDSHAKE_FAILS: "count",
    sig.SIGNAL_CPU_STEAL_PCT: "pct",
    sig.SIGNAL_CFS_THROTTLED_MS: "ms",
    sig.SIGNAL_MEM_RECLAIM_LATENCY_MS: "ms",
    sig.SIGNAL_DISK_IO_LATENCY_MS: "ms",
    sig.SIGNAL_SYSCALL_LATENCY_MS: "ms",
    sig.SIGNAL_XLA_COMPILE_MS: "ms",
    sig.SIGNAL_HBM_ALLOC_STALL_MS: "ms",
    sig.SIGNAL_HBM_UTILIZATION_PCT: "pct",
    sig.SIGNAL_ICI_LINK_RETRIES: "count",
    sig.SIGNAL_ICI_COLLECTIVE_MS: "ms",
    sig.SIGNAL_HOST_OFFLOAD_STALL_MS: "ms",
    sig.SIGNAL_DCN_TRANSFER_MS: "ms",
    sig.SIGNAL_DEVICE_IDLE_GAP_MS: "ms",
    sig.SIGNAL_DEVICE_EVICTION_EVENTS: "count",
    sig.SIGNAL_DEVICE_UNEXPLAINED_SHARE: "ratio",
    sig.SIGNAL_DEVICE_MFU_PCT: "pct",
}

# Signals only the continuous profiler's capture windows can source:
# the synthetic fault generator has no per-request story for them (they
# are per-WINDOW ledger folds), and — load-bearing — adding them to
# ``_BASE_PROFILE``/``_FAULT_OVERRIDES`` would insert RNG draws into
# ``calibrate.corrupt``'s sequential stream and re-roll every
# calibrated likelihood floor.  ``Generator.set_signals`` filters them
# out of the enabled set so both fan-out paths (row and columnar)
# never look them up in a fault profile.
PROFILER_ONLY_SIGNALS = frozenset(
    {
        sig.SIGNAL_DEVICE_UNEXPLAINED_SHARE,
        sig.SIGNAL_DEVICE_MFU_PCT,
    }
)

# Signals that carry a network flow tuple.
_CONN_TUPLE_SIGNALS = frozenset(
    {
        sig.SIGNAL_DNS_LATENCY_MS,
        sig.SIGNAL_TCP_RETRANSMITS,
        sig.SIGNAL_CONNECT_LATENCY_MS,
        sig.SIGNAL_CONNECT_ERRORS,
        sig.SIGNAL_TLS_HANDSHAKE_MS,
        sig.SIGNAL_TLS_HANDSHAKE_FAILS,
    }
)

# Healthy baseline values; CPU rows mirror reference ``generator.go:244-261``.
_BASE_PROFILE: dict[str, float] = {
    sig.SIGNAL_DNS_LATENCY_MS: 12,
    sig.SIGNAL_TCP_RETRANSMITS: 0.2,
    sig.SIGNAL_RUNQUEUE_DELAY_MS: 4,
    sig.SIGNAL_CONNECT_LATENCY_MS: 18,
    sig.SIGNAL_CONNECT_ERRORS: 0,
    sig.SIGNAL_TLS_HANDSHAKE_MS: 22,
    sig.SIGNAL_TLS_HANDSHAKE_FAILS: 0,
    sig.SIGNAL_CPU_STEAL_PCT: 0.6,
    sig.SIGNAL_CFS_THROTTLED_MS: 5,
    sig.SIGNAL_MEM_RECLAIM_LATENCY_MS: 0.5,
    sig.SIGNAL_DISK_IO_LATENCY_MS: 2,
    sig.SIGNAL_SYSCALL_LATENCY_MS: 5,
    sig.SIGNAL_XLA_COMPILE_MS: 0,
    sig.SIGNAL_HBM_ALLOC_STALL_MS: 0.2,
    sig.SIGNAL_HBM_UTILIZATION_PCT: 62,
    sig.SIGNAL_ICI_LINK_RETRIES: 0,
    sig.SIGNAL_ICI_COLLECTIVE_MS: 3.5,
    sig.SIGNAL_HOST_OFFLOAD_STALL_MS: 1.5,
    sig.SIGNAL_DCN_TRANSFER_MS: 8.0,
    sig.SIGNAL_DEVICE_IDLE_GAP_MS: 2.0,
    sig.SIGNAL_DEVICE_EVICTION_EVENTS: 0,
}

# Fault label -> (signal overrides, connect errno).
# CPU rows mirror reference ``generator.go:263-289``.  TPU rows encode
# how each accelerator fault manifests across the probe surface:
#   ici_drop            — link retries + collective latency explode; a
#                         degraded link also backs up the launch queue.
#   hbm_pressure        — allocator stalls + near-full HBM; the runtime
#                         starts spilling to host, so offload stall
#                         creeps into warning.
#   xla_recompile_storm — compile wall-time dominates; compiles burn
#                         host CPU so the runqueue warms up.
#   host_offload_stall  — host<->device transfers stall; feeding from
#                         disk drags disk/syscall latency with it.
_FAULT_OVERRIDES: dict[str, tuple[dict[str, float], int]] = {
    "baseline": ({}, 0),
    "dns_latency": (
        {
            sig.SIGNAL_DNS_LATENCY_MS: 220,
            sig.SIGNAL_CONNECT_LATENCY_MS: 130,
        },
        0,
    ),
    "cpu_throttle": (
        {
            sig.SIGNAL_RUNQUEUE_DELAY_MS: 28,
            sig.SIGNAL_CPU_STEAL_PCT: 9,
            sig.SIGNAL_CFS_THROTTLED_MS: 170,
        },
        0,
    ),
    "memory_pressure": (
        {
            sig.SIGNAL_RUNQUEUE_DELAY_MS: 14,
            sig.SIGNAL_CFS_THROTTLED_MS: 90,
            sig.SIGNAL_MEM_RECLAIM_LATENCY_MS: 25,
            sig.SIGNAL_DISK_IO_LATENCY_MS: 60,
        },
        0,
    ),
    "provider_throttle": (
        {
            # Backoff at the provider edge: accepts and handshakes slow
            # past their warning lines while reads block on rate limits.
            sig.SIGNAL_CONNECT_LATENCY_MS: 95,
            sig.SIGNAL_TLS_HANDSHAKE_MS: 70,
            sig.SIGNAL_CONNECT_ERRORS: 1,
            sig.SIGNAL_SYSCALL_LATENCY_MS: 250,
        },
        110,
    ),
    "network_partition": (
        {
            sig.SIGNAL_CONNECT_LATENCY_MS: 350,
            sig.SIGNAL_CONNECT_ERRORS: 3,
            sig.SIGNAL_TCP_RETRANSMITS: 12,
            sig.SIGNAL_DNS_LATENCY_MS: 180,
            sig.SIGNAL_TLS_HANDSHAKE_FAILS: 2,
        },
        113,
    ),
    "ici_drop": (
        {
            sig.SIGNAL_ICI_LINK_RETRIES: 45,
            sig.SIGNAL_ICI_COLLECTIVE_MS: 55,
            sig.SIGNAL_HOST_OFFLOAD_STALL_MS: 8,
        },
        0,
    ),
    "hbm_pressure": (
        {
            sig.SIGNAL_HBM_ALLOC_STALL_MS: 60,
            sig.SIGNAL_HBM_UTILIZATION_PCT: 97,
            sig.SIGNAL_HOST_OFFLOAD_STALL_MS: 25,
        },
        0,
    ),
    "xla_recompile_storm": (
        {
            sig.SIGNAL_XLA_COMPILE_MS: 3200,
            sig.SIGNAL_RUNQUEUE_DELAY_MS: 12,
        },
        0,
    ),
    "host_offload_stall": (
        {
            sig.SIGNAL_HOST_OFFLOAD_STALL_MS: 120,
            sig.SIGNAL_DISK_IO_LATENCY_MS: 40,
            sig.SIGNAL_SYSCALL_LATENCY_MS: 80,
        },
        0,
    ),
    # preemption_eviction — the chip is preempted/evicted out from
    # under the serving process: the runtime posts eviction notices and
    # the device-plane ledger shows a massive idle gap while the host
    # re-acquires the device.  The restart recompiles warm xla_compile
    # only mildly (sub-warning — the separator from a recompile storm),
    # and ICI/HBM stay clean (the separators from the fabric domains).
    "preemption_eviction": (
        {
            sig.SIGNAL_DEVICE_EVICTION_EVENTS: 4,
            sig.SIGNAL_DEVICE_IDLE_GAP_MS: 420,
            sig.SIGNAL_XLA_COMPILE_MS: 380,
            sig.SIGNAL_HOST_OFFLOAD_STALL_MS: 6,
        },
        0,
    ),
    # noisy_neighbor_cpu — another tenant's burst starves this host's
    # vCPUs: steal and runqueue delay explode WITHOUT cgroup quota
    # throttling (cfs_throttled stays at baseline — the separator from
    # cpu_throttle, whose physiology is the quota).  The starved
    # dispatch thread cannot feed the chip, so the ledger's idle gap
    # creeps past warning — host-plane cause, device-plane symptom.
    "noisy_neighbor_cpu": (
        {
            sig.SIGNAL_CPU_STEAL_PCT: 18,
            sig.SIGNAL_RUNQUEUE_DELAY_MS: 32,
            sig.SIGNAL_DEVICE_IDLE_GAP_MS: 60,
            sig.SIGNAL_SYSCALL_LATENCY_MS: 70,
        },
        0,
    ),
    # dcn_degradation — the cross-slice transfer phase stalls: the DCN
    # fabric is ethernet, so retransmits climb with it and whole-
    # collective latency warms up, but ICI link retries stay clean
    # (that is the separator from ici_drop) and there are no connect/
    # DNS symptoms (the separator from network_partition).
    "dcn_degradation": (
        {
            sig.SIGNAL_DCN_TRANSFER_MS: 140,
            sig.SIGNAL_TCP_RETRANSMITS: 6,
            sig.SIGNAL_ICI_COLLECTIVE_MS: 18,
        },
        0,
    ),
    "mixed_multi": (
        {
            # Concurrent network partition + provider throttle.
            sig.SIGNAL_CONNECT_LATENCY_MS: 350,
            sig.SIGNAL_CONNECT_ERRORS: 3,
            sig.SIGNAL_TCP_RETRANSMITS: 12,
            sig.SIGNAL_DNS_LATENCY_MS: 180,
            sig.SIGNAL_TLS_HANDSHAKE_FAILS: 2,
            sig.SIGNAL_TLS_HANDSHAKE_MS: 70,
            sig.SIGNAL_SYSCALL_LATENCY_MS: 250,
        },
        110,
    ),
}


def profile_for_fault(fault_label: str) -> dict[str, float]:
    """Full signal→value map for a fault label (base + overrides)."""
    overrides, _ = _FAULT_OVERRIDES.get(fault_label or "baseline", ({}, 0))
    profile = dict(_BASE_PROFILE)
    profile.update(overrides)
    return profile


def errno_for_fault(fault_label: str) -> int:
    return _FAULT_OVERRIDES.get(fault_label or "baseline", ({}, 0))[1]


def signal_status(signal: str, value: float) -> str:
    """Map a signal value to ok/warning/error via per-signal thresholds."""
    thresholds = SIGNAL_THRESHOLDS.get(signal)
    if thresholds is None:
        return "ok"
    warning, error = thresholds
    if value >= error:
        return "error"
    if value >= warning:
        return "warning"
    return "ok"


_REQ_NUM = re.compile(r"(\d+)$")


def _launch_id_for(sample: RawSample) -> int:
    """Deterministic synthetic XLA launch id derived from request identity."""
    match = _REQ_NUM.search(sample.request_id or "")
    return int(match.group(1)) if match else 0


class Generator:
    """Capability-filtered probe-event generator.

    Reference: ``pkg/signals/generator.go:27-155``.  Thread-safe: the
    agent's shedding loop disables signals concurrently with generation.
    """

    def __init__(
        self,
        mode: str,
        signal_set: Iterable[str] | None = None,
        enricher: MetadataEnricher | None = None,
    ):
        self._mode = mode
        self._enricher = enricher
        self._lock = threading.Lock()
        self._enabled: set[str] = set()
        self._shed: list[str] = []  # guard-shed signals, shed order
        self.set_signals(signal_set or [])

    @property
    def mode(self) -> str:
        return self._mode

    def set_signals(self, signal_set: Iterable[str]) -> None:
        """Replace enabled probes at runtime, filtered by capability."""
        allowed = (
            set(sig.supported_signals_for_mode(self._mode))
            - PROFILER_ONLY_SIGNALS
        )
        requested = set(signal_set)
        with self._lock:
            self._enabled = (requested & allowed) if requested else allowed
            self._shed.clear()  # a new set supersedes shed history

    def enabled_signals(self) -> list[str]:
        with self._lock:
            return sorted(self._enabled)

    def disable(self, signal: str) -> bool:
        with self._lock:
            if signal not in self._enabled:
                return False
            self._enabled.discard(signal)
            return True

    def disable_highest_cost(self) -> str | None:
        """Shed the next signal in the high-cost disable order."""
        with self._lock:
            for candidate in sig.HIGH_COST_DISABLE_ORDER:
                if candidate in self._enabled:
                    self._enabled.discard(candidate)
                    self._shed.append(candidate)
                    return candidate
        return None

    def shed_signals(self) -> list[str]:
        """Guard-shed signals awaiting restore, in shed order."""
        with self._lock:
            return list(self._shed)

    def restore_one(self) -> str | None:
        """Re-enable the most recently shed signal (reverse cost order:
        the cheapest still-shed probe comes back first).  Degradation is
        no longer one-way — see tpuslo.safety.ShedRecoveryPolicy."""
        with self._lock:
            while self._shed:
                signal = self._shed.pop()
                if signal in self._enabled:
                    continue  # re-enabled out of band (set_signals race)
                self._enabled.add(signal)
                return signal
        return None

    def restore_signal(self, signal: str) -> bool:
        """Re-enable one specific shed signal (remediation rollback:
        the engine must restore exactly the probe *it* shed, not
        whatever happens to sit on top of the shed stack)."""
        with self._lock:
            if signal not in self._shed:
                return False
            self._shed.remove(signal)
            self._enabled.add(signal)
            return True

    def import_shed(self, signals: Iterable[str]) -> list[str]:
        """Adopt a restored shed list (oldest-shed first).

        A restarted agent must not re-enable probes its previous
        incarnation shed for overhead: the CPU pressure that forced the
        shed does not reset with the process.  Signals are re-shed in
        the recorded order so ``restore_one`` still ramps back cheapest
        first.  Returns the signals actually re-shed (unknown or
        already-shed names are skipped).
        """
        imported: list[str] = []
        with self._lock:
            for signal in signals:
                if signal in self._enabled:
                    self._enabled.discard(signal)
                    self._shed.append(signal)
                    imported.append(signal)
        return imported

    def generate(self, sample: RawSample, meta: Metadata) -> list[ProbeEventV1]:
        """Expand one sample into normalized probe events, one per signal."""
        return self.generate_batch([sample], meta)

    def generate_batch(
        self, samples: Iterable[RawSample], meta: Metadata
    ) -> list[ProbeEventV1]:
        """Expand a sample batch, in sample order then signal order.

        The hot-path twin of :meth:`generate`: the enabled-signal set and
        metadata enrichment are snapshotted once per batch (one lock
        acquisition, one enricher call), per-signal templates
        (unit / conn-tuple membership / errno eligibility / ICI link)
        are precomputed, and the per-fault value+status pairs are cached
        per distinct fault label rather than rebuilt per sample.
        """
        with self._lock:
            enabled = self._enabled.copy()
        if not enabled:
            return []

        if self._enricher is not None:
            meta = self._enricher.enrich(meta)

        # Per-batch signal templates: (signal, unit, is_conn, takes_errno,
        # ici_link or None when the signal carries no TPU block).
        templates = [
            (
                signal,
                SIGNAL_UNITS[signal],
                signal in _CONN_TUPLE_SIGNALS,
                signal
                in (sig.SIGNAL_CONNECT_LATENCY_MS, sig.SIGNAL_CONNECT_ERRORS),
                (0 if signal == sig.SIGNAL_ICI_LINK_RETRIES else -1)
                if signal in sig.TPU_SIGNALS
                else None,
            )
            for signal in sig.ALL_SIGNALS
            if signal in enabled
        ]
        conn_tuple = ConnTuple("10.244.0.10", "10.244.0.53", 42424, 443, "tcp")
        node, namespace, pod = meta.node, meta.namespace, meta.pod
        container, pid, tid = meta.container, meta.pid, meta.tid
        trace_id, span_id = meta.trace_id, meta.span_id
        chip = meta.tpu_chip or "accel0"

        # (value, status) per enabled signal, keyed by fault label: a
        # batch usually carries a handful of labels across hundreds of
        # samples, so threshold lookups happen once per label.
        fault_rows: dict[str, tuple[tuple[float, str], ...]] = {}

        out: list[ProbeEventV1] = []
        for sample in samples:
            label = sample.fault_label
            rows = fault_rows.get(label)
            if rows is None:
                profile = profile_for_fault(label)
                rows = tuple(
                    (profile[signal], signal_status(signal, profile[signal]))
                    for signal, _, _, _, _ in templates
                )
                fault_rows[label] = rows
            errno = errno_for_fault(label)
            ts_ns = int(sample.timestamp.timestamp() * 1e9)
            launch_id = _launch_id_for(sample)
            # TPU identity is per sample (launch id), shared across the
            # sample's TPU events except the ICI-link variant.
            tpu_ref = ici_ref = None

            for (signal, unit, is_conn, takes_errno, ici_link), (
                value,
                status,
            ) in zip(templates, rows):
                event = ProbeEventV1(
                    ts_unix_nano=ts_ns,
                    signal=signal,
                    node=node,
                    namespace=namespace,
                    pod=pod,
                    container=container,
                    pid=pid,
                    tid=tid,
                    value=value,
                    unit=unit,
                    status=status,
                    trace_id=trace_id,
                    span_id=span_id,
                )
                if is_conn:
                    event.conn_tuple = conn_tuple
                    if errno and takes_errno:
                        event.errno = errno
                if ici_link is not None:
                    if ici_link >= 0:
                        if ici_ref is None:
                            ici_ref = self._tpu_ref(
                                chip, meta, launch_id, ici_link
                            )
                        event.tpu = ici_ref
                    else:
                        if tpu_ref is None:
                            tpu_ref = self._tpu_ref(
                                chip, meta, launch_id, ici_link
                            )
                        event.tpu = tpu_ref
                out.append(event)
        return out

    def generate_batch_columnar(self, samples, meta: Metadata, trace_ids=None):
        """Columnar twin of :meth:`generate_batch`: samples → columns.

        Same snapshot semantics (one lock acquisition, one enricher
        call per batch), but the expansion writes a
        :class:`tpuslo.columnar.ColumnarBatch` directly — no per-event
        dataclass.  ``trace_ids`` optionally stamps each sample's own
        trace identity (the agent's columnar loop needs per-sample
        traces; the row batch API carries one meta for the batch).
        Parity with the row path is locked in by
        tests/test_columnar_parity.py.
        """
        from tpuslo.columnar.generate import columns_from_samples

        with self._lock:
            enabled = self._enabled.copy()
        if self._enricher is not None:
            meta = self._enricher.enrich(meta)
        return columns_from_samples(samples, meta, enabled, trace_ids)

    @staticmethod
    def _tpu_ref(
        chip: str, meta: Metadata, launch_id: int, ici_link: int
    ) -> TPURef:
        return TPURef(
            chip=chip,
            slice_id=meta.slice_id,
            host_index=meta.host_index,
            ici_link=ici_link,
            program_id=meta.xla_program_id,
            launch_id=launch_id,
        )
