"""L2 signal modeling: registry, capability modes, synthetic profiles."""

from tpuslo.signals.constants import (
    ALL_SIGNALS,
    CAPABILITY_BCC_DEGRADED,
    CAPABILITY_CORE_FULL,
    CAPABILITY_MODES,
    CAPABILITY_TPU_FULL,
    CPU_SIGNALS,
    HIGH_COST_DISABLE_ORDER,
    TPU_SIGNALS,
    disable_order,
    required_minimum_signals,
    supported_signals_for_mode,
)
from tpuslo.signals.generator import (
    SIGNAL_THRESHOLDS,
    SIGNAL_UNITS,
    Generator,
    errno_for_fault,
    profile_for_fault,
    signal_status,
)
from tpuslo.signals.metadata import (
    Metadata,
    MetadataEnricher,
    ProcMetadataEnricher,
    StaticMetadataEnricher,
    TPUMetadataEnricher,
    parse_cgroup_identity,
)
from tpuslo.signals.mode import (
    detect_capability_mode,
    find_libtpu,
    has_btf,
    has_tpu_surface,
    parse_capability_mode,
)

__all__ = [
    "ALL_SIGNALS",
    "CAPABILITY_BCC_DEGRADED",
    "CAPABILITY_CORE_FULL",
    "CAPABILITY_MODES",
    "CAPABILITY_TPU_FULL",
    "CPU_SIGNALS",
    "HIGH_COST_DISABLE_ORDER",
    "TPU_SIGNALS",
    "SIGNAL_THRESHOLDS",
    "SIGNAL_UNITS",
    "Generator",
    "Metadata",
    "MetadataEnricher",
    "ProcMetadataEnricher",
    "StaticMetadataEnricher",
    "TPUMetadataEnricher",
    "detect_capability_mode",
    "disable_order",
    "errno_for_fault",
    "find_libtpu",
    "has_btf",
    "has_tpu_surface",
    "parse_capability_mode",
    "parse_cgroup_identity",
    "profile_for_fault",
    "required_minimum_signals",
    "signal_status",
    "supported_signals_for_mode",
]
