"""Signal registry and capability modes.

Reference: ``pkg/signals/constants.go:4-59`` defines twelve CPU-side
signal keys, two capability modes (``core_full`` / ``bcc_degraded``) and
the overhead disable order.  The TPU-native build adds seven accelerator
signals sourced from libtpu uprobes and ``/dev/accel*`` kprobes and a
``tpu_full`` capability mode; TPU probes are shed *first* when the
overhead guard trips (SURVEY.md §7 step 6).
"""

from __future__ import annotations

# --- CPU-side kernel signals (reference parity) -------------------------
SIGNAL_DNS_LATENCY_MS = "dns_latency_ms"
SIGNAL_TCP_RETRANSMITS = "tcp_retransmits_total"
SIGNAL_RUNQUEUE_DELAY_MS = "runqueue_delay_ms"
SIGNAL_CONNECT_LATENCY_MS = "connect_latency_ms"
SIGNAL_CONNECT_ERRORS = "connect_errors_total"
SIGNAL_TLS_HANDSHAKE_MS = "tls_handshake_ms"
SIGNAL_TLS_HANDSHAKE_FAILS = "tls_handshake_fail_total"
SIGNAL_CPU_STEAL_PCT = "cpu_steal_pct"
SIGNAL_CFS_THROTTLED_MS = "cfs_throttled_ms"
SIGNAL_MEM_RECLAIM_LATENCY_MS = "mem_reclaim_latency_ms"
SIGNAL_DISK_IO_LATENCY_MS = "disk_io_latency_ms"
SIGNAL_SYSCALL_LATENCY_MS = "syscall_latency_ms"

# --- TPU-side signals (TPU-native extension) ----------------------------
# XLA program compile wall time, from uprobes on libtpu compile entry/exit.
SIGNAL_XLA_COMPILE_MS = "xla_compile_ms"
# Time a device allocation waited for HBM to free up (allocator uprobes).
SIGNAL_HBM_ALLOC_STALL_MS = "hbm_alloc_stall_ms"
# Fraction of device HBM in use, sampled from the allocator statistics.
SIGNAL_HBM_UTILIZATION_PCT = "hbm_utilization_pct"
# Per-window count of ICI link-level retries (driver counters).
SIGNAL_ICI_LINK_RETRIES = "ici_link_retries_total"
# Wall time of cross-chip collectives (all-reduce/all-gather launches).
SIGNAL_ICI_COLLECTIVE_MS = "ici_collective_latency_ms"
# Host<->device transfer stall (infeed/outfeed/offload wait), dma uprobes
# plus /dev/accel* ioctl kprobe latency.
SIGNAL_HOST_OFFLOAD_STALL_MS = "host_offload_stall_ms"
# Wall time of the cross-slice (DCN) transfer phase inside multi-slice
# collectives, from megascale transfer uprobes.  Distinct from the ICI
# signals: DCN rides the data-center ethernet fabric between slices, so
# its failure physiology pairs with TCP retransmits, not link retries.
SIGNAL_DCN_TRANSFER_MS = "dcn_transfer_latency_ms"
# Per-window device idle-gap time from the device-plane ledger
# (tpuslo/deviceplane): wall time inside the observation window where
# the chip ran NO launch at all.  A preempted/evicted device shows a
# huge gap; a starved dispatch thread (noisy-neighbor host CPU) shows a
# creeping one.  Sampled from the ledger, not probed.
SIGNAL_DEVICE_IDLE_GAP_MS = "device_idle_gap_ms"
# Per-window count of device preemption/eviction notices (maintenance
# events, device re-init after the runtime lost the chip).
SIGNAL_DEVICE_EVICTION_EVENTS = "device_eviction_events_total"
# Fraction of the profiler window's device time the ledger's tier
# ladder could NOT explain (tpuslo/deviceplane/ledger.py's honest
# remainder).  A creeping share means the join ladder is losing
# launches — capture truncation, a new anonymous program, or a lane
# the ledger has never seen.  Sampled per capture window by the
# continuous profiler (tpuslo/deviceplane/profiler.py); the synthetic
# fault generator never fabricates it (see Generator.set_signals).
SIGNAL_DEVICE_UNEXPLAINED_SHARE = "device_unexplained_share"
# Model-FLOP utilisation of the window's serving program against the
# chip's compute roof, from the roofline fold over the ledger's joined
# launches.  LOW is bad (and on memory-bound decode, meaningless — the
# attached roofline verdict carries the interpretation), so it takes
# no place in the high-is-bad warn/error ladder: informational only.
SIGNAL_DEVICE_MFU_PCT = "device_mfu_pct"

CPU_SIGNALS: tuple[str, ...] = (
    SIGNAL_DNS_LATENCY_MS,
    SIGNAL_TCP_RETRANSMITS,
    SIGNAL_RUNQUEUE_DELAY_MS,
    SIGNAL_CONNECT_LATENCY_MS,
    SIGNAL_CONNECT_ERRORS,
    SIGNAL_TLS_HANDSHAKE_MS,
    SIGNAL_TLS_HANDSHAKE_FAILS,
    SIGNAL_CPU_STEAL_PCT,
    SIGNAL_CFS_THROTTLED_MS,
    SIGNAL_MEM_RECLAIM_LATENCY_MS,
    SIGNAL_DISK_IO_LATENCY_MS,
    SIGNAL_SYSCALL_LATENCY_MS,
)

TPU_SIGNALS: tuple[str, ...] = (
    SIGNAL_XLA_COMPILE_MS,
    SIGNAL_HBM_ALLOC_STALL_MS,
    SIGNAL_HBM_UTILIZATION_PCT,
    SIGNAL_ICI_LINK_RETRIES,
    SIGNAL_ICI_COLLECTIVE_MS,
    SIGNAL_HOST_OFFLOAD_STALL_MS,
    SIGNAL_DCN_TRANSFER_MS,
    SIGNAL_DEVICE_IDLE_GAP_MS,
    SIGNAL_DEVICE_EVICTION_EVENTS,
    SIGNAL_DEVICE_UNEXPLAINED_SHARE,
    SIGNAL_DEVICE_MFU_PCT,
)

ALL_SIGNALS: tuple[str, ...] = CPU_SIGNALS + TPU_SIGNALS

# --- Capability modes ---------------------------------------------------
# tpu_full     — TPU-VM host with libtpu + /dev/accel access: all signals.
# core_full    — CO-RE capable kernel, no TPU probe surface: CPU signals.
# bcc_degraded — no BTF; BCC fallback covers DNS + TCP retransmits only.
CAPABILITY_TPU_FULL = "tpu_full"
CAPABILITY_CORE_FULL = "core_full"
CAPABILITY_BCC_DEGRADED = "bcc_degraded"

CAPABILITY_MODES = (
    CAPABILITY_TPU_FULL,
    CAPABILITY_CORE_FULL,
    CAPABILITY_BCC_DEGRADED,
)

_BCC_SIGNAL_SET: tuple[str, ...] = (
    SIGNAL_DNS_LATENCY_MS,
    SIGNAL_TCP_RETRANSMITS,
)

# Disable order when the overhead guard trips.  TPU uprobes are shed
# before kernel probes: high-rate libtpu call sites (collective launches,
# allocator hits) dominate event volume on a busy chip, and losing TPU
# depth degrades attribution less than losing the kernel spine entirely.
# The CPU tail mirrors reference ``constants.go:46-59``.
HIGH_COST_DISABLE_ORDER: tuple[str, ...] = (
    # The device-plane ledger signals are sampled (no probe cost), but
    # producing them requires an xprof/ledger pass — shed that first.
    # The continuous-profiler window signals sit at the very front:
    # they ride the same capture the profiler's own overhead governor
    # already degrades, so they are the cheapest depth to give back.
    SIGNAL_DEVICE_UNEXPLAINED_SHARE,
    SIGNAL_DEVICE_MFU_PCT,
    SIGNAL_DEVICE_IDLE_GAP_MS,
    SIGNAL_DEVICE_EVICTION_EVENTS,
    SIGNAL_DCN_TRANSFER_MS,
    SIGNAL_ICI_COLLECTIVE_MS,
    SIGNAL_HBM_ALLOC_STALL_MS,
    SIGNAL_HOST_OFFLOAD_STALL_MS,
    SIGNAL_XLA_COMPILE_MS,
    SIGNAL_HBM_UTILIZATION_PCT,
    SIGNAL_ICI_LINK_RETRIES,
    SIGNAL_TLS_HANDSHAKE_MS,
    SIGNAL_SYSCALL_LATENCY_MS,
    SIGNAL_RUNQUEUE_DELAY_MS,
    SIGNAL_DISK_IO_LATENCY_MS,
    SIGNAL_CONNECT_LATENCY_MS,
    SIGNAL_MEM_RECLAIM_LATENCY_MS,
    SIGNAL_CPU_STEAL_PCT,
    SIGNAL_DNS_LATENCY_MS,
    SIGNAL_TCP_RETRANSMITS,
    SIGNAL_CFS_THROTTLED_MS,
    SIGNAL_CONNECT_ERRORS,
    SIGNAL_TLS_HANDSHAKE_FAILS,
)


def required_minimum_signals() -> list[str]:
    """The six required baseline signals (reference ``constants.go:62-71``)."""
    return [
        SIGNAL_DNS_LATENCY_MS,
        SIGNAL_TCP_RETRANSMITS,
        SIGNAL_RUNQUEUE_DELAY_MS,
        SIGNAL_CONNECT_LATENCY_MS,
        SIGNAL_TLS_HANDSHAKE_MS,
        SIGNAL_CPU_STEAL_PCT,
    ]


def supported_signals_for_mode(mode: str) -> list[str]:
    """Signal set available under a capability mode.

    Reference: ``pkg/signals/constants.go:74-82``.
    """
    if mode == CAPABILITY_BCC_DEGRADED:
        return list(_BCC_SIGNAL_SET)
    if mode == CAPABILITY_CORE_FULL:
        return list(CPU_SIGNALS)
    return list(ALL_SIGNALS)


def disable_order() -> list[str]:
    """Preferred shed order when overhead exceeds budget."""
    return list(HIGH_COST_DISABLE_ORDER)
