"""Capability-mode autodetection.

Reference: ``pkg/signals/mode.go:9-31`` — BTF presence selects
``core_full`` vs ``bcc_degraded``.  The TPU-native build adds the top
tier: a host with BTF *and* a visible TPU probe surface (``/dev/accel*``
nodes or a resolvable ``libtpu.so``) runs ``tpu_full``.
"""

from __future__ import annotations

import glob
import os

from tpuslo.signals import constants as sig

BTF_PATH = "/sys/kernel/btf/vmlinux"
DEFAULT_ACCEL_GLOB = "/dev/accel*"
DEFAULT_LIBTPU_CANDIDATES = (
    "/usr/lib/libtpu.so",
    "/lib/libtpu.so",
    "/usr/local/lib/libtpu.so",
)


def has_btf(btf_path: str = BTF_PATH) -> bool:
    return os.path.exists(btf_path)


def find_libtpu(env: dict[str, str] | None = None) -> str:
    """Best-effort libtpu.so discovery (env override, then well-known paths)."""
    env = env if env is not None else dict(os.environ)
    override = env.get("TPU_LIBRARY_PATH", "")
    if override and os.path.exists(override):
        return override
    for candidate in DEFAULT_LIBTPU_CANDIDATES:
        if os.path.exists(candidate):
            return candidate
    return ""


def has_tpu_surface(
    accel_glob: str = DEFAULT_ACCEL_GLOB, env: dict[str, str] | None = None
) -> bool:
    return bool(glob.glob(accel_glob)) or bool(find_libtpu(env))


def detect_capability_mode(
    btf_path: str = BTF_PATH,
    accel_glob: str = DEFAULT_ACCEL_GLOB,
    env: dict[str, str] | None = None,
) -> str:
    """Autodetect the richest supported capability mode for this host."""
    if not has_btf(btf_path):
        return sig.CAPABILITY_BCC_DEGRADED
    if has_tpu_surface(accel_glob, env):
        return sig.CAPABILITY_TPU_FULL
    return sig.CAPABILITY_CORE_FULL


def parse_capability_mode(raw: str) -> str:
    """Parse a user-supplied mode; ``auto``/empty triggers detection."""
    mode = (raw or "auto").strip().lower()
    if mode == "auto":
        return detect_capability_mode()
    if mode not in sig.CAPABILITY_MODES:
        raise ValueError(
            f"unsupported capability mode {raw!r}; "
            f"expected one of {', '.join(sig.CAPABILITY_MODES)} or 'auto'"
        )
    return mode
