"""Low-watermark admission for bounded out-of-order event streams.

Streaming-systems discipline (the Flink/Beam watermark, applied to
probe events): the stream's *watermark* trails the maximum event
timestamp seen by an allowed-lateness bound.  Events at or above the
watermark are admitted in (bounded) order; events below it are **late**
— not dropped, but flagged so the caller can route them to a
low-confidence re-match pass (``tpuslo.ingest.gate.rematch_late``)
instead of letting a stale timestamp silently win a full-confidence
window join.
"""

from __future__ import annotations

from typing import Any

DEFAULT_LATENESS_NS = 2_000_000_000  # matcher's global window (2 s)


class Watermark:
    """Tracks ``max(ts) - lateness`` over a monotone-ish event stream."""

    def __init__(self, lateness_ns: int = DEFAULT_LATENESS_NS):
        self.lateness_ns = max(0, lateness_ns)
        self._max_ts = 0
        self.admitted = 0
        self.late = 0

    @property
    def watermark_ns(self) -> int:
        """Current low watermark (0 until the first event)."""
        if self._max_ts == 0:
            return 0
        return self._max_ts - self.lateness_ns

    def lag_ns(self, ts_unix_nano: int) -> int:
        """How far behind the stream head a timestamp sits (>= 0)."""
        return max(0, self._max_ts - ts_unix_nano)

    def admit(self, ts_unix_nano: int) -> bool:
        """Advance the watermark; True = in order (within lateness)."""
        if ts_unix_nano >= self._max_ts:
            self._max_ts = ts_unix_nano
            self.admitted += 1
            return True
        if ts_unix_nano >= self.watermark_ns:
            self.admitted += 1
            return True
        self.late += 1
        return False

    # ---- snapshot hooks (tpuslo.runtime.StateStore) -------------------

    def export_state(self) -> dict[str, Any]:
        return {
            "max_ts": self._max_ts,
            "admitted": self.admitted,
            "late": self.late,
        }

    def restore_state(self, state: dict[str, Any]) -> None:
        """Resume the watermark where the previous incarnation left it.

        Only moves forward: a restored head behind live traffic (the
        snapshot predates events already seen this run) must not drag
        the watermark backwards and re-admit stale history.
        """
        self._max_ts = max(self._max_ts, int(state.get("max_ts", 0)))
        self.admitted += int(state.get("admitted", 0))
        self.late += int(state.get("late", 0))
