"""Hardened telemetry ingest: the gate in front of correlation.

Real DaemonSet telemetry arrives skewed, reordered, duplicated and
occasionally corrupt — exactly the failure modes ARGUS and CrossTrace
identify as the dominant source of cross-host mis-joins (PAPERS.md).
``TelemetryGate`` sits between raw probe-event streams and the
consumers that join them (``match_batch``, ``SliceJoiner.add_all``,
attribution reconstruction) and makes the path degrade gracefully:

* event-id **dedup** over a bounded LRU window,
* malformed-event **quarantine** to a capped JSONL spool with reason
  classes (reusing the PR 1 fast-path validator's outcome),
* per-host **clock-skew estimation** from overlapping collective
  launch groups, with timestamp correction,
* a **watermark** that admits bounded out-of-order events and routes
  late arrivals to a low-confidence re-match pass instead of dropping
  them.
"""

from tpuslo.ingest.gate import (
    ADMITTED,
    DUPLICATE,
    LATE,
    LATE_CONFIDENCE_CAP,
    QUARANTINED,
    GateBatch,
    GateConfig,
    GateObserver,
    LateEvent,
    TelemetryGate,
    rematch_late,
)
from tpuslo.ingest.quarantine import Quarantine
from tpuslo.ingest.skew import ClockSkewEstimator
from tpuslo.ingest.watermark import Watermark

__all__ = [
    "ADMITTED",
    "DUPLICATE",
    "LATE",
    "LATE_CONFIDENCE_CAP",
    "QUARANTINED",
    "GateBatch",
    "GateConfig",
    "GateObserver",
    "LateEvent",
    "TelemetryGate",
    "rematch_late",
    "Quarantine",
    "ClockSkewEstimator",
    "Watermark",
]
