"""TelemetryGate: the hardened admission point for probe-event streams.

Sits in front of every consumer that joins raw telemetry
(``match_batch``, ``SliceJoiner.add_all``, attribution reconstruction)
and applies, in order:

1. **Structural validation** — the PR 1 fast-path validator's
   "definitely valid / jsonschema fallback / reject" outcome
   (:func:`tpuslo.schema.fastpath.validate_probe_payload`).  Rejects
   are quarantined with a reason class, never silently dropped.
2. **Deduplication** — at-least-once delivery (the spool replay
   contract, retransmitting exporters) means exact duplicates are
   normal; a bounded LRU window of event identities absorbs them.
3. **Clock-skew correction** — per-node offsets estimated from
   overlapping collective launch groups against the coordinator host
   (:class:`tpuslo.ingest.skew.ClockSkewEstimator`); admitted events
   get their ``ts_unix_nano`` corrected onto the coordinator's clock.
4. **Watermark admission** — bounded out-of-order events are admitted;
   events behind the low watermark are *late*: still returned (with
   their lag) so the caller can route them through
   :func:`rematch_late`, which caps correlation confidence below the
   enrichment threshold unless a timestamp re-check passes.

The gate never mutates caller-owned dicts: corrected events are
shallow copies with a new ``ts_unix_nano``.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Iterable

from tpuslo.correlation.matcher import (
    DEFAULT_WINDOW_MS,
    BatchMatch,
    Decision,
    SignalRef,
    SpanRef,
    match_batch,
)
from tpuslo.ingest.quarantine import (
    DEFAULT_MAX_AGE_S,
    DEFAULT_MAX_BYTES,
    Quarantine,
)
from tpuslo.ingest.skew import (
    DEFAULT_COORDINATOR_HOST,
    DEFAULT_MIN_SAMPLES,
    ClockSkewEstimator,
)
from tpuslo.ingest.watermark import Watermark
from tpuslo.metrics.rejections import REJECTION_COUNTERS
from tpuslo.schema.fastpath import (
    REJECT_BAD_FIELD_TYPE,
    REJECT_MISSING_FIELD,
    REJECT_NOT_OBJECT,
    REJECT_SCHEMA,
    classify_probe_payload_reject,
    validate_probe_payload,
)
from tpuslo.signals.constants import (
    SIGNAL_DCN_TRANSFER_MS,
    SIGNAL_ICI_COLLECTIVE_MS,
)

# Outcome labels for admit().
ADMITTED = "admitted"
DUPLICATE = "duplicate"
QUARANTINED = "quarantined"
LATE = "late"

# Quarantine reason classes (defined beside the fast-path rules they
# mirror — tpuslo/schema/fastpath.py — so the two cannot drift apart
# unreviewed).
REASON_NOT_OBJECT = REJECT_NOT_OBJECT
REASON_MISSING_FIELD = REJECT_MISSING_FIELD
REASON_BAD_FIELD_TYPE = REJECT_BAD_FIELD_TYPE
REASON_SCHEMA_REJECT = REJECT_SCHEMA

# Confidence ceiling for late-admitted events that fail the timestamp
# re-check: strictly below the 0.70 enrichment threshold, so a stale
# or id-reused event can never silently enrich a span.
LATE_CONFIDENCE_CAP = 0.65

# Signals whose completion is a cross-host synchronization point —
# the only timestamps the skew estimator may learn from.
_SYNC_SIGNALS = frozenset({SIGNAL_ICI_COLLECTIVE_MS, SIGNAL_DCN_TRANSFER_MS})

@dataclass
class GateConfig:
    """Knobs for one :class:`TelemetryGate` (config: ``ingest:``)."""

    dedup_window: int = 4096
    watermark_lateness_ms: int = DEFAULT_WINDOW_MS
    coordinator_host: int = DEFAULT_COORDINATOR_HOST
    min_skew_samples: int = DEFAULT_MIN_SAMPLES
    skew_correction: bool = True
    quarantine_dir: str = ""
    quarantine_max_bytes: int = DEFAULT_MAX_BYTES
    quarantine_max_age_s: float = DEFAULT_MAX_AGE_S


class GateObserver:
    """No-op observer; the agent bridges these to Prometheus."""

    def admitted(self) -> None: ...

    def duplicate(self) -> None: ...

    def quarantined(self, reason: str) -> None: ...

    def late(self, lag_ns: int) -> None: ...

    def skew_offsets(self, offsets_ms: dict[str, float]) -> None: ...

    def watermark_lag_ms(self, lag_ms: float) -> None: ...


@dataclass
class LateEvent:
    """One watermark-late event plus how far behind the head it was."""

    event: dict[str, Any]
    lag_ns: int


@dataclass
class GateBatch:
    """Outcome of one ``admit_all`` call."""

    admitted: list[dict[str, Any]] = field(default_factory=list)
    late: list[LateEvent] = field(default_factory=list)

    def all_events(self) -> list[dict[str, Any]]:
        """Admitted plus late, in admission order within each class."""
        return self.admitted + [entry.event for entry in self.late]


def _event_key(event: dict[str, Any]) -> tuple:
    """Stable identity for dedup.

    Probe events carry no explicit event id (that's an SLOEvent
    field), so identity is the full natural key: an exact duplicate —
    spool replay, exporter retransmit, chaos dup — reproduces every
    component; two genuinely distinct events differ in at least one.
    """
    tpu = event.get("tpu")
    tpu = tpu if isinstance(tpu, dict) else {}
    return (
        event.get("ts_unix_nano"),
        event.get("signal"),
        event.get("node"),
        event.get("pod"),
        event.get("pid"),
        event.get("tid"),
        event.get("value"),
        event.get("trace_id", ""),
        tpu.get("host_index", -1),
        tpu.get("launch_id", -1),
        tpu.get("ici_link", -1),
    )


def _key_digest(key: tuple) -> int:
    """Stable 64-bit digest of a dedup key, portable across processes.

    ``hash()`` is salted per interpreter (PYTHONHASHSEED), so the
    snapshot carries blake2b digests instead: a restarted agent must
    recognize the pre-crash window's identities, and 64 bits keeps the
    collision odds negligible at window sizes (4096² / 2⁶⁵ ≈ 1e-12).
    """
    h = hashlib.blake2b(repr(key).encode("utf-8"), digest_size=8)
    return int.from_bytes(h.digest(), "big")


class TelemetryGate:
    """Validation → dedup → skew correction → watermark, with stats."""

    def __init__(
        self,
        config: GateConfig | None = None,
        quarantine: Quarantine | None = None,
        observer: GateObserver | None = None,
    ):
        self.config = config or GateConfig()
        if quarantine is None and self.config.quarantine_dir:
            quarantine = Quarantine(
                self.config.quarantine_dir,
                max_bytes=self.config.quarantine_max_bytes,
                max_age_s=self.config.quarantine_max_age_s,
            )
        self.quarantine = quarantine
        self._observer = observer or GateObserver()
        self._dedup: OrderedDict[tuple, None] = OrderedDict()
        self._dedup_window = max(1, self.config.dedup_window)
        # Digests restored from a pre-crash snapshot: identities seen
        # by the previous incarnation.  Checked only while non-empty,
        # so the steady-state hot path never pays the digest cost.
        # A dict (insertion-ordered) rather than a set: re-export after
        # a second crash must truncate oldest-first, like the LRU.
        # Dropped wholesale once a full window of live identities has
        # accumulated — by then the LRU itself covers everything the
        # window semantics promise, and the hot path stops paying the
        # per-event digest cost.
        self._restored_digests: dict[int, None] = {}
        self._admissions_since_restore = 0
        self.skew = ClockSkewEstimator(
            coordinator_host=self.config.coordinator_host,
            min_samples=self.config.min_skew_samples,
        )
        self.watermark = Watermark(
            lateness_ns=self.config.watermark_lateness_ms * 1_000_000
        )
        self._observed_groups = 0
        self.admitted = 0
        self.duplicates = 0
        self.quarantined = 0
        self.quarantined_by_reason: dict[str, int] = {}
        self.late_admitted = 0
        self.skew_corrected = 0
        self.last_lag_ns = 0

    # ---- admission ----------------------------------------------------

    def admit(
        self, event: dict[str, Any]
    ) -> tuple[str, dict[str, Any] | None]:
        """Gate one raw probe-event dict.

        Returns ``(outcome, event)`` where outcome is one of
        :data:`ADMITTED` / :data:`LATE` (event is the possibly
        skew-corrected copy) or :data:`DUPLICATE` / :data:`QUARANTINED`
        (event is None).
        """
        if not validate_probe_payload(event):
            reason = classify_probe_payload_reject(event)
            self.quarantined += 1
            self.quarantined_by_reason[reason] = (
                self.quarantined_by_reason.get(reason, 0) + 1
            )
            REJECTION_COUNTERS.note("ingest_gate", reason)
            if self.quarantine is not None:
                self.quarantine.put(event, reason)
            self._observer.quarantined(reason)
            return QUARANTINED, None

        key = _event_key(event)
        if key in self._dedup:
            self._dedup.move_to_end(key)
            self.duplicates += 1
            self._observer.duplicate()
            return DUPLICATE, None
        if self._restored_digests:
            if _key_digest(key) in self._restored_digests:
                # Seen by the pre-crash incarnation: a spool replay or
                # re-emitted cycle crossing the restart boundary.
                self.duplicates += 1
                self._observer.duplicate()
                return DUPLICATE, None
            self._admissions_since_restore += 1
            if self._admissions_since_restore >= self._dedup_window:
                # The live LRU now spans a full window: the inherited
                # digests have aged out of the dedup contract, and the
                # hot path stops paying for them.
                self._restored_digests.clear()
        self._dedup[key] = None
        if len(self._dedup) > self._dedup_window:
            self._dedup.popitem(last=False)

        ts = int(event["ts_unix_nano"])
        if self.config.skew_correction:
            if event.get("signal") in _SYNC_SIGNALS:
                self.skew.observe(event)
                if self.skew.groups_observed != self._observed_groups:
                    # New offset evidence landed: refresh the gauges on
                    # the per-event path too (ring mode never batches).
                    self._observed_groups = self.skew.groups_observed
                    self._observer.skew_offsets(self.skew.offsets_ms())
            corrected = self.skew.correct(str(event.get("node", "")), ts)
            if corrected != ts:
                event = {**event, "ts_unix_nano": corrected}
                ts = corrected
                self.skew_corrected += 1

        in_order = self.watermark.admit(ts)
        lag = self.watermark.lag_ns(ts)
        self.last_lag_ns = lag
        self._observer.watermark_lag_ms(lag / 1e6)
        if in_order:
            self.admitted += 1
            self._observer.admitted()
            return ADMITTED, event
        self.late_admitted += 1
        self._observer.late(lag)
        return LATE, event

    def admit_all(self, events: Iterable[dict[str, Any]]) -> GateBatch:
        """Gate a stream; duplicates/quarantined are consumed here."""
        batch = GateBatch()
        for event in events:
            outcome, gated = self.admit(event)
            if outcome == ADMITTED:
                batch.admitted.append(gated)
            elif outcome == LATE:
                batch.late.append(LateEvent(gated, self.last_lag_ns))
        self._observer.skew_offsets(self.skew.offsets_ms())
        return batch

    # ---- reporting ----------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        return {
            "admitted": self.admitted,
            "duplicates": self.duplicates,
            "quarantined": self.quarantined,
            "quarantined_by_reason": dict(
                sorted(self.quarantined_by_reason.items())
            ),
            "late_admitted": self.late_admitted,
            "skew_corrected": self.skew_corrected,
            "skew_offsets_ms": {
                node: round(ms, 3)
                for node, ms in self.skew.offsets_ms().items()
            },
            "watermark_ns": self.watermark.watermark_ns,
        }

    def close(self) -> None:
        if self.quarantine is not None:
            self.quarantine.close()

    # ---- snapshot hooks (tpuslo.runtime.StateStore) -------------------

    def export_state(self) -> dict[str, Any]:
        """Compact restartable gate state: dedup digest + skew + head.

        The dedup window is exported as 64-bit digests (not full keys):
        ~32 KB for the default 4096-entry window, enough for a restarted
        gate to reject every duplicate from the pre-crash window.
        """
        digests = [_key_digest(key) for key in self._dedup]
        if self._restored_digests:
            # Keep inherited identities that are still inside one
            # window's worth of history; both lists run oldest-first,
            # so the truncation evicts oldest-first like the LRU.
            merged = list(self._restored_digests) + digests
            digests = merged[-self._dedup_window:]
        # Deliberately no gate counters: they are per-process
        # operational stats (Prometheus owns their lifetime), and this
        # payload is serialized + fsynced on the snapshot hot path.
        return {
            "dedup_digests": digests,
            "watermark": self.watermark.export_state(),
            "skew": self.skew.export_state(),
        }

    def restore_state(self, state: dict[str, Any]) -> None:
        digests = state.get("dedup_digests") or []
        for digest in digests[-self._dedup_window:]:
            self._restored_digests[int(digest)] = None
        if isinstance(state.get("watermark"), dict):
            self.watermark.restore_state(state["watermark"])
        if isinstance(state.get("skew"), dict):
            self.skew.restore_state(state["skew"])


def rematch_late(
    spans: list[SpanRef],
    late: list[LateEvent],
    window_ms: int = 0,
    cap: float = LATE_CONFIDENCE_CAP,
    max_lag_ns: int | None = None,
) -> list[BatchMatch]:
    """Low-confidence re-match pass for watermark-late events.

    Late events still correlate — dropping them is how evidence of the
    very incident that delayed them gets lost — but their timestamps
    are suspect by construction (the producer clock or the delivery
    path already misbehaved).  The **timestamp re-check** restores full
    tier confidence only when both sides carry timestamps, the pairwise
    window still holds on the (skew-corrected) values, and the event's
    watermark lag is at most one correlation window *beyond* the
    admission lateness (2x the window by default — a late event lags
    more than the lateness bound by definition, so the re-check bound
    must sit beyond it); anything staler is indistinguishable from
    trace/launch id reuse after a restart and is capped below the
    enrichment threshold.
    """
    if max_lag_ns is None:
        max_lag_ns = (
            2 * (window_ms if window_ms > 0 else DEFAULT_WINDOW_MS)
            * 1_000_000
        )
    signals = [SignalRef.from_probe_dict(entry.event) for entry in late]
    out: list[BatchMatch] = []
    for result in match_batch(spans, signals, window_ms):
        decision = result.decision
        if decision.matched and result.signal_index >= 0:
            span = spans[result.span_index]
            signal = signals[result.signal_index]
            recheck_ok = (
                span.timestamp is not None
                and signal.timestamp is not None
                and late[result.signal_index].lag_ns <= max_lag_ns
            )
            if not recheck_ok and decision.confidence > cap:
                result = BatchMatch(
                    result.span_index,
                    result.signal_index,
                    Decision(True, cap, decision.tier),
                )
        out.append(result)
    return out
