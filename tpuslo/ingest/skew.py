"""Per-host clock-skew estimation from overlapping launch groups.

A multi-host TPU pod gives the ingest layer a free clock reference:
every cross-chip collective is a synchronization point, and **all
participating hosts finish it together** (the collective completes when
the last input arrives and the result is exchanged — the same physics
``SliceJoiner`` uses for straggler attribution).  So for one
``(slice_id, program_id, launch_id)`` group, the *finish* timestamps
recorded by different hosts should agree up to jitter; a systematic
per-host difference against the coordinator host is clock skew, not
physics.

Offsets are estimated from collective events (they carry the
launch-group identity) but keyed by **node**, because skew is a
property of the host's clock: once ``node-3`` is known to run 180 ms
ahead of the coordinator, every event it emits — DNS latency and HBM
stalls included — gets the same correction.

The estimator keeps a sliding window of per-launch offsets per node and
reports the **median** (robust to stragglers: a late-entering host
observes a short wall time but still finishes with everyone else, so
launch-group finish skew stays small next to a drifting clock).  A
sliding window rather than a global median lets the estimate track
slow drift.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from statistics import median
from typing import Any

DEFAULT_COORDINATOR_HOST = 0
DEFAULT_MIN_SAMPLES = 3
DEFAULT_WINDOW_SAMPLES = 128
# Launch groups awaiting the coordinator's observation; bounded so a
# stream that never delivers the coordinator's view cannot grow state.
_MAX_PENDING_GROUPS = 1024


class ClockSkewEstimator:
    """Median pairwise offset of each node against the coordinator."""

    def __init__(
        self,
        coordinator_host: int = DEFAULT_COORDINATOR_HOST,
        min_samples: int = DEFAULT_MIN_SAMPLES,
        window_samples: int = DEFAULT_WINDOW_SAMPLES,
    ):
        self.coordinator_host = coordinator_host
        self.min_samples = max(1, min_samples)
        self._samples: dict[str, deque[int]] = {}
        self._window = max(self.min_samples, window_samples)
        self.coordinator_node: str = ""
        # group key -> {host_index: (ts_unix_nano, node)}; insertion-
        # ordered so overflow evicts the oldest group first.
        self._pending: OrderedDict[
            tuple[str, str, int], dict[int, tuple[int, str]]
        ] = OrderedDict()
        self.groups_observed = 0
        #: Total offset samples recorded; unlike ``groups_observed``
        #: this only moves when the per-node evidence (and therefore a
        #: possible offset estimate) actually changed — the columnar
        #: gate keys its segment breakpoints on it.
        self.samples_observed = 0

    def observe(self, event: dict[str, Any]) -> None:
        """Feed one probe-event dict; only launch-group members count.

        Events without full ``(slice_id, program_id, launch_id,
        host_index)`` identity are ignored — skew evidence must be an
        exact-identity join, never a timestamp guess.  The caller is
        expected to feed only synchronization-point signals
        (collective / cross-slice transfer completions); other
        launch-stamped events do not finish simultaneously across
        hosts.
        """
        tpu = event.get("tpu")
        if not isinstance(tpu, dict):
            return
        try:
            host = int(tpu.get("host_index", -1))
            launch_id = int(tpu.get("launch_id", -1))
            ts = int(event.get("ts_unix_nano", 0))
        except (TypeError, ValueError):
            return
        slice_id = tpu.get("slice_id", "")
        program_id = tpu.get("program_id", "")
        node = event.get("node", "")
        if host < 0 or launch_id < 0 or not slice_id or not node or ts <= 0:
            return
        self.observe_group(
            str(slice_id), str(program_id), launch_id, host, str(node), ts
        )

    def observe_group(
        self,
        slice_id: str,
        program_id: str,
        launch_id: int,
        host: int,
        node: str,
        ts: int,
    ) -> None:
        """Guard-free core of :meth:`observe` for pre-validated rows.

        The columnar gate applies ``observe``'s guard clauses as one
        vectorized mask and feeds the surviving rows here directly —
        same state transitions, no per-event dict round trip.
        """
        if host == self.coordinator_host:
            self.coordinator_node = node

        key = (slice_id, program_id, launch_id)
        group = self._pending.get(key)
        if group is None:
            if len(self._pending) >= _MAX_PENDING_GROUPS:
                self._pending.popitem(last=False)
            group = self._pending[key] = {}
        group[host] = (ts, str(node))

        coord = group.get(self.coordinator_host)
        if coord is None:
            return
        coord_ts = coord[0]
        # Coordinator view present: every other host in the group
        # yields one offset sample (its clock minus the coordinator's).
        for other, (other_ts, other_node) in group.items():
            if other == self.coordinator_host:
                continue
            samples = self._samples.get(other_node)
            if samples is None:
                samples = self._samples[other_node] = deque(
                    maxlen=self._window
                )
            samples.append(other_ts - coord_ts)
            self.samples_observed += 1
        self.groups_observed += 1
        # Re-keep only the coordinator entry: late host observations of
        # the same launch still pair against it without re-sampling the
        # hosts already seen.
        self._pending[key] = {self.coordinator_host: coord}

    def offset_ns(self, node: str) -> int:
        """Estimated clock offset of ``node`` vs the coordinator.

        Zero until ``min_samples`` launch groups have paired the node
        with the coordinator — under-evidenced correction is worse than
        none.
        """
        if node == self.coordinator_node:
            return 0
        samples = self._samples.get(node)
        if samples is None or len(samples) < self.min_samples:
            return 0
        return int(median(samples))

    def correct(self, node: str, ts_unix_nano: int) -> int:
        """Skew-correct one timestamp onto the coordinator's clock."""
        return ts_unix_nano - self.offset_ns(node)

    def offsets_ms(self) -> dict[str, float]:
        """Current per-node offset estimates in milliseconds."""
        return {
            node: self.offset_ns(node) / 1e6
            for node in sorted(self._samples)
            if len(self._samples[node]) >= self.min_samples
        }

    # ---- snapshot hooks (tpuslo.runtime.StateStore) -------------------

    def export_state(self) -> dict[str, Any]:
        """Per-node offset evidence, portable across restarts.

        Pending launch groups are deliberately not exported: they are
        sub-second joins against in-flight collectives, stale by the
        time any restart completes.  The sample windows are what make
        a restarted agent correct timestamps from its first event.
        """
        return {
            "coordinator_node": self.coordinator_node,
            "groups_observed": self.groups_observed,
            "samples": {
                node: list(samples)
                for node, samples in self._samples.items()
            },
        }

    def restore_state(self, state: dict[str, Any]) -> None:
        self.coordinator_node = str(
            state.get("coordinator_node", self.coordinator_node)
        )
        self.groups_observed += int(state.get("groups_observed", 0))
        restored = sum(
            len(v) for v in (state.get("samples") or {}).values()
        )
        self.samples_observed += int(restored)
        for node, values in (state.get("samples") or {}).items():
            samples = self._samples.get(str(node))
            if samples is None:
                samples = self._samples[str(node)] = deque(
                    maxlen=self._window
                )
            # Restored (older) evidence first, so live samples keep
            # evicting it as the window refills.
            fresh = list(samples)
            samples.clear()
            for value in values:
                samples.append(int(value))
            for value in fresh:
                samples.append(value)
