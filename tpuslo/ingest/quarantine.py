"""Reason-classed quarantine for malformed probe events.

Rejected events are evidence, not garbage: a corrupt-event storm is a
diagnosable incident (a broken producer, a torn ring buffer, an
attacker), and triage needs the actual bytes.  Each quarantined event
is appended as one JSONL record ``{"reason": ..., "event": ...}``.

Storage rides :class:`tpuslo.delivery.spool.DiskSpool` — the same
segmented, size/age-capped WAL the delivery layer uses — so a storm
truncates oldest segments instead of filling the disk, with truncation
counted (never silent).
"""

from __future__ import annotations

import os
from typing import Any, Callable

from tpuslo.delivery.spool import DiskSpool

DEFAULT_MAX_BYTES = 8 * 1024 * 1024
DEFAULT_MAX_AGE_S = 24 * 3600.0
_SEGMENT_BYTES = 64 * 1024


class Quarantine:
    """Capped JSONL quarantine directory with per-reason accounting."""

    def __init__(
        self,
        directory: str | os.PathLike,
        max_bytes: int = DEFAULT_MAX_BYTES,
        max_age_s: float = DEFAULT_MAX_AGE_S,
        on_truncate: Callable[[int], None] | None = None,
    ):
        self._spool = DiskSpool(
            directory,
            segment_max_bytes=_SEGMENT_BYTES,
            max_bytes=max_bytes,
            max_age_s=max_age_s,
            on_truncate=self._note_truncated,
        )
        self._on_truncate = on_truncate
        self.by_reason: dict[str, int] = {}
        self.truncated = 0

    def _note_truncated(self, records: int) -> None:
        self.truncated += records
        if self._on_truncate is not None:
            self._on_truncate(records)

    def put(self, event: Any, reason: str) -> None:
        """Quarantine one rejected event under a reason class.

        Unserializable payloads are stored as their ``repr`` — the
        quarantine must never raise back into the ingest hot path for
        the very malformedness it exists to capture.
        """
        self.by_reason[reason] = self.by_reason.get(reason, 0) + 1
        try:
            try:
                self._spool.append({"reason": reason, "event": event})
            except (TypeError, ValueError):
                self._spool.append(
                    {"reason": reason, "event_repr": repr(event)}
                )
        except OSError:
            # Disk trouble while quarantining (either append): the
            # count above already recorded the rejection; losing the
            # body is survivable.
            pass

    def pending_bytes(self) -> int:
        return self._spool.pending_bytes()

    def drain(self, handler: Callable[[dict[str, Any]], None]) -> int:
        """Replay quarantined records oldest-first (triage tooling)."""
        return self._spool.drain(handler)

    def close(self) -> None:
        self._spool.close()
