"""tpuslo — TPU-native SLO observability and fault-attribution toolkit.

A three-stage pipeline for LLM inference services on TPU-VM hosts:

1. **Collection** — low-level signals per node: the nine classic kernel
   signals (DNS latency, TCP retransmits, runqueue delay, connect latency,
   TLS handshake, CPU steal, memory reclaim, disk I/O, syscall latency)
   plus TPU-native probes (uprobes on ``libtpu.so``, kprobes on the
   ``/dev/accel*`` driver) capturing XLA-compile latency, HBM-allocation
   stalls, ICI link retries, collective latency, and host-offload stalls.
2. **Correlation** — tiered confidence join of signals to JAX/XLA
   OpenTelemetry spans (trace-id exact, XLA launch-id, pod+pid, pod+conn,
   slice+host, service+node).
3. **Attribution** — naive-Bayes posterior over twelve fault domains
   (network, compute, provider, retrieval + TPU domains ICI / HBM /
   XLA-compile / host-offload) producing ranked fault hypotheses with
   confusion-matrix evaluation and statistical release gates.

Capability parity with the reference toolkit
(ogulcanaydogan/llm-slo-ebpf-toolkit) is documented per-module via
``Reference:`` docstring citations (file:line into /root/reference).
"""

__version__ = "0.4.0"

TOOLKIT_NAME = "tpuslo"
