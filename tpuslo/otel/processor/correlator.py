"""Batch span-enrichment processor: joins probe signals onto JAX/XLA spans.

Reference: ``pkg/otel/processor/ebpfcorrelator/{correlator,processor}.go``
— confidence filter, join-fanout cap 3, signal→semconv attribute
mapping, retrieval decomposition, and per-batch debug stats.  The
TPU-native build adds TPU signal attributes and a device-side
decomposition (``llm.tpu.kernel_attributed_ms``) that tells operators
what fraction of a generation span is attributable to TPU-observable
stalls.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from datetime import datetime

from tpuslo import semconv
from tpuslo.correlation.matcher import (
    DEFAULT_ENRICHMENT_THRESHOLD,
    DEFAULT_WINDOW_MS,
    Decision,
    SignalRef,
    SpanRef,
    match,
)

DEFAULT_MAX_JOIN_FANOUT = 3


@dataclass
class DebugStats:
    """Non-enriched correlation outcomes, for diagnostics."""

    unmatched: int = 0
    low_confidence: int = 0
    fanout_dropped: int = 0
    unsupported_type: int = 0

    def merge(self, other: "DebugStats") -> "DebugStats":
        return DebugStats(
            unmatched=self.unmatched + other.unmatched,
            low_confidence=self.low_confidence + other.low_confidence,
            fanout_dropped=self.fanout_dropped + other.fanout_dropped,
            unsupported_type=self.unsupported_type + other.unsupported_type,
        )


@dataclass
class Candidate:
    signal: SignalRef
    decision: Decision


@dataclass
class EnrichmentResult:
    attributes: dict[str, float]
    candidates: list[Candidate]
    debug: DebugStats


@dataclass
class SpanRecord:
    """Lightweight span representation for batch correlation."""

    trace_id: str = ""
    span_id: str = ""
    service: str = ""
    node: str = ""
    pod: str = ""
    pid: int = 0
    conn_tuple: str = ""
    timestamp: datetime | None = None
    slice_id: str = ""
    host_index: int = -1
    program_id: str = ""
    launch_id: int = -1
    attributes: dict[str, float] = field(default_factory=dict)

    def to_span_ref(self) -> SpanRef:
        return SpanRef(
            timestamp=self.timestamp,
            trace_id=self.trace_id,
            service=self.service,
            node=self.node,
            pod=self.pod,
            pid=self.pid,
            conn_tuple=self.conn_tuple,
            slice_id=self.slice_id,
            host_index=self.host_index,
            program_id=self.program_id,
            launch_id=self.launch_id,
        )


@dataclass
class ProcessedBatch:
    spans: list[SpanRecord]
    debug: DebugStats


class Correlator:
    """Span enrichment with confidence filtering and fanout capping."""

    def __init__(
        self,
        window_ms: int = DEFAULT_WINDOW_MS,
        enrichment_threshold: float = DEFAULT_ENRICHMENT_THRESHOLD,
        max_join_fanout: int = DEFAULT_MAX_JOIN_FANOUT,
    ):
        self.window_ms = window_ms
        self.enrichment_threshold = enrichment_threshold
        self.max_join_fanout = max_join_fanout

    def enrich_attributes(
        self,
        base: dict[str, float] | None,
        span: SpanRef,
        signals: list[SignalRef],
    ) -> EnrichmentResult:
        """Enrich one span from a signal set."""
        threshold = (
            self.enrichment_threshold
            if self.enrichment_threshold > 0
            else DEFAULT_ENRICHMENT_THRESHOLD
        )
        fanout = self.max_join_fanout if self.max_join_fanout > 0 else 3

        out = dict(base or {})
        debug = DebugStats()
        candidates: list[Candidate] = []

        for signal in signals:
            if signal.signal not in semconv.SIGNAL_ATTR_KEYS:
                debug.unsupported_type += 1
                continue
            decision = match(span, signal, self.window_ms)
            if not decision.matched:
                debug.unmatched += 1
                continue
            if decision.confidence < threshold:
                debug.low_confidence += 1
                continue
            candidates.append(Candidate(signal, decision))

        def sort_key(c: Candidate):
            distance = (
                abs((span.timestamp - c.signal.timestamp).total_seconds())
                if span.timestamp and c.signal.timestamp
                else float("inf")
            )
            return (-c.decision.confidence, distance)

        candidates.sort(key=sort_key)
        if len(candidates) > fanout:
            debug.fanout_dropped = len(candidates) - fanout
            candidates = candidates[:fanout]

        max_confidence = 0.0
        best_tier = ""
        for candidate in candidates:
            attr = semconv.SIGNAL_ATTR_KEYS[candidate.signal.signal]
            if attr not in out or candidate.signal.value > out[attr]:
                out[attr] = candidate.signal.value
            if candidate.decision.confidence > max_confidence:
                max_confidence = candidate.decision.confidence
                best_tier = candidate.decision.tier
        if max_confidence > 0:
            out[semconv.ATTR_CORRELATION_CONF] = max_confidence
            _ = best_tier  # tier exposed via candidates; attrs stay numeric

        return EnrichmentResult(out, candidates, debug)

    def enrich_dns_attributes(
        self,
        base: dict[str, float] | None,
        span: SpanRef,
        signal: SignalRef,
    ) -> tuple[dict[str, float], Decision]:
        """Single-signal convenience wrapper used by the demo service."""
        result = self.enrich_attributes(base, span, [signal])
        if not result.candidates:
            return result.attributes, Decision()
        return result.attributes, result.candidates[0].decision

    def process_batch(
        self, spans: list[SpanRecord], signals: list[SignalRef]
    ) -> ProcessedBatch:
        """Apply enrichment + decompositions over a span batch."""
        out = ProcessedBatch(spans=[], debug=DebugStats())
        for record in spans:
            enriched = self.enrich_attributes(
                record.attributes, record.to_span_ref(), signals
            )
            decompose_retrieval(enriched.attributes)
            decompose_tpu(enriched.attributes)
            record.attributes = enriched.attributes
            out.spans.append(record)
            out.debug = out.debug.merge(enriched.debug)
        return out


def decompose_retrieval(attrs: dict[str, float]) -> float:
    """Sum kernel-attributed retrieval components (DNS+connect+TLS).

    Reference: ``ebpfcorrelator/correlator.go:179-194``.
    """
    total = sum(
        attrs.get(key, 0.0)
        for key in (
            semconv.ATTR_DNS_LATENCY_MS,
            semconv.ATTR_CONNECT_LATENCY_MS,
            semconv.ATTR_TLS_HANDSHAKE_MS,
        )
    )
    if total > 0:
        attrs[semconv.ATTR_RETRIEVAL_KERNEL_MS] = total
    return total


def decompose_tpu(attrs: dict[str, float]) -> float:
    """Sum TPU-attributed generation stall components.

    Compile wait + HBM allocation stall + collective latency + host
    offload stall — the device-side analogue of retrieval
    decomposition for the generation span.
    """
    total = sum(
        attrs.get(key, 0.0)
        for key in (
            semconv.ATTR_XLA_COMPILE_MS,
            semconv.ATTR_HBM_ALLOC_STALL_MS,
            semconv.ATTR_ICI_COLLECTIVE_MS,
            semconv.ATTR_HOST_OFFLOAD_STALL_MS,
        )
    )
    if total > 0:
        attrs[semconv.ATTR_TPU_KERNEL_MS] = total
    return total
