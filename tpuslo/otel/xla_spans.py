"""xprof/JAX-profiler span source for the correlation engine.

The reference correlates kernel signals against OTel spans exported by
the *instrumented* demo app (`demo/rag-service/main.go:782-820`); spans
exist only where someone added tracing calls.  On TPU there is a better
span source that needs no instrumentation at all: the XLA profiler
(xprof).  ``jax.profiler.trace`` writes a trace-viewer JSON whose
"XLA Modules" lane carries one event per device execution of a compiled
program, named ``<module>(<program fingerprint>)`` with a monotonically
increasing ``run_id`` — precisely the ``program_id``/``launch_id``
identity the ``xla_launch`` correlation tier joins on
(`tpuslo/correlation/matcher.py`), recovered here from the device's own
timeline instead of libtpu uprobes (SURVEY.md §5 "consider xprof/
XLA-dump hooks as the tracing source").

Two caveats the API shapes around:

* trace timestamps are **microseconds relative to profiling start**
  with no wall-clock anchor in the file, so :class:`capture` records
  ``time.time_ns()`` on entry and anchors every span to it;
* the profile directory layout is ``<dir>/plugins/profile/<run>/
  <host>.trace.json.gz`` — one file per host, so multi-host pods get
  per-host span streams that feed the same SliceJoiner/matcher path as
  probe events.
"""

from __future__ import annotations

import bisect
import glob
import gzip
import json
import os
import re
import time
from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import Any, Iterator

from tpuslo.schema import rfc3339

# "jit_train_step(13839021870486437105)" -> module + fingerprint.
_MODULE_RE = re.compile(r"^(?P<module>.+?)\((?P<fingerprint>\d+)\)$")

MODULES_LANE = "XLA Modules"
OPS_LANE = "XLA Ops"


@dataclass
class XLASpan:
    """One device-side execution span recovered from an xprof trace."""

    name: str
    module_name: str = ""
    program_id: str = ""
    launch_id: int = -1
    start_us: float = 0.0
    duration_us: float = 0.0
    device_pid: int = -1
    lane: str = MODULES_LANE
    hlo_category: str = ""
    args: dict[str, Any] = field(default_factory=dict)

    def to_span_ref_dict(
        self,
        anchor_unix_ns: int,
        service: str = "",
        node: str = "",
        slice_id: str = "",
        host_index: int = -1,
    ) -> dict[str, Any]:
        """SpanRef-compatible dict for the correlation matcher."""
        ts_ns = anchor_unix_ns + int(self.start_us * 1_000)
        out: dict[str, Any] = {
            "timestamp": rfc3339(
                datetime.fromtimestamp(ts_ns / 1e9, tz=timezone.utc)
            ),
            "service": service,
            "node": node,
            "program_id": self.program_id,
            "launch_id": self.launch_id,
            "duration_ms": self.duration_us / 1000.0,
            "name": self.module_name or self.name,
        }
        if slice_id:
            out["slice_id"] = slice_id
        if host_index >= 0:
            out["host_index"] = host_index
        return out


def _thread_lanes(events: list[dict[str, Any]]) -> dict[tuple[int, int], str]:
    lanes: dict[tuple[int, int], str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            args = e.get("args") or {}
            lanes[(e.get("pid", -1), e.get("tid", -1))] = args.get("name", "")
    return lanes


def parse_trace_events(
    data: dict[str, Any], include_ops: bool = False
) -> list[XLASpan]:
    """XLA device spans from one trace-viewer JSON document."""
    events = data.get("traceEvents", [])
    lanes = _thread_lanes(events)
    spans: list[XLASpan] = []
    for e in events:
        if e.get("ph") != "X":
            continue
        lane = lanes.get((e.get("pid", -1), e.get("tid", -1)), "")
        if lane != MODULES_LANE and not (include_ops and lane == OPS_LANE):
            continue
        args = e.get("args", {}) or {}
        name = e.get("name", "")
        span = XLASpan(
            name=name,
            start_us=float(e.get("ts", 0.0)),
            duration_us=float(e.get("dur", 0.0)),
            device_pid=int(e.get("pid", -1)),
            lane=lane,
            hlo_category=args.get("hlo_category", ""),
            args=args,
        )
        if lane == MODULES_LANE:
            m = _MODULE_RE.match(name)
            if m:
                span.module_name = m.group("module")
                span.program_id = m.group("fingerprint")
            else:
                span.module_name = name
            try:
                span.launch_id = int(args.get("run_id", -1))
            except (TypeError, ValueError):
                span.launch_id = -1
        spans.append(span)
    spans.sort(key=lambda s: s.start_us)
    return spans


def find_trace_files(log_dir: str) -> list[str]:
    """All per-host trace-viewer files under a profiler log dir, newest
    profile run first, host files sorted within a run."""
    runs = sorted(
        glob.glob(os.path.join(log_dir, "plugins", "profile", "*")),
        key=os.path.basename,
        reverse=True,
    )
    out: list[str] = []
    for run in runs:
        out.extend(sorted(glob.glob(os.path.join(run, "*.trace.json.gz"))))
    return out


def load_trace_file(path: str, include_ops: bool = False) -> list[XLASpan]:
    with gzip.open(path, "rt", encoding="utf-8") as fh:
        return parse_trace_events(json.load(fh), include_ops=include_ops)


def load_latest_trace_by_host(
    log_dir: str, include_ops: bool = False
) -> dict[str, list[XLASpan]]:
    """Newest profile run's spans, keyed by host (trace-file stem).

    Per-host grouping matters on multi-host pods: each host's file has
    its own ``run_id`` counter, so merging hosts would collide the
    exact-identity (program_id, launch_id) join.
    """
    files = find_trace_files(log_dir)
    if not files:
        return {}
    run_dir = os.path.dirname(files[0])
    out: dict[str, list[XLASpan]] = {}
    for path in files:
        if os.path.dirname(path) != run_dir:
            break
        # Strip the fixed suffix only: dotted hostnames must stay
        # distinct or per-host run_id counters would collide.
        host = os.path.basename(path)[: -len(".trace.json.gz")]
        out.setdefault(host, []).extend(
            load_trace_file(path, include_ops=include_ops)
        )
    for spans in out.values():
        spans.sort(key=lambda s: s.start_us)
    return out


def load_latest_trace(log_dir: str, include_ops: bool = False) -> list[XLASpan]:
    """Spans from the newest profile run, all hosts merged time-sorted.

    Use :func:`load_latest_trace_by_host` on multi-host pods — merged
    launch ids are only unique per host file.
    """
    spans: list[XLASpan] = []
    for host_spans in load_latest_trace_by_host(
        log_dir, include_ops=include_ops
    ).values():
        spans.extend(host_spans)
    spans.sort(key=lambda s: s.start_us)
    return spans


COLLECTIVE_MARKERS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)


def is_collective_op(span: XLASpan) -> bool:
    """Does this ops-lane span belong to a cross-chip collective?

    Matches the HLO category first (canonical), falling back to the op
    name so async variants (``all-reduce-start``/``-done``) and fusions
    that keep the collective in their name are caught.
    """
    if span.lane != OPS_LANE:
        return False
    hay = f"{span.hlo_category} {span.name}"
    return any(marker in hay for marker in COLLECTIVE_MARKERS)


def _sum_ops_by_launch(
    spans: list[XLASpan], op_filter: "Callable[[XLASpan], bool]"
) -> tuple[dict[tuple[str, int], float], dict[tuple[str, int], XLASpan]]:
    """Sum filtered ops-lane durations into their enclosing launches.

    Returns ``(totals_ms, anchor_mod)`` keyed by the launch's
    ``(program_id, launch_id)`` identity.  Module launches are grouped
    per device pid: multi-chip hosts run the same launch concurrently on
    every chip, so containment must pair an op with *its own device's*
    module span or op time gets double-counted onto whichever chip
    sorts first.
    """
    mods_by_dev: dict[int, list[XLASpan]] = {}
    for s in spans:
        if s.lane == MODULES_LANE:
            mods_by_dev.setdefault(s.device_pid, []).append(s)
    starts_by_dev: dict[int, list[float]] = {}
    for dev, mods in mods_by_dev.items():
        mods.sort(key=lambda s: s.start_us)
        starts_by_dev[dev] = [m.start_us for m in mods]

    # One signal per launch per host: chips of one host aggregate by
    # the launch's (program_id, launch_id) identity.
    totals: dict[tuple[str, int], float] = {}
    anchor_mod: dict[tuple[str, int], XLASpan] = {}
    for op in spans:
        if op.lane != OPS_LANE or not op_filter(op):
            continue
        mods = mods_by_dev.get(op.device_pid, [])
        idx = bisect.bisect_right(starts_by_dev.get(op.device_pid, []), op.start_us) - 1
        if idx < 0:
            continue
        mod = mods[idx]
        if not op.start_us < mod.start_us + mod.duration_us:
            continue
        if mod.launch_id >= 0:
            key = (mod.program_id, mod.launch_id)
        else:
            # No run_id: key the anonymous launch by its own module
            # span (device + start) so all its ops still sum into one
            # event; without a launch id it cannot merge across chips.
            key = (
                f"{mod.program_id}#anon@{mod.device_pid}:{mod.start_us}",
                -1,
            )
        totals[key] = totals.get(key, 0.0) + op.duration_us / 1000.0
        prior = anchor_mod.get(key)
        if prior is None or mod.start_us < prior.start_us:
            anchor_mod[key] = mod
    return totals, anchor_mod


def _launch_signal_events(
    totals: dict[tuple[str, int], float],
    anchor_mod: dict[tuple[str, int], XLASpan],
    signal: str,
    anchor_unix_ns: int,
    node: str,
    slice_id: str,
    host_index: int,
    namespace: str,
    pod: str,
    chip: str,
) -> list[dict[str, Any]]:
    """Per-launch probe events from aggregated op totals."""
    from tpuslo.signals.generator import signal_status

    out: list[dict[str, Any]] = []
    for key, total_ms in sorted(
        totals.items(), key=lambda kv: anchor_mod[kv[0]].start_us
    ):
        mod = anchor_mod[key]
        tpu: dict[str, Any] = {"chip": chip}
        if slice_id:
            tpu["slice_id"] = slice_id
        if host_index >= 0:
            tpu["host_index"] = host_index
        if mod.program_id:
            tpu["program_id"] = mod.program_id
        if mod.launch_id >= 0:
            tpu["launch_id"] = mod.launch_id
        if mod.module_name:
            tpu["module_name"] = mod.module_name
        out.append(
            {
                "ts_unix_nano": anchor_unix_ns + int(mod.start_us * 1_000),
                "signal": signal,
                "node": node,
                "namespace": namespace,
                "pod": pod or node,
                "container": "xprof",
                "pid": 0,
                "tid": 0,
                "value": round(total_ms, 4),
                "unit": "ms",
                "status": signal_status(signal, total_ms),
                "tpu": tpu,
            }
        )
    return out


def extract_collective_signals(
    spans: list[XLASpan],
    anchor_unix_ns: int,
    node: str = "",
    slice_id: str = "",
    host_index: int = -1,
    namespace: str = "llm-slo",
    pod: str = "",
    chip: str = "accel0",
) -> list[dict[str, Any]]:
    """``ici_collective_latency_ms`` probe events from one host's trace.

    A second, eBPF-free source for the signal the libtpu uprobes
    produce (``ebpf/c/libtpu_uprobes.bpf.c``): each collective op in
    the XLA Ops lane is assigned to its enclosing module execution by
    time containment, and per (module launch) the op durations are
    summed into one event carrying the launch's exact
    ``program_id``/``launch_id`` identity.  The straggler physics of
    `tpuslo/correlation/multihost.py` carries over: punctual hosts
    accumulate wait time *inside* collectives, the late host does not,
    so per-launch totals joined across hosts by SliceJoiner still name
    the straggler.  Requires a trace captured with ``include_ops=True``.
    """
    totals, anchor_mod = _sum_ops_by_launch(spans, is_collective_op)
    return _launch_signal_events(
        totals,
        anchor_mod,
        "ici_collective_latency_ms",
        anchor_unix_ns,
        node,
        slice_id,
        host_index,
        namespace,
        pod,
        chip,
    )


def extract_device_time_signals(
    spans: list[XLASpan],
    anchor_unix_ns: int,
    node: str = "",
    slice_id: str = "",
    host_index: int = -1,
    namespace: str = "llm-slo",
    pod: str = "",
    chip: str = "accel0",
) -> list[dict[str, Any]]:
    """``xla_device_time_ms`` probe events: per-launch device compute time.

    Sums *every* XLA Ops-lane event into its enclosing module launch —
    the single-chip analog of :func:`extract_collective_signals` (which
    filters to collectives and is empty on one chip).  Each event
    carries the launch's exact ``program_id``/``launch_id`` identity, so
    the ``xla_launch`` correlation tier
    (`tpuslo/correlation/matcher.py`) can join it against module-lane
    span refs from the same or another span source — the
    zero-instrumentation per-step attribution feed.  Requires a trace
    captured with ``include_ops=True``.
    """
    totals, anchor_mod = _sum_ops_by_launch(spans, lambda _op: True)
    return _launch_signal_events(
        totals,
        anchor_mod,
        "xla_device_time_ms",
        anchor_unix_ns,
        node,
        slice_id,
        host_index,
        namespace,
        pod,
        chip,
    )


def extract_collective_signals_by_host(
    spans_by_host: dict[str, list[XLASpan]],
    anchor_unix_ns: int,
    identities: dict[str, dict[str, Any]] | None = None,
    slice_id: str = "",
    namespace: str = "llm-slo",
) -> list[dict[str, Any]]:
    """Flat event list over every host, ready for ``SliceJoiner.add_all``.

    ``identities`` maps trace-file stem → ``{"node": ..,
    "host_index": ..}``; hosts default to their stem and list position.
    """
    identities = identities or {}
    out: list[dict[str, Any]] = []
    for pos, (host, spans) in enumerate(sorted(spans_by_host.items())):
        ident = identities.get(host, {})
        out.extend(
            extract_collective_signals(
                spans,
                anchor_unix_ns,
                node=ident.get("node", host),
                slice_id=slice_id,
                host_index=int(ident.get("host_index", pos)),
                namespace=namespace,
            )
        )
    return out


def launch_match_breakdown(
    spans: list[XLASpan],
    compile_events: list[Any] | None = None,
    ledger: Any | None = None,
) -> dict[str, Any]:
    """Explain every module-lane launch that produced no device-time
    signal.

    Historical note: the r02 evidence ran at a 0.556 RAW exact-identity
    join rate with no accounting for the other half of device time.
    That number was never a defect to "fix" upward — helpers and
    anonymous launches legitimately carry no exact identity — it was a
    reporting gap.  The ledger now publishes the raw rate and the
    tiered substantive rate side by side (the continuous profiler
    repeats both per capture window, straight off the same ledger), so
    the headline number can no longer hide the remainder.

    The numbers come from the device-plane ledger
    (:func:`tpuslo.deviceplane.ledger.build_ledger`) — ONE source for
    both the raw and substantive join rates, which ``serving_bench``
    used to derive independently with its own identity loop (the
    split-brain this delegation removes).  Reason classes for launches
    the exact ``(program_id, launch_id)`` join cannot see:

    * ``no_ops_lane`` — the trace has no ops events for that device at
      all (capture ran with ``include_ops=False``, or xprof dropped the
      lane);
    * ``no_contained_ops`` — ops exist on the device but none fall
      inside this launch's window: dispatch-only helper programs
      (scalar converts, argmax glue) execute without any device op
      event — real launches, no device-time denominator;
    * ``ops_assigned_to_overlapping_launch`` — ops inside the window
      summed into a later-starting overlapping launch;
    * ``ops_on_split_lane`` — the launch's ops landed on an ops-only
      satellite lane (recovered by the ledger's lane_window tier);
    * ``anonymous_launch`` — the module span carries no ``run_id``, so
      its signal uses a synthetic key that exact-identity span joins
      can never see.

    ``substantive_join_rate`` keeps its historical exact-join meaning
    (fraction of own-ops launches whose identity the ``xla_launch``
    tier can use); the ledger's TIERED rate — the one the device-plane
    gate holds at >= 0.9 — rides in ``ledger_substantive_join_rate``
    next to the full bucket accounting under ``ledger``.  ``reasons``
    counts only launches that did NOT end up joined (tier-recovered
    joined launches — e.g. lane-split steps — are not "unmatched";
    their recovery counts live in ``ledger.tier_counts``).

    Pass a prebuilt ``ledger`` to avoid folding the spans twice when
    the caller already has one (it must come from the same spans +
    compile events, or the two reports diverge — the split-brain this
    function exists to prevent).
    """
    from tpuslo.deviceplane.ledger import (
        BUCKET_JOINED,
        BUCKET_UNEXPLAINED,
        build_ledger,
    )

    if ledger is None:
        ledger = build_ledger(spans, compile_events or ())
    reasons: dict[str, int] = {}
    unmatched: list[dict[str, Any]] = []
    for rec in ledger.launches:
        if rec.tier == "identity":
            continue  # the exact join serves these
        if rec.reason and rec.bucket != BUCKET_JOINED:
            reasons[rec.reason] = reasons.get(rec.reason, 0) + 1
        if rec.ops_source != "own" and (
            rec.bucket == BUCKET_UNEXPLAINED or rec.ops_source == ""
        ):
            unmatched.append(
                {
                    "module": rec.module_name or rec.name,
                    "program_id": rec.program_id,
                    "launch_id": rec.launch_id,
                    "duration_us": round(rec.duration_us, 1),
                    "reason": rec.reason,
                    "tier": rec.tier,
                    "bucket": rec.bucket,
                }
            )
    return {
        "launches": len(ledger.launches),
        "launches_with_ops": ledger.launches_with_ops,
        "unmatched_count": len(unmatched),
        "reasons": reasons,
        "unmatched": unmatched[:24],
        "substantive_join_rate": round(
            ledger.exact_substantive_join_rate, 4
        ),
        "ledger_substantive_join_rate": round(
            ledger.substantive_join_rate, 4
        ),
        "raw_join_rate": round(ledger.raw_join_rate, 4),
        "ledger": ledger.to_dict(),
    }


class capture:
    """Context manager: profile a workload region and yield its spans.

    Wraps ``jax.profiler.trace`` and records the wall-clock anchor the
    trace file lacks, so ``span_refs()`` emits absolute timestamps the
    matcher can join against probe events::

        with xla_spans.capture(tmpdir) as cap:
            engine.generate(...)
        refs = cap.span_refs(service="rag-demo", node="host-0")
    """

    def __init__(self, log_dir: str, include_ops: bool = False):
        self.log_dir = log_dir
        self.include_ops = include_ops
        self.anchor_unix_ns = 0
        self.spans: list[XLASpan] = []
        self.spans_by_host: dict[str, list[XLASpan]] = {}
        self._trace_cm = None

    def __enter__(self) -> "capture":
        import jax

        self.anchor_unix_ns = time.time_ns()
        self._trace_cm = jax.profiler.trace(self.log_dir)
        self._trace_cm.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self._trace_cm.__exit__(exc_type, exc, tb)
        if exc_type is None:
            self.spans_by_host = load_latest_trace_by_host(
                self.log_dir, include_ops=self.include_ops
            )
            self.spans = sorted(
                (s for spans in self.spans_by_host.values() for s in spans),
                key=lambda s: s.start_us,
            )

    def span_refs(
        self,
        service: str = "",
        node: str = "",
        slice_id: str = "",
        host_index: int = -1,
        modules_only: bool = True,
    ) -> list[dict[str, Any]]:
        """Single-host convenience; multi-host runs must label per host
        (launch ids are only unique within one host's file)."""
        if len(self.spans_by_host) > 1 and (node or host_index >= 0):
            raise ValueError(
                "multiple host trace files captured; use "
                "span_refs_by_host() to label each host correctly"
            )
        return [
            s.to_span_ref_dict(
                self.anchor_unix_ns,
                service=service,
                node=node,
                slice_id=slice_id,
                host_index=host_index,
            )
            for s in self.spans
            if (not modules_only) or s.lane == MODULES_LANE
        ]

    def span_refs_by_host(
        self,
        identities: dict[str, dict[str, Any]],
        service: str = "",
        slice_id: str = "",
        modules_only: bool = True,
    ) -> dict[str, list[dict[str, Any]]]:
        """Per-host span refs; ``identities`` maps trace-file stem →
        ``{"node": ..., "host_index": ...}`` labels."""
        out: dict[str, list[dict[str, Any]]] = {}
        for host, spans in self.spans_by_host.items():
            ident = identities.get(host, {})
            out[host] = [
                s.to_span_ref_dict(
                    self.anchor_unix_ns,
                    service=service,
                    node=ident.get("node", host),
                    slice_id=slice_id,
                    host_index=int(ident.get("host_index", -1)),
                )
                for s in spans
                if (not modules_only) or s.lane == MODULES_LANE
            ]
        return out

    def launches(self) -> Iterator[XLASpan]:
        """Module-execution spans only (one per device launch)."""
        return (s for s in self.spans if s.lane == MODULES_LANE)
