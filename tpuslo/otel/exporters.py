"""Hand-rolled OTLP/HTTP logs exporters (no OTel SDK on the export path).

Reference: ``pkg/otel/{slo_event_exporter,probe_event_exporter}.go`` —
the agent ships JSON OTLP logs payloads directly to keep the export
path dependency-light; the demo workload is where full OTel tracing
lives.  Probe events additionally carry conn-tuple / errno / confidence
and (TPU-native) accelerator-identity attributes.
"""

from __future__ import annotations

import http.client
import json
import time
import urllib.error
import urllib.request

from tpuslo.schema import ProbeEventV1, SLOEvent

DEFAULT_SERVICE_NAME = "tpuslo"
DEFAULT_TIMEOUT_S = 5.0


class ExportError(RuntimeError):
    """OTLP export failure; ``retryable`` feeds the delivery layer's
    retry / dead-letter verdict (4xx = poison payload, never retried)."""

    def __init__(self, message: str, retryable: bool = True):
        super().__init__(message)
        self.retryable = retryable


def _str_attr(key: str, value: str) -> dict:
    return {"key": key, "value": {"stringValue": value}}


def _double_attr(key: str, value: float) -> dict:
    return {"key": key, "value": {"doubleValue": float(value)}}


def _int_attr(key: str, value: int) -> dict:
    return {"key": key, "value": {"intValue": str(int(value))}}


def _severity(status: str) -> str:
    if status in ("breach", "error"):
        return "ERROR"
    if status == "warning":
        return "WARN"
    return "INFO"


class _BaseExporter:
    def __init__(
        self,
        endpoint: str,
        service_name: str = DEFAULT_SERVICE_NAME,
        scope_name: str = "",
        timeout_s: float = DEFAULT_TIMEOUT_S,
    ):
        self.endpoint = endpoint
        self.service_name = service_name or DEFAULT_SERVICE_NAME
        self.scope_name = scope_name
        self.timeout_s = timeout_s if timeout_s > 0 else DEFAULT_TIMEOUT_S

    def _envelope(self, records: list[dict]) -> dict:
        """OTLP envelope around pre-built records; subclasses that ship
        a different signal (traces) override only this."""
        return {
            "resourceLogs": [
                {
                    "resource": {
                        "attributes": [_str_attr("service.name", self.service_name)]
                    },
                    "scopeLogs": [
                        {
                            "scope": {"name": self.scope_name},
                            "logRecords": records,
                        }
                    ],
                }
            ]
        }

    def _post(self, records: list[dict]) -> None:
        if not records:
            return
        if not self.endpoint:
            raise ExportError("otlp endpoint is required", retryable=False)
        body = json.dumps(self._envelope(records)).encode()
        req = urllib.request.Request(
            self.endpoint,
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                if not 200 <= resp.status < 300:
                    raise ExportError(f"otlp endpoint returned status {resp.status}")
        except urllib.error.HTTPError as exc:
            # 4xx = the payload itself is rejected; resending the same
            # bytes can never succeed, so mark it non-retryable — except
            # 429/408, which the OTLP/HTTP spec defines as retryable
            # (rate limiting / request timeout, not poison).
            raise ExportError(
                f"otlp endpoint returned status {exc.code}",
                retryable=(
                    not 400 <= exc.code < 500 or exc.code in (408, 429)
                ),
            ) from exc
        except TimeoutError as exc:
            raise ExportError(
                f"otlp post timed out after {self.timeout_s:.1f}s"
            ) from exc
        except urllib.error.URLError as exc:
            raise ExportError(f"otlp post failed: {exc.reason}") from exc
        except (http.client.HTTPException, OSError) as exc:
            # e.g. BadStatusLine / RemoteDisconnected when the endpoint
            # drops the connection mid-exchange: an outage, not poison.
            raise ExportError(f"otlp post failed: {exc!r}") from exc

    def post_records(self, records: list[dict]) -> None:
        """Ship pre-built OTLP log records (the delivery-channel path:
        records are built at submit time, spooled as plain JSON, and
        posted verbatim on delivery/replay)."""
        self._post(records)

    def close(self) -> None:
        """Stateless HTTP exporter: nothing pending, present for the
        EventWriters close contract."""


class SLOEventExporter(_BaseExporter):
    """Batch exporter for normalized SLO events."""

    def __init__(self, endpoint: str, service_name: str = DEFAULT_SERVICE_NAME,
                 scope_name: str = "tpuslo/collector", timeout_s: float = DEFAULT_TIMEOUT_S):
        super().__init__(endpoint, service_name, scope_name, timeout_s)

    def to_records(self, events: list[SLOEvent]) -> list[dict]:
        # One observation timestamp per batch: the whole batch is
        # observed by this call, and it keeps the hot loop clock-free.
        now_ns = time.time_ns()
        return [self._record(e, now_ns) for e in events]

    def export_batch(self, events: list[SLOEvent]) -> None:
        self._post(self.to_records(events))

    def _record(self, event: SLOEvent, now_ns: int | None = None) -> dict:
        now_ns = now_ns if now_ns is not None else time.time_ns()
        ts_ns = int(event.timestamp.timestamp() * 1e9) if event.timestamp else now_ns
        attrs = [
            _str_attr("event.id", event.event_id),
            _str_attr("cluster", event.cluster),
            _str_attr("namespace", event.namespace),
            _str_attr("workload", event.workload),
            _str_attr("service", event.service),
            _str_attr("request.id", event.request_id),
            _str_attr("trace.id", event.trace_id),
            _str_attr("sli.name", event.sli_name),
            _double_attr("sli.value", event.sli_value),
            _str_attr("sli.unit", event.unit),
            _str_attr("sli.status", event.status),
        ]
        attrs.extend(
            _str_attr(f"label.{key}", value) for key, value in event.labels.items()
        )
        return {
            "timeUnixNano": str(ts_ns),
            "observedTimeUnixNano": str(now_ns),
            "severityText": _severity(event.status),
            "body": {
                "stringValue": (
                    f"sli={event.sli_name} value={event.sli_value:.6f} "
                    f"status={event.status} service={event.service}"
                )
            },
            "attributes": attrs,
        }


class ProbeEventExporter(_BaseExporter):
    """Batch exporter for probe events (kernel + TPU signals)."""

    def __init__(self, endpoint: str, service_name: str = DEFAULT_SERVICE_NAME,
                 scope_name: str = "tpuslo/agent", timeout_s: float = DEFAULT_TIMEOUT_S):
        super().__init__(endpoint, service_name, scope_name, timeout_s)

    def to_records(self, events: list[ProbeEventV1]) -> list[dict]:
        now_ns = time.time_ns()
        return [self._record(e, now_ns) for e in events]

    def export_batch(self, events: list[ProbeEventV1]) -> None:
        self._post(self.to_records(events))

    def _record(self, event: ProbeEventV1, now_ns: int | None = None) -> dict:
        now_ns = now_ns if now_ns is not None else time.time_ns()
        attrs = [
            _str_attr("signal", event.signal),
            _str_attr("node", event.node),
            _str_attr("namespace", event.namespace),
            _str_attr("pod", event.pod),
            _str_attr("container", event.container),
            _int_attr("pid", event.pid),
            _int_attr("tid", event.tid),
            _double_attr("value", event.value),
            _str_attr("unit", event.unit),
            _str_attr("status", event.status),
        ]
        if event.trace_id:
            attrs.append(_str_attr("trace.id", event.trace_id))
        if event.span_id:
            attrs.append(_str_attr("span.id", event.span_id))
        if event.conn_tuple is not None:
            attrs.append(_str_attr("conn.tuple", event.conn_tuple.key()))
        if event.errno is not None:
            attrs.append(_int_attr("errno", event.errno))
        if event.confidence is not None:
            attrs.append(_double_attr("correlation.confidence", event.confidence))
        if event.tpu is not None:
            tpu = event.tpu
            if tpu.chip:
                attrs.append(_str_attr("tpu.chip", tpu.chip))
            if tpu.slice_id:
                attrs.append(_str_attr("tpu.slice_id", tpu.slice_id))
            if tpu.host_index >= 0:
                attrs.append(_int_attr("tpu.host_index", tpu.host_index))
            if tpu.ici_link >= 0:
                attrs.append(_int_attr("tpu.ici_link", tpu.ici_link))
            if tpu.program_id:
                attrs.append(_str_attr("tpu.xla.program_id", tpu.program_id))
            if tpu.launch_id >= 0:
                attrs.append(_int_attr("tpu.xla.launch_id", tpu.launch_id))
        return {
            "timeUnixNano": str(event.ts_unix_nano),
            "observedTimeUnixNano": str(now_ns),
            "severityText": _severity(event.status),
            "body": {
                "stringValue": (
                    f"signal={event.signal} value={event.value:.6f} "
                    f"status={event.status} node={event.node}"
                )
            },
            "attributes": attrs,
        }
