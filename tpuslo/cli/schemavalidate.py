"""Schemavalidate: compile all contracts and validate golden payloads.

Reference: ``cmd/schemavalidate/main.go:32-146`` — compiles the four
JSON schemas and validates golden sample payloads plus toolkit.yaml.
"""

from __future__ import annotations

import argparse
import sys
from datetime import datetime, timezone

from tpuslo import schema
from tpuslo.config import default_config
from tpuslo.schema import (
    ConnTuple,
    Evidence,
    FaultHypothesis,
    IncidentAttribution,
    ProbeEventV1,
    SLOEvent,
    SLOImpact,
    TPURef,
)

TS = datetime(2026, 1, 1, tzinfo=timezone.utc)


def golden_payloads() -> list[tuple[str, dict]]:
    slo_event = SLOEvent(
        event_id="golden-req-0001-ttft_ms",
        timestamp=TS,
        cluster="tpu-cluster",
        namespace="llm",
        workload="rag-service",
        service="rag-service",
        request_id="golden-req-0001",
        trace_id="golden-trace-0001",
        sli_name="ttft_ms",
        sli_value=340.0,
        unit="ms",
        status="ok",
        labels={"source": "synthetic"},
    )
    probe_event = ProbeEventV1(
        ts_unix_nano=int(TS.timestamp() * 1e9),
        signal="ici_collective_latency_ms",
        node="tpu-vm-0",
        namespace="llm",
        pod="rag-service-abc",
        container="rag",
        pid=1234,
        tid=1234,
        value=55.0,
        unit="ms",
        status="error",
        tpu=TPURef(
            chip="accel0",
            slice_id="v5e-8-s0",
            host_index=0,
            ici_link=2,
            program_id="jit_decode_step",
            launch_id=17,
        ),
    )
    kernel_probe = ProbeEventV1(
        ts_unix_nano=int(TS.timestamp() * 1e9),
        signal="dns_latency_ms",
        node="tpu-vm-0",
        namespace="llm",
        pod="rag-service-abc",
        container="rag",
        pid=1234,
        tid=1234,
        value=220.0,
        unit="ms",
        status="error",
        conn_tuple=ConnTuple("10.0.0.10", "10.0.0.53", 42424, 53, "udp"),
        errno=110,
    )
    incident = IncidentAttribution(
        incident_id="golden-inc-0001",
        timestamp=TS,
        cluster="tpu-cluster",
        namespace="llm",
        service="rag-service",
        predicted_fault_domain="tpu_hbm",
        confidence=0.93,
        evidence=[
            Evidence("hbm_alloc_stall_ms", 60.0, "libtpu"),
            Evidence("hbm_utilization_pct", 97.0, "libtpu"),
        ],
        slo_impact=SLOImpact("ttft_ms", 2.4, 30),
        trace_ids=["golden-trace-0001"],
        request_ids=["golden-req-0001"],
        fault_hypotheses=[
            FaultHypothesis("tpu_hbm", 0.93, ["hbm_alloc_stall_ms"]),
            FaultHypothesis("host_offload", 0.05, []),
        ],
        # Self-observability pointer (ISSUE 5): producing cycle's trace
        # + supporting probe events; full chain via `sloctl explain`.
        provenance={
            "trace_id": "0af7651916cd43dd8448eb211c80319c",
            "root_span_id": "b7ad6b7169203331",
            "probe_event_ids": ["hbm_alloc_stall_ms@1767225600000000000"],
        },
    )
    return [
        (schema.SCHEMA_SLO_EVENT, slo_event.to_dict()),
        (schema.SCHEMA_PROBE_EVENT, probe_event.to_dict()),
        (schema.SCHEMA_PROBE_EVENT, kernel_probe.to_dict()),
        (schema.SCHEMA_INCIDENT_ATTRIBUTION, incident.to_dict()),
        (schema.SCHEMA_TOOLKIT_CONFIG, default_config().to_dict()),
    ]


def build_parser() -> argparse.ArgumentParser:
    return argparse.ArgumentParser(prog="tpuslo schemavalidate", description=__doc__)


def main(argv: list[str] | None = None) -> int:
    build_parser().parse_args(argv)
    failures = 0
    for name in schema.ALL_SCHEMAS:
        try:
            schema.load_schema(name)
            print(f"schema {name}: compiles")
        except Exception as exc:  # noqa: BLE001
            failures += 1
            print(f"schema {name}: FAILED to compile: {exc}", file=sys.stderr)
    for name, payload in golden_payloads():
        try:
            schema.validate(payload, name)
            print(f"golden payload vs {name}: valid")
        except schema.SchemaValidationError as exc:
            failures += 1
            print(f"golden payload vs {name}: INVALID: {exc}", file=sys.stderr)
    if failures:
        print(f"schemavalidate: {failures} failure(s)", file=sys.stderr)
        return 1
    print("schemavalidate: all contracts and golden payloads valid")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
