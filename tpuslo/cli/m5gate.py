"""M5gate: run the B5/D3/E3 statistical release gates.

Reference: ``cmd/m5gate/main.go`` — all stat knobs as flags, JSON + MD
summaries, exit 1 on gate failure.

``--chaos-sweep`` runs the telemetry-chaos release gate instead: the
source→correlation→attribution path is replayed under seeded chaos at
increasing intensity, with and without the ingest gate, and the run
fails unless degradation is graceful (gated macro-F1 within tolerance
of the clean baseline at moderate chaos, strictly better than the
ungated path at every swept intensity).

``--crash-sweep`` runs the kill -9 crash/restart gate: the agent
subprocess is SIGKILLed at seeded cycle points and restarted against
the same state dir; the run fails unless zero torn JSONL lines are
replayed, zero cycles are lost, zero webhook alerts duplicate, and
the restart resumes warm from the snapshot
(``tpuslo.chaos.crash``, evidence in docs/evidence/crash-sweep.md).

``--lint`` runs the tpulint v2 analyzer (``tpuslo.analysis``) with the
committed baseline and fails on any new finding; ``--racecheck-smoke``
runs the threaded suites under the dynamic lock-order race detector.
``make m5-gate`` runs both before the statistical gates, so a release
candidate with a fresh lint finding or a lock-order inversion never
reaches the benchmark comparison.

``--fleet-sweep`` runs the fleet observability-plane gate
(``tpuslo.fleet.sweep``): 1k simulated nodes ship gated columnar
batches over the versioned wire contract to sharded aggregators; the
run fails unless the aggregate ingest floor holds, every injected
fleet fault rolls up to exactly one incident at the correct blast
radius (no cross-tenant/cross-domain merges), and killing one
aggregator mid-sweep — ring re-home + snapshot restore + spool
re-send — loses and duplicates zero incidents.

``--federation-sweep`` runs the federation-plane gate
(``tpuslo.federation.sweep``): 10k simulated nodes over a two-level
aggregator tree (cluster shard rings → region rollup) must sustain
the single-level aggregate ingest floor, collapse every injected
fault to exactly one region incident with cross-cluster identity
under continuous node churn + rolling shard restarts, survive a
mid-sweep region-aggregator kill with zero lost/duplicated
incidents, and — under forced ingest saturation — degrade batch
granularity and sample low-severity rows (counted by level, bounded
incident staleness) without ever dropping a gated fault's incident.

``--global-sweep`` runs the global-tier gate
(``tpuslo.federation.sweep.run_global_sweep``): 100k simulated nodes
(10 regions x 10k) through the three-tier fold must sustain the
ingest floor, collapse a cross-region fault to exactly ONE
globally-identified page under WAN latency + one-way ack loss (the
gap-tolerant cursor's dedup exercised, not idle), survive one region
dark for a simulated hour — healthy side keeps paging
partition-scoped, rejoin replays the spool within the bounded-budget
round count, zero pages lost or duplicated — and prove the
split-brain heal: merged emitted-window registries suppress replayed
sessions instead of re-paging.

``--burn-sweep`` runs the error-budget burn-scenario gate
(``tpuslo.sloengine.sweep``): seeded synthetic traffic shapes (steady,
fast-burn, slow-burn, latency regression, flapping, tenant-isolated,
kill/restart) replayed through the burn engine, asserting alert
precision/recall, page promptness, zero flap-induced duplicates,
tenant isolation, and snapshot/restore equivalence.

``--remediation-sweep`` runs the auto-remediation action-loop gate
(``tpuslo.remediation.sweep``): seeded fault injections (faultreplay →
Bayesian attribution) under synthesized burn traffic drive the
observe → attribute → remediate → verify loop, asserting action
precision 1.0 (zero actions on healthy / low-confidence targets),
burn verified subsided or rolled back within the window budget,
rate-limit/budget damping under a mis-attribution storm, zero
duplicate actions across a mid-sweep engine kill, and every action
traceable end-to-end in the provenance chain.

``--frontdoor-bench`` runs the serving front-door gate
(``tpuslo.benchmark.frontdoor_bench``): loadgen-synthesized bursty
multi-tenant traffic through the FrontDoorEngine (batched speculative
rounds inside continuous-batching slots, SLO-aware admission) must
deliver >= 2x the goodput and tokens/s of the same streams served
sequentially through the per-stream SpeculativeEngine, with zero
steady-state recompiles under jitaudit, host syncs per token within
the serving ceiling, and the burn-aware admission observable.

``--router-bench`` runs the serving scale-out gate
(``tpuslo.benchmark.router_bench``): thousands of concurrent streams
placed by the SLORouter over N replicated paged-KV front doors in a
virtual-time discrete-event harness — aggregate goodput must reach
>= 0.8xN of a single identical engine on the same burst, bounded-load
prefix affinity must beat uniform-random placement on TTFT p99 on a
paced multi-group workload, every fleet pass must show zero
steady-state recompiles (jitaudit), and a mid-run engine kill must
drain parked/running slots onto siblings with zero lost requests and
bit-exact stream parity against an uninterrupted reference.

``--live-chaos-sweep`` runs the live deployment-plane gate
(``tpuslo.chaos.procs``): the whole tree — node agent → cluster
aggregator → region aggregator over real livenet sockets, plus the
serving front door with its co-located remediation agent — as
supervised OS processes; every kill target (agent, cluster, region,
front door) is SIGKILLed mid-window and the cluster → region socket
is black-holed once, and the run fails unless zero incidents are lost
or duplicated across the tree, every restart resumes warm from its
spool/seq-journal/snapshot, the agent's shipment cadence measurably
coarsens at pressure level >= 1, no listener ever rejects a frame,
and the live ``demote_tenant`` remediation flips the admission order
and survives the front-door kill.

``--deviceplane-sweep`` runs the device-plane truth gate
(``tpuslo.deviceplane.sweep``): seeded synthetic-xprof traces with
every real-capture join pathology (lane-split ops, anonymous warmups,
dispatch-only helpers, idle/preemption gaps) are folded through the
per-launch device-time ledger — buckets must sum to total device time,
the substantive join rate must hold >= 0.9 and unexplained share
<= 0.1; every serving-path attribution must carry a schema-valid
roofline verdict (decode memory-bound, prefill compute-bound); and the
calibrated heldout suite with the two device-plane fault domains
(tpu_preemption, host_noisy_neighbor) must hold macro-F1 >= 0.96 at
full-domain noise sigma 1.0.

``--profiler-sweep`` runs the continuous-profiler gate
(``tpuslo.deviceplane.profiler``): seeded capture windows folded
through the same ledger must hold the measured-overhead budget (EMA
<= 3% of cycle budget), the governor must degrade under forced-slow
capture without ever dropping an eviction-bearing window and
re-engage on sustained headroom, every window must hold substantive
join >= 0.9 with the raw exact-identity rate reported alongside,
per-window bucket sums must match one ledger over the spliced full
capture, and the injected preemption window must attribute to
``tpu_preemption``.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from tpuslo import releasegate


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpuslo m5gate", description=__doc__)
    p.add_argument("--candidate-root", default="artifacts/weekly-benchmark")
    p.add_argument("--baseline-root", default="")
    p.add_argument("--baseline-manifest", default="")
    p.add_argument("--candidate-ref", default="")
    p.add_argument("--candidate-commit", default="")
    p.add_argument("--require-baseline-manifest", action="store_true")
    p.add_argument("--scenarios", default="", help="comma-separated override")
    p.add_argument("--max-overhead-pct", type=float, default=3.0)
    p.add_argument("--max-variance-pct", type=float, default=10.0)
    p.add_argument("--min-runs", type=int, default=3)
    p.add_argument("--regression-pct-limit", type=float, default=5.0)
    p.add_argument("--alpha", type=float, default=0.05)
    p.add_argument("--bootstrap-iterations", type=int, default=1000)
    p.add_argument("--bootstrap-seed", type=int, default=42)
    p.add_argument("--min-samples", type=int, default=30)
    p.add_argument("--min-cliffs-delta", type=float, default=0.147)
    p.add_argument("--summary-json", default="")
    p.add_argument("--summary-md", default="")
    # ---- telemetry chaos-sweep gate ----------------------------------
    p.add_argument(
        "--chaos-sweep",
        action="store_true",
        help="run the telemetry chaos-sweep gate instead of B5/D3/E3",
    )
    p.add_argument("--chaos-scenario", default="tpu_mixed")
    p.add_argument("--chaos-count", type=int, default=60)
    p.add_argument("--chaos-seed", type=int, default=1337)
    p.add_argument(
        "--chaos-intensities",
        default="0,0.5,1,2",
        help="comma-separated chaos intensities (1.0 = moderate: "
        "skew<=250ms, 5%% dup, 5%% reorder, 1%% corrupt)",
    )
    p.add_argument("--chaos-hosts", type=int, default=4)
    p.add_argument(
        "--chaos-rel-tolerance",
        type=float,
        default=0.05,
        help="max relative macro-F1 loss vs the no-chaos baseline "
        "allowed at up-to-moderate intensities with the gate on",
    )
    # ---- crash chaos-sweep gate ---------------------------------------
    p.add_argument(
        "--crash-sweep",
        action="store_true",
        help="run the kill -9 crash/restart gate instead of B5/D3/E3: "
        "SIGKILL the agent subprocess at seeded cycle points, restart "
        "it, and fail unless zero torn lines are replayed, zero cycles "
        "are lost, and zero webhook alerts duplicate",
    )
    # ---- static-analysis + racecheck gates (ISSUE 6) -----------------
    p.add_argument(
        "--lint",
        action="store_true",
        help="run the tpulint v2 analyzer (zero-delta vs the committed "
        "baseline) instead of the statistical gates",
    )
    p.add_argument(
        "--racecheck-smoke",
        action="store_true",
        help="run the delivery/runtime/obs suites under the dynamic "
        "lock-order race detector (TPUSLO_RACECHECK=1)",
    )
    p.add_argument(
        "--jitcheck-smoke",
        action="store_true",
        help="run the serving suites under the dynamic retrace/"
        "host-sync auditor (TPUSLO_JITAUDIT=1): the session fails if "
        "a steady-state decode loop triggers an XLA backend compile",
    )
    # ---- error-budget burn-scenario gate (tpuslo.sloengine) -----------
    p.add_argument(
        "--burn-sweep",
        action="store_true",
        help="run the burn-scenario gate instead of B5/D3/E3: seeded "
        "traffic shapes through the error-budget engine, asserting "
        "alert precision/recall, promptness, dedup, tenant isolation "
        "and snapshot/restore equivalence",
    )
    p.add_argument("--burn-seed", type=int, default=1337)
    p.add_argument("--burn-bucket-s", type=int, default=10)
    p.add_argument("--burn-eval-interval-s", type=float, default=30.0)
    # ---- auto-remediation action-loop gate (tpuslo.remediation) -------
    p.add_argument(
        "--remediation-sweep",
        action="store_true",
        help="run the auto-remediation gate instead of B5/D3/E3: "
        "seeded fault scenarios through the observe -> attribute -> "
        "remediate -> verify loop, asserting action precision 1.0, "
        "verify-or-rollback within the window budget, storm damping, "
        "zero duplicate actions across a mid-sweep kill, and "
        "end-to-end provenance",
    )
    p.add_argument("--remediation-seed", type=int, default=1337)
    p.add_argument(
        "--remediation-eval-interval-s", type=float, default=60.0
    )
    p.add_argument("--remediation-verify-windows", type=int, default=10)
    p.add_argument(
        "--remediation-provenance-dir",
        default="",
        help="directory for per-scenario provenance chains (default: "
        "a temp dir)",
    )
    # ---- serving front-door gate (tpuslo.models.frontdoor) ------------
    p.add_argument(
        "--frontdoor-bench",
        action="store_true",
        help="run the serving front-door gate instead of B5/D3/E3: "
        "loadgen-driven bursty multi-tenant traffic through the "
        "FrontDoorEngine must deliver >= 2x the goodput AND tokens/s "
        "of the same streams served sequentially through the "
        "per-stream SpeculativeEngine, with zero steady-state "
        "recompiles (jitaudit), host syncs per token under the "
        "serving ceiling, and burn-aware admission observable "
        "(burning tenant's goodput share drops, healthy p99 holds)",
    )
    p.add_argument("--frontdoor-seed", type=int, default=1337)
    p.add_argument("--frontdoor-streams", type=int, default=192)
    p.add_argument("--frontdoor-slots", type=int, default=16)
    p.add_argument("--frontdoor-k", type=int, default=4)
    p.add_argument("--frontdoor-tokens", type=int, default=96)
    p.add_argument("--frontdoor-tenants", type=int, default=4)
    p.add_argument("--frontdoor-arrival", default="burst")
    p.add_argument("--frontdoor-passes", type=int, default=2)
    p.add_argument("--frontdoor-rounds-per-step", type=int, default=3)
    p.add_argument(
        "--frontdoor-retries",
        type=int,
        default=1,
        help="re-run the whole lane this many times if a wall-clock "
        "gate fails (the lane times real serving on a possibly-"
        "shared box; counter gates are deterministic either way)",
    )
    # ---- serving scale-out gate (tpuslo.models.router) ----------------
    p.add_argument(
        "--router-bench",
        action="store_true",
        help="run the serving scale-out gate instead of B5/D3/E3: "
        "SLO-aware routing over N replicated paged-KV front doors in "
        "virtual time — aggregate goodput >= 0.8xN of one engine, "
        "bounded-load prefix affinity beats random placement on TTFT "
        "p99, zero steady-state recompiles per engine, and a mid-run "
        "engine kill loses zero requests with bit-exact stream parity",
    )
    p.add_argument("--router-seed", type=int, default=1337)
    p.add_argument("--router-engines", type=int, default=4)
    p.add_argument("--router-streams", type=int, default=1024)
    p.add_argument("--router-slots", type=int, default=8)
    p.add_argument("--router-k", type=int, default=3)
    p.add_argument("--router-tokens", type=int, default=16)
    p.add_argument("--router-tenants", type=int, default=4)
    p.add_argument("--router-prefix-groups", type=int, default=8)
    p.add_argument("--router-kill-streams", type=int, default=96)
    p.add_argument(
        "--router-retries",
        type=int,
        default=1,
        help="re-run the whole lane this many times if a wall-clock "
        "gate fails (virtual time is built from real step durations "
        "on a possibly-shared box; counter gates are deterministic)",
    )
    # ---- device-plane truth gate (tpuslo.deviceplane) -----------------
    p.add_argument(
        "--deviceplane-sweep",
        action="store_true",
        help="run the device-plane truth gate instead of B5/D3/E3: "
        "seeded synthetic-xprof traces through the per-launch "
        "device-time ledger (buckets sum to total, substantive join "
        ">= 0.9, unexplained <= 0.1), roofline verdicts on every "
        "serving attribution, and the calibrated heldout suite with "
        "the preemption + noisy-neighbor domains at >= 0.96 macro-F1",
    )
    p.add_argument("--deviceplane-seed", type=int, default=1337)
    p.add_argument("--deviceplane-steps", type=int, default=24)
    p.add_argument("--deviceplane-heldout-count", type=int, default=25)
    p.add_argument(
        "--deviceplane-skip-heldout",
        action="store_true",
        help="skip the heldout lane's noise sweep (the ledger and "
        "roofline lanes still run, including the one shared "
        "calibrated fit)",
    )
    # ---- continuous-profiler gate (tpuslo.deviceplane.profiler) -------
    p.add_argument(
        "--profiler-sweep",
        action="store_true",
        help="run the continuous-profiler gate instead of B5/D3/E3: "
        "seeded capture windows through the ledger must hold the "
        "measured-overhead budget (EMA <= 3% of cycle budget), the "
        "governor must degrade under forced-slow capture, never drop "
        "an eviction-bearing window, and re-engage on headroom; "
        "per-window substantive join >= 0.9 with the raw rate "
        "reported alongside; per-window buckets must sum to the "
        "spliced full-capture ledger; and the injected preemption "
        "window must attribute to tpu_preemption",
    )
    p.add_argument("--profiler-seed", type=int, default=1337)
    p.add_argument("--profiler-cycles", type=int, default=24)
    p.add_argument("--profiler-parity-windows", type=int, default=5)
    # ---- fleet observability-plane gate (tpuslo.fleet) ----------------
    p.add_argument(
        "--fleet-sweep",
        action="store_true",
        help="run the fleet observability-plane gate instead of "
        "B5/D3/E3: 1k simulated nodes over sharded aggregators must "
        "sustain the aggregate columnar ingest floor, every injected "
        "fleet fault must yield exactly one incident at the correct "
        "blast radius, and killing one aggregator mid-sweep must lose "
        "and duplicate zero incidents",
    )
    p.add_argument("--fleet-nodes", type=int, default=1000)
    p.add_argument("--fleet-shards", type=int, default=4)
    p.add_argument("--fleet-seed", type=int, default=1337)
    p.add_argument("--fleet-chaos-intensity", type=float, default=1.0)
    p.add_argument("--fleet-events-per-node", type=int, default=6000)
    p.add_argument("--fleet-rounds", type=int, default=24)
    p.add_argument(
        "--fleet-min-ingest",
        type=float,
        default=5_000_000.0,
        help="aggregate columnar ingest floor in events/s across all "
        "shards (total events over the slowest shard's busy time)",
    )
    p.add_argument(
        "--fleet-no-kill",
        action="store_true",
        help="skip the mid-sweep aggregator kill (failover contract)",
    )
    # ---- federation-plane gate (tpuslo.federation) ---------------------
    p.add_argument(
        "--federation-sweep",
        action="store_true",
        help="run the federation-plane gate instead of B5/D3/E3: 10k "
        "simulated nodes over a two-level aggregator tree must "
        "sustain the single-level ingest floor, collapse every "
        "injected fault to exactly one region incident (the "
        "fleet-scope fault spanning clusters) under continuous node "
        "churn + rolling shard restarts, survive a mid-sweep region "
        "kill with zero lost/duplicated incidents, and degrade "
        "granularity — counted by level, bounded staleness, never "
        "dropped evidence — under forced ingest saturation",
    )
    p.add_argument("--federation-nodes", type=int, default=10000)
    p.add_argument("--federation-clusters", type=int, default=4)
    p.add_argument(
        "--federation-shards-per-cluster", type=int, default=4
    )
    p.add_argument("--federation-seed", type=int, default=1337)
    p.add_argument(
        "--federation-churn-rate",
        type=int,
        default=4,
        help="node leaves+joins per round of the seeded churn "
        "schedule (rolling shard restarts are always included)",
    )
    p.add_argument("--federation-rounds", type=int, default=18)
    p.add_argument(
        "--federation-events-per-node", type=int, default=600
    )
    p.add_argument("--federation-chaos-intensity", type=float, default=1.0)
    p.add_argument(
        "--federation-min-ingest",
        type=float,
        default=5_000_000.0,
        help="aggregate ingest floor in events/s across every "
        "cluster's shards (the PR 9 single-level floor — federation "
        "must not cost throughput)",
    )
    p.add_argument(
        "--federation-staleness-ceiling-ms",
        type=float,
        default=30_000.0,
        help="max incident staleness (region head past window end at "
        "emission), including under forced saturation",
    )
    p.add_argument(
        "--federation-no-kill",
        action="store_true",
        help="skip the mid-sweep region-aggregator kill",
    )
    p.add_argument(
        "--federation-no-saturate",
        action="store_true",
        help="skip the forced-saturation lane",
    )
    # ---- global-tier gate (tpuslo.federation.global_tier) --------------
    p.add_argument(
        "--global-sweep",
        action="store_true",
        help="run the global-tier gate instead of B5/D3/E3: 100k "
        "simulated nodes (10 regions x 10k) must sustain the ingest "
        "floor through the three-tier fold, collapse a cross-region "
        "fault to exactly ONE globally-identified page under WAN "
        "latency + ack loss (seq dedup exercised, not idle), survive "
        "a region dark for one simulated hour with zero "
        "lost/duplicated pages and bounded spool replay, and keep "
        "split-brain peers from re-paging after the heal-time "
        "emitted-window registry merge",
    )
    p.add_argument("--global-regions", type=int, default=4)
    p.add_argument("--global-nodes-per-region", type=int, default=96)
    p.add_argument("--global-seed", type=int, default=1337)
    p.add_argument(
        "--global-round-s",
        type=float,
        default=60.0,
        help="simulated seconds per round (at 60, the default dark "
        "duration below is one hour of event time)",
    )
    p.add_argument("--global-replay-budget", type=int, default=8)
    p.add_argument(
        "--global-wan-latency-rounds", type=int, default=2
    )
    p.add_argument(
        "--global-partition-rounds",
        type=int,
        default=6,
        help="length of the one-way ack-loss window (the asymmetric "
        "partition lane: frames arrive, acks vanish)",
    )
    p.add_argument(
        "--global-dark-duration-rounds",
        type=int,
        default=60,
        help="rounds the dark region stays partitioned "
        "(60 x 60s rounds = one simulated hour)",
    )
    p.add_argument("--global-ingest-regions", type=int, default=10)
    p.add_argument(
        "--global-ingest-nodes-per-region", type=int, default=10_000
    )
    p.add_argument(
        "--global-min-ingest",
        type=float,
        default=5_000_000.0,
        help="aggregate ingest floor in events/s through the "
        "three-tier fold at the 100k ceiling (the global hop must "
        "not cost throughput)",
    )
    p.add_argument(
        "--global-no-ingest",
        action="store_true",
        help="skip the 100k ingest lane (the slow half of the gate; "
        "the smoke target uses this)",
    )
    # ---- peer-mesh gate (tpuslo.federation symmetric root) -------------
    p.add_argument(
        "--peer-sweep",
        action="store_true",
        help="run the symmetric-peer-mesh gate instead of B5/D3/E3: "
        "N global aggregators gossiping over the 100k-node "
        "simulator; killing the leader's whole peering domain "
        "mid-sweep must elect a new root within bounded gossip "
        "rounds with zero lost/duplicate pages, a split-brain where "
        "BOTH sides elect must heal by gossip alone, and a deposed "
        "root returning from an hour dark must emit nothing at its "
        "stale epoch (rejections counted, evidence re-stamped)",
    )
    p.add_argument(
        "--peer-count",
        type=int,
        default=3,
        help="mesh size for the handover and deposed-root lanes "
        "(the split-brain lane always runs five so both halves can "
        "confirm commits internally)",
    )
    p.add_argument(
        "--root-dark-rounds",
        type=int,
        default=12,
        help="rounds the leader's peering domain stays dark in the "
        "handover lane",
    )
    p.add_argument(
        "--peer-deposed-dark-rounds",
        type=int,
        default=60,
        help="rounds the deposed root sits in its own partition "
        "(60 x 60s rounds = one simulated hour)",
    )
    p.add_argument(
        "--peer-gossip-latency-rounds", type=int, default=1
    )
    p.add_argument(
        "--peer-no-ingest",
        action="store_true",
        help="skip the 100k ingest lane (the slow half of the gate; "
        "the smoke target uses this)",
    )
    # ---- live deployment-plane gate (tpuslo.chaos.procs) --------------
    p.add_argument(
        "--live-chaos-sweep",
        action="store_true",
        help="run the live deployment-plane gate instead of B5/D3/E3: "
        "the whole tree as supervised processes over real sockets; "
        "SIGKILL every target mid-window + one socket partition, "
        "requiring zero lost/dup incidents, warm resume, measured "
        "cadence coarsening at pressure >= 1, clean framing, and the "
        "live demote_tenant remediation surviving the front-door kill",
    )
    p.add_argument("--live-chaos-root", default="artifacts/live-chaos")
    p.add_argument("--live-chaos-seed", type=int, default=1)
    p.add_argument(
        "--live-chaos-targets",
        default="agent,cluster,region,frontdoor",
        help="comma-separated kill targets (the partition run always "
        "runs after them)",
    )
    p.add_argument("--crash-root", default="artifacts/crash")
    p.add_argument("--crash-seeds", default="1,2,3,4,5")
    p.add_argument("--crash-kill-points", default="0.25,0.5,0.8")
    p.add_argument("--crash-count", type=int, default=16)
    p.add_argument("--crash-interval-s", type=float, default=0.05)
    return p


def render_crash_markdown(report) -> str:
    lines = [
        "# Crash chaos-sweep gate (kill -9 / restart)",
        "",
        f"**Overall: {'PASS' if report.passed else 'FAIL'}**",
        "",
        f"- {report.count} cycles per run at {report.interval_s:g}s "
        "interval; agent killed with SIGKILL at the kill cycle, then "
        "restarted against the same state dir",
        "- contracts: 0 torn lines replayed, 0 cycles lost, "
        "0 duplicate webhook alerts, warm resume from the snapshot",
        "",
        "| seed | kill pt | killed @ | resumed @ | torn replayed | "
        "lost | dup alerts | dup lines | restored | pass |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]
    for run in report.runs:
        lines.append(
            f"| {run.seed} | {run.kill_point:g} | {run.kill_cycle} "
            f"| {run.resumed_cycle} | {run.torn_lines_replayed} "
            f"| {run.lost_cycles} | {run.duplicate_alerts} "
            f"| {run.duplicate_event_lines} "
            f"| {','.join(run.restored_components) or '-'} "
            f"| {run.passed} |"
        )
    if report.failures:
        lines += ["", "## Failures", ""]
        lines += [f"- {f}" for f in report.failures]
    return "\n".join(lines) + "\n"


def run_crash_gate(args) -> int:
    from tpuslo.chaos.crash import run_crash_sweep

    seeds = tuple(
        int(v) for v in args.crash_seeds.split(",") if v.strip()
    )
    kill_points = tuple(
        float(v) for v in args.crash_kill_points.split(",") if v.strip()
    )
    report = run_crash_sweep(
        args.crash_root,
        seeds=seeds,
        kill_points=kill_points,
        count=args.crash_count,
        interval_s=args.crash_interval_s,
        log=lambda msg: print(f"m5gate: {msg}", file=sys.stderr),
    )
    if args.summary_json:
        Path(args.summary_json).write_text(
            json.dumps(report.to_dict(), indent=2) + "\n"
        )
    if args.summary_md:
        Path(args.summary_md).write_text(render_crash_markdown(report))
    print(
        f"m5gate: crash-sweep {'PASS' if report.passed else 'FAIL'}"
        + ("" if report.passed else f" ({'; '.join(report.failures)})"),
        file=sys.stderr,
    )
    return 0 if report.passed else 1


def render_burn_markdown(report) -> str:
    lines = [
        "# Error-budget burn-scenario gate",
        "",
        f"**Overall: {'PASS' if report.passed else 'FAIL'}**",
        "",
        f"- seed {report.seed}, evaluation every "
        f"{report.eval_interval_s:g}s of event time",
        "- contracts: alert precision + recall per scenario, fast page "
        "within one evaluation of the windows crossing, zero "
        "flap-induced duplicate transitions, tenant isolation, "
        "snapshot/restore equivalence",
        "",
        "| scenario | outcomes | evals | alerts | fast crossed @ | "
        "page fired @ | pass |",
        "|---|---|---|---|---|---|---|",
    ]
    for run in report.runs:
        crossed = (
            f"{run.fast_crossing_eval_s:.0f}s"
            if run.fast_crossing_eval_s >= 0
            else "-"
        )
        fired = (
            f"{run.fast_fired_eval_s:.0f}s"
            if run.fast_fired_eval_s >= 0
            else "-"
        )
        lines.append(
            f"| {run.name} | {run.outcomes} | {run.evaluations} "
            f"| {len(run.fired)} | {crossed} | {fired} | {run.passed} |"
        )
    if report.failures:
        lines += ["", "## Failures", ""]
        lines += [f"- {f}" for f in report.failures]
    return "\n".join(lines) + "\n"


def run_burn_gate(args) -> int:
    from tpuslo.sloengine.sweep import run_burn_sweep

    report = run_burn_sweep(
        seed=args.burn_seed,
        bucket_s=args.burn_bucket_s,
        eval_interval_s=args.burn_eval_interval_s,
        log=lambda msg: print(f"m5gate: {msg}", file=sys.stderr),
    )
    if args.summary_json:
        Path(args.summary_json).write_text(
            json.dumps(report.to_dict(), indent=2) + "\n"
        )
    if args.summary_md:
        Path(args.summary_md).write_text(render_burn_markdown(report))
    print(
        f"m5gate: burn-sweep {'PASS' if report.passed else 'FAIL'}"
        + ("" if report.passed else f" ({'; '.join(report.failures)})"),
        file=sys.stderr,
    )
    return 0 if report.passed else 1


def render_remediation_markdown(report) -> str:
    lines = [
        "# Auto-remediation action-loop gate",
        "",
        f"**Overall: {'PASS' if report.passed else 'FAIL'}**",
        "",
        f"- seed {report.seed}, evaluation every "
        f"{report.eval_interval_s:g}s of event time, verify window "
        f"budget {report.verify_windows}",
        "- contracts: action precision 1.0 (zero actions on healthy / "
        "low-confidence targets), burn verified subsided or action "
        "rolled back within the window budget, storm damping under "
        "the global budget + rate limits, zero duplicate actions "
        "across a mid-sweep kill, every action in the provenance "
        "chain",
        "",
        "| scenario | evals | actions | confirmed | rolled back | "
        "mitigate (s) | max in-flight | pass |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for run in report.runs:
        confirmed = sum(
            1 for a in run.actions if a["phase"] == "confirmed"
        )
        rolled = sum(
            1 for a in run.actions if a["phase"] == "rolled_back"
        )
        mitigate = (
            f"{max(run.time_to_mitigate_s):.0f}"
            if run.time_to_mitigate_s
            else "-"
        )
        lines.append(
            f"| {run.name} | {run.evaluations} | {len(run.actions)} "
            f"| {confirmed} | {rolled} | {mitigate} "
            f"| {run.max_in_flight} | {run.passed} |"
        )
    if report.failures:
        lines += ["", "## Failures", ""]
        lines += [f"- {f}" for f in report.failures]
    return "\n".join(lines) + "\n"


def render_frontdoor_markdown(report: dict) -> str:
    seq = report["sequential"]
    fd = report["frontdoor"]
    burn = report["burn_scenario"]
    lines = [
        "# Serving front-door gate (batched spec + SLO-aware admission)",
        "",
        f"**Overall: {'PASS' if report['passed'] else 'FAIL'}**",
        "",
        f"- seed {report['seed']}: {report['streams']} streams, "
        f"{report['arrival']} arrival over {report['tenants']} tenants "
        f"(mix {report['tenant_mix']}, prefix rate "
        f"{report['prefix_rate']:g}), {report['max_new_tokens']} "
        f"tokens each; front door at {report['max_slots']} slots, "
        f"k={report['k']}",
        f"- SLO (solo-calibrated): TTFT {report['slo']['ttft_ms']:g} ms, "
        f"TPOT {report['slo']['tpot_ms']:g} ms",
        "",
        "| path | tok/s | goodput tok/s | TTFT p99 (ms) | TPOT p99 (ms) |",
        "|---|---|---|---|---|",
        f"| sequential per-stream spec | {seq['tokens_per_sec']:g} "
        f"| {seq['goodput_tokens_per_sec']:g} | {seq['ttft_p99_ms']:g} "
        f"| {seq['tpot_p99_ms']:g} |",
        f"| front door | {fd['tokens_per_sec']:g} "
        f"| {fd['goodput_tokens_per_sec']:g} | {fd['ttft_p99_ms']:g} "
        f"| {fd['tpot_p99_ms']:g} |",
        "",
        f"- goodput speedup **{report['frontdoor_goodput_speedup']:g}x**"
        f" / throughput **{report['frontdoor_throughput_speedup']:g}x** "
        f"(floors {report['gates']['goodput_speedup_floor']:g}x)",
        f"- steady-state recompiles {report['spec_retrace_count']} "
        f"(ceiling 0), host syncs/token "
        f"{report['frontdoor_host_syncs_per_token']:g} (ceiling "
        f"{report['gates']['host_syncs_per_token_ceiling']:g})",
        f"- burn scenario: tenant {burn['burning_tenant']} "
        f"({burn['burn_state']}) submitted "
        f"{burn['submitted_share']:.1%} of traffic, took "
        f"{burn['goodput_share']:.1%} of goodput; healthy TTFT p99 "
        f"{burn['healthy_ttft_p99_ms']:g} ms (hold bound "
        f"{burn['healthy_hold_ms']:g} ms); shed {burn['shed']}",
    ]
    if report["failures"]:
        lines += ["", "## Failures", ""]
        lines += [f"- {f}" for f in report["failures"]]
    return "\n".join(lines) + "\n"


def render_router_markdown(report: dict) -> str:
    fleet = report["fleet"]
    single = report["single"]
    aff = report["affinity"]
    rnd = report["random"]
    kill = report["kill_scenario"]
    lines = [
        "# Serving scale-out gate (SLO router over replicated front doors)",
        "",
        f"**Overall: {'PASS' if report['passed'] else 'FAIL'}**",
        "",
        f"- seed {report['seed']}: {report['streams']} streams over "
        f"{report['engines']} paged engines "
        f"(block size {report['block_size']}), {report['tenants']} "
        f"tenants, {report['prefix_groups']} prefix groups at rate "
        f"{report['prefix_rate']:g}; {report['max_slots']} slots, "
        f"k={report['k']}, {report['max_new_tokens']} tokens each",
        f"- SLO (solo-calibrated): TTFT {report['slo']['ttft_ms']:g} ms, "
        f"TPOT {report['slo']['tpot_ms']:g} ms; virtual-time harness "
        f"(paced window {report['paced_window_s']:g}s)",
        "",
        "| pass | tok/s | goodput tok/s | TTFT p99 (ms) | shed |",
        "|---|---|---|---|---|",
        f"| fleet (burst, N={report['engines']}) "
        f"| {fleet['tokens_per_sec']:g} "
        f"| {fleet['goodput_tokens_per_sec']:g} "
        f"| {fleet['ttft_p99_ms']:g} | {fleet['shed']} |",
        f"| single engine (same burst) | {single['tokens_per_sec']:g} "
        f"| {single['goodput_tokens_per_sec']:g} "
        f"| {single['ttft_p99_ms']:g} | {single['shed']} |",
        f"| affinity policy (paced) | {aff['tokens_per_sec']:g} "
        f"| {aff['goodput_tokens_per_sec']:g} "
        f"| {aff['ttft_p99_ms']:g} | {aff['shed']} |",
        f"| random policy (paced) | {rnd['tokens_per_sec']:g} "
        f"| {rnd['goodput_tokens_per_sec']:g} "
        f"| {rnd['ttft_p99_ms']:g} | {rnd['shed']} |",
        "",
        f"- aggregate goodput **{report['router_goodput_ratio']:g}x** "
        f"the single engine (floor {report['router_scaling_floor']:g}x "
        f"= 0.8xN; throughput {report['router_throughput_ratio']:g}x)",
        f"- affinity TTFT p99 {report['router_affinity_ttft_p99_ms']:g} "
        f"ms vs random {report['router_random_ttft_p99_ms']:g} ms "
        f"(hit rate {report['router_affinity_hit_rate']:.1%})",
        f"- steady-state recompiles {report['spec_retrace_count']} "
        f"(ceiling 0)",
        f"- engine kill: {kill['streams']} streams, engine "
        f"{kill['killed_engine']} killed mid-run, {kill['rebalanced']} "
        f"rebalanced, {kill['lost_requests']} lost, "
        f"{kill['mismatched_streams']} diverged from the uninterrupted "
        f"reference",
    ]
    if report["failures"]:
        lines += ["", "## Failures", ""]
        lines += [f"- {f}" for f in report["failures"]]
    return "\n".join(lines) + "\n"


def run_router_gate(args) -> int:
    from tpuslo.benchmark.router_bench import run_router_bench

    log = lambda msg: print(f"m5gate: {msg}", file=sys.stderr)  # noqa: E731
    report = None
    for attempt in range(max(1, args.router_retries + 1)):
        if attempt:
            log("router-bench retrying (wall-clock gate failed)")
        report = run_router_bench(
            seed=args.router_seed,
            engines=args.router_engines,
            streams=args.router_streams,
            max_slots=args.router_slots,
            k=args.router_k,
            max_new_tokens=args.router_tokens,
            tenants=args.router_tenants,
            prefix_groups=args.router_prefix_groups,
            kill_streams=args.router_kill_streams,
            log=log,
        )
        if report["passed"]:
            break
    if args.summary_json:
        Path(args.summary_json).write_text(
            json.dumps(report, indent=2, default=str) + "\n"
        )
    if args.summary_md:
        Path(args.summary_md).write_text(render_router_markdown(report))
    print(
        f"m5gate: router-bench {'PASS' if report['passed'] else 'FAIL'}"
        + (
            ""
            if report["passed"]
            else f" ({'; '.join(report['failures'])})"
        ),
        file=sys.stderr,
    )
    return 0 if report["passed"] else 1


def run_frontdoor_gate(args) -> int:
    from tpuslo.benchmark.frontdoor_bench import run_frontdoor_bench

    log = lambda msg: print(f"m5gate: {msg}", file=sys.stderr)  # noqa: E731
    report = None
    for attempt in range(max(1, args.frontdoor_retries + 1)):
        if attempt:
            log("frontdoor-bench retrying (wall-clock gate failed)")
        report = run_frontdoor_bench(
            seed=args.frontdoor_seed,
            streams=args.frontdoor_streams,
            max_slots=args.frontdoor_slots,
            k=args.frontdoor_k,
            max_new_tokens=args.frontdoor_tokens,
            tenants=args.frontdoor_tenants,
            arrival=args.frontdoor_arrival,
            passes=args.frontdoor_passes,
            rounds_per_step=args.frontdoor_rounds_per_step,
            log=log,
        )
        if report["passed"]:
            break
    if args.summary_json:
        Path(args.summary_json).write_text(
            json.dumps(report, indent=2, default=str) + "\n"
        )
    if args.summary_md:
        Path(args.summary_md).write_text(
            render_frontdoor_markdown(report)
        )
    print(
        f"m5gate: frontdoor-bench {'PASS' if report['passed'] else 'FAIL'}"
        + (
            ""
            if report["passed"]
            else f" ({'; '.join(report['failures'])})"
        ),
        file=sys.stderr,
    )
    return 0 if report["passed"] else 1


def run_remediation_gate(args) -> int:
    from tpuslo.remediation.sweep import run_remediation_sweep

    report = run_remediation_sweep(
        seed=args.remediation_seed,
        eval_interval_s=args.remediation_eval_interval_s,
        verify_windows=args.remediation_verify_windows,
        provenance_dir=args.remediation_provenance_dir or None,
        log=lambda msg: print(f"m5gate: {msg}", file=sys.stderr),
    )
    if args.summary_json:
        Path(args.summary_json).write_text(
            json.dumps(report.to_dict(), indent=2) + "\n"
        )
    if args.summary_md:
        Path(args.summary_md).write_text(
            render_remediation_markdown(report)
        )
    print(
        f"m5gate: remediation-sweep {'PASS' if report.passed else 'FAIL'}"
        + ("" if report.passed else f" ({'; '.join(report.failures)})"),
        file=sys.stderr,
    )
    return 0 if report.passed else 1


def render_fleet_markdown(report) -> str:
    lines = [
        "# Fleet observability-plane gate",
        "",
        f"**Overall: {'PASS' if report.passed else 'FAIL'}**",
        "",
        f"- {report.nodes} simulated nodes over {report.shards} "
        f"aggregator shards (seed {report.seed}, chaos intensity "
        f"{report.chaos_intensity:g})",
        f"- aggregate ingest: {report.ingest_events_per_sec:,.0f} "
        f"events/s (floor {report.min_ingest_events_per_sec:,.0f}); "
        f"rollup {report.rollup_latency_ms:.1f} ms",
        f"- page dedup: precision {report.precision:.3f} recall "
        f"{report.recall:.3f} macro-F1 {report.macro_f1:.3f}",
        "- failover: "
        + (
            "killed {killed}, re-homed {rehomed} nodes, re-sent "
            "{resent} shipments, {rebalances} ring rebalance(s), "
            "{suppressed} re-emitted window(s) suppressed".format(
                killed=report.failover.get("killed", "?"),
                rehomed=report.failover.get("rehomed_nodes", 0),
                resent=report.failover.get("resent_shipments", 0),
                rebalances=report.failover.get("ring_rebalances", 0),
                suppressed=report.failover.get(
                    "rollup_windows_suppressed", 0
                ),
            )
            if report.failover
            else "(skipped)"
        )
        + f" — lost {len(report.failover_lost)}, duplicated "
        f"{len(report.failover_duplicated)}",
        "",
        "| injection | domain | tenant | expected radius | matched | "
        "radius | exact |",
        "|---|---|---|---|---|---|---|",
    ]
    for m in report.matches:
        lines.append(
            f"| {m.injection} | {m.domain} | {m.namespace} "
            f"| {m.expected_blast_radius} | {m.matched_count} "
            f"| {m.matched_blast_radius or '-'} | {m.exact} |"
        )
    if report.failures:
        lines += ["", "## Failures", ""]
        lines += [f"- {f}" for f in report.failures]
    return "\n".join(lines) + "\n"


def run_fleet_gate(args) -> int:
    from tpuslo.fleet.sweep import run_fleet_sweep

    report = run_fleet_sweep(
        nodes=args.fleet_nodes,
        shards=args.fleet_shards,
        seed=args.fleet_seed,
        chaos_intensity=args.fleet_chaos_intensity,
        events_per_node=args.fleet_events_per_node,
        rounds=args.fleet_rounds,
        kill_shard=not args.fleet_no_kill,
        min_ingest_events_per_sec=args.fleet_min_ingest,
        log=lambda msg: print(f"m5gate: {msg}", file=sys.stderr),
    )
    if args.summary_json:
        Path(args.summary_json).write_text(
            json.dumps(report.to_dict(), indent=2) + "\n"
        )
    if args.summary_md:
        Path(args.summary_md).write_text(render_fleet_markdown(report))
    print(
        f"m5gate: fleet-sweep {'PASS' if report.passed else 'FAIL'}"
        + ("" if report.passed else f" ({'; '.join(report.failures)})"),
        file=sys.stderr,
    )
    return 0 if report.passed else 1


def render_federation_markdown(report) -> str:
    lines = [
        "# Federation-plane gate (two-level tree, 10k nodes)",
        "",
        f"**Overall: {'PASS' if report.passed else 'FAIL'}**",
        "",
        f"- {report.nodes} simulated nodes over {report.clusters} "
        f"clusters x {report.shards_per_cluster} shards (seed "
        f"{report.seed}, churn {report.churn_per_round}/round)",
        f"- aggregate ingest: {report.ingest_events_per_sec:,.0f} "
        f"events/s (floor {report.min_ingest_events_per_sec:,.0f}); "
        f"region rollup {report.rollup_latency_ms:.1f} ms",
        f"- cross-cluster dedup under churn: precision "
        f"{report.precision:.3f} recall {report.recall:.3f}; "
        f"fleet-scope incident spans "
        f"{report.cross_cluster_members} clusters; "
        f"{report.moved_keys} arcs re-homed across "
        f"{report.churn.get('shard_down', 0)} shard restarts, "
        f"{report.churn.get('node_leave', 0)} leaves / "
        f"{report.churn.get('node_join', 0)} joins; staleness "
        f"{report.baseline_staleness_ms:.0f} ms "
        f"(ceiling {report.max_staleness_ms:.0f})",
        "- region failover: "
        + (
            "re-sent {resent} envelope(s) ({accepted} accepted), "
            "{suppressed} re-emitted window(s) suppressed".format(
                resent=report.failover.get("resent_envelopes", 0),
                accepted=report.failover.get("accepted_resends", 0),
                suppressed=report.failover.get(
                    "rollup_windows_suppressed", 0
                ),
            )
            if report.failover
            else "(skipped)"
        )
        + f" — lost {len(report.failover_lost)}, duplicated "
        f"{len(report.failover_duplicated)}",
        "- saturation: "
        + (
            "level reached {level}, sampled by level {sampled}, "
            "precision {p:.3f} recall {r:.3f}, staleness "
            "{stale:.0f} ms".format(
                level=report.saturation.get("max_level_seen", 0),
                sampled=report.saturation.get(
                    "sampled_rows_by_level", {}
                ),
                p=report.saturation.get("precision", 0.0),
                r=report.saturation.get("recall", 0.0),
                stale=report.saturation.get("max_staleness_ms", 0.0),
            )
            if report.saturation
            else "(skipped)"
        ),
        "",
        "| injection | domain | tenant | expected radius | matched | "
        "radius | exact |",
        "|---|---|---|---|---|---|---|",
    ]
    for m in report.matches:
        lines.append(
            f"| {m.injection} | {m.domain} | {m.namespace} "
            f"| {m.expected_blast_radius} | {m.matched_count} "
            f"| {m.matched_blast_radius or '-'} | {m.exact} |"
        )
    if report.failures:
        lines += ["", "## Failures", ""]
        lines += [f"- {f}" for f in report.failures]
    return "\n".join(lines) + "\n"


def run_federation_gate(args) -> int:
    from tpuslo.federation.sweep import run_federation_sweep

    report = run_federation_sweep(
        nodes=args.federation_nodes,
        clusters=args.federation_clusters,
        shards_per_cluster=args.federation_shards_per_cluster,
        seed=args.federation_seed,
        churn_per_round=args.federation_churn_rate,
        rounds=args.federation_rounds,
        events_per_node=args.federation_events_per_node,
        chaos_intensity=args.federation_chaos_intensity,
        kill_region=not args.federation_no_kill,
        saturate=not args.federation_no_saturate,
        min_ingest_events_per_sec=args.federation_min_ingest,
        max_staleness_ms=args.federation_staleness_ceiling_ms,
        log=lambda msg: print(f"m5gate: {msg}", file=sys.stderr),
    )
    if args.summary_json:
        Path(args.summary_json).write_text(
            json.dumps(report.to_dict(), indent=2) + "\n"
        )
    if args.summary_md:
        Path(args.summary_md).write_text(
            render_federation_markdown(report)
        )
    print(
        f"m5gate: federation-sweep "
        f"{'PASS' if report.passed else 'FAIL'}"
        + ("" if report.passed else f" ({'; '.join(report.failures)})"),
        file=sys.stderr,
    )
    return 0 if report.passed else 1


def render_global_markdown(report) -> str:
    ingest = report.ingest
    wan = report.wan
    dark = report.dark
    sb = report.splitbrain
    heal = dark.get("heal_stats", {})
    lines = [
        "# Global-tier gate (three-tier tree under WAN chaos)",
        "",
        f"**Overall: {'PASS' if report.passed else 'FAIL'}**",
        "",
        f"- {report.regions} regions x {report.nodes_per_region} "
        f"nodes (seed {report.seed}, {report.round_s:.0f}s rounds, "
        f"replay budget {report.replay_budget})",
        "- 100k ingest: "
        + (
            "{eps:,.0f} events/s over {nodes} nodes in {regions} "
            "regions / {shards} shards (floor {floor:,.0f}); global "
            "fold {fold:.1f} ms".format(
                eps=ingest.get("events_per_sec", 0),
                nodes=ingest.get("nodes", 0),
                regions=ingest.get("regions", 0),
                shards=ingest.get("shards", 0),
                floor=report.min_ingest_events_per_sec,
                fold=ingest.get("global_fold_ms", 0.0),
            )
            if ingest
            else "(skipped)"
        ),
        f"- WAN identity: precision {report.precision:.3f} recall "
        f"{report.recall:.3f} at "
        f"{wan.get('latency_rounds', 0)}-round latency; "
        f"{wan.get('lost_acks', 0)} acks lost and "
        f"{wan.get('duplicate_envelopes', 0)} replayed envelopes "
        f"absorbed by the gap-tolerant cursor",
        "- hour dark: {region} dark {rounds} rounds, rejoined with "
        "{backlog} spooled envelopes, replayed in {used} rounds "
        "(bound {bound}) — lost {lost}, duplicated {dup}, "
        "{pages} healthy-side pages while dark".format(
            region=dark.get("dark_region", "-"),
            rounds=report.dark_rounds,
            backlog=heal.get("backlog_at_heal", 0),
            used=heal.get("replay_rounds", 0),
            bound=dark.get("replay_bound_rounds", 0),
            lost=len(dark.get("lost", [])),
            dup=len(dark.get("duplicated", [])),
            pages=dark.get("pages_during_dark", 0),
        ),
        "- split brain: {a} page(s) on A / {b} on B during the "
        "partition, {merged} window(s) merged on heal, {sup} "
        "replayed session(s) suppressed, {re} re-pages".format(
            a=len(sb.get("pages_a", [])),
            b=len(sb.get("pages_b", [])),
            merged=sb.get("merged_windows", 0),
            sup=sb.get("suppressed", 0),
            re=sb.get("re_pages", 0),
        ),
        "",
        "| injection | expected radius | expected regions | matched "
        "| radius | regions | exact |",
        "|---|---|---|---|---|---|---|",
    ]
    for m in report.matches:
        lines.append(
            f"| {m.injection} | {m.expected_blast_radius} "
            f"| {','.join(m.expected_regions)} | {m.matched_count} "
            f"| {m.matched_blast_radius or '-'} "
            f"| {','.join(m.matched_regions) or '-'} | {m.exact} |"
        )
    if report.failures:
        lines += ["", "## Failures", ""]
        lines += [f"- {f}" for f in report.failures]
    return "\n".join(lines) + "\n"


def run_global_gate(args) -> int:
    from tpuslo.federation.sweep import run_global_sweep

    report = run_global_sweep(
        regions=args.global_regions,
        nodes_per_region=args.global_nodes_per_region,
        seed=args.global_seed,
        round_s=args.global_round_s,
        replay_budget=args.global_replay_budget,
        wan_latency_rounds=args.global_wan_latency_rounds,
        ack_loss_rounds=args.global_partition_rounds,
        dark_rounds=args.global_dark_duration_rounds,
        ingest_regions=args.global_ingest_regions,
        ingest_nodes_per_region=args.global_ingest_nodes_per_region,
        min_ingest_events_per_sec=args.global_min_ingest,
        measure_ingest_lane=not args.global_no_ingest,
        log=lambda msg: print(f"m5gate: {msg}", file=sys.stderr),
    )
    if args.summary_json:
        Path(args.summary_json).write_text(
            json.dumps(report.to_dict(), indent=2) + "\n"
        )
    if args.summary_md:
        Path(args.summary_md).write_text(
            render_global_markdown(report)
        )
    print(
        f"m5gate: global-sweep "
        f"{'PASS' if report.passed else 'FAIL'}"
        + ("" if report.passed else f" ({'; '.join(report.failures)})"),
        file=sys.stderr,
    )
    return 0 if report.passed else 1


def render_peer_markdown(report) -> str:
    ingest = report.ingest
    ho = report.handover
    sb = report.splitbrain
    dp = report.deposed
    lines = [
        "# Peer-mesh gate (symmetric global root under WAN chaos)",
        "",
        f"**Overall: {'PASS' if report.passed else 'FAIL'}**",
        "",
        f"- {report.peers} mesh peers over {report.regions} regions "
        f"x {report.nodes_per_region} nodes (seed {report.seed}, "
        f"{report.round_s:.0f}s rounds, gossip latency "
        f"{report.gossip_latency_rounds} round(s))",
        "- 100k ingest: "
        + (
            "{eps:,.0f} events/s over {nodes} nodes in {regions} "
            "regions (floor {floor:,.0f}); global fold "
            "{fold:.1f} ms".format(
                eps=ingest.get("events_per_sec", 0),
                nodes=ingest.get("nodes", 0),
                regions=ingest.get("regions", 0),
                floor=report.min_ingest_events_per_sec,
                fold=ingest.get("global_fold_ms", 0.0),
            )
            if ingest
            else "(skipped)"
        ),
        "- handover: root dark at round {kill}, successor at round "
        "{take} (bound {bound}), {pages} page(s) while dark, "
        "{failovers} region failovers — lost {lost}, duplicated "
        "{dup}, split {split}".format(
            kill=ho.get("kill_round", "-"),
            take=ho.get("first_successor_round", "-"),
            bound=ho.get("kill_round", 0)
            + ho.get("election_bound_rounds", 0),
            pages=ho.get("pages_during_dark", 0),
            failovers=ho.get("failovers", 0),
            lost=len(ho.get("lost", [])),
            dup=len(ho.get("duplicated", [])),
            split=len(ho.get("split", [])),
        ),
        "- split brain: sides elected a={a} b={b}, {sup} replayed "
        "session(s) suppressed across the heal, converged on "
        "{leaders} at epoch(s) {epochs} — lost {lost}, duplicated "
        "{dup}".format(
            a=(sb.get("sides_elected") or {}).get("a"),
            b=(sb.get("sides_elected") or {}).get("b"),
            sup=sb.get("replays_suppressed", 0),
            leaders=sorted(set((sb.get("final_leaders") or {}).values())),
            epochs=sorted(set((sb.get("final_epochs") or {}).values())),
            lost=len(sb.get("lost", [])),
            dup=len(sb.get("duplicated", [])),
        ),
        "- deposed root: {rounds} rounds dark, {fenced} stale "
        "page(s) fenced at heal ({restamped} re-stamped under the "
        "won-back epoch), {rej} stale-epoch rejection(s) counted on "
        "the survivors, {emits} stale emission(s) — lost {lost}, "
        "duplicated {dup}".format(
            rounds=dp.get("dark_rounds", 0),
            fenced=dp.get("stale_pages_dropped", 0),
            restamped=dp.get("pages_restamped", 0),
            rej=dp.get("stale_epoch_rejections", 0),
            emits=len(dp.get("stale_emits", [])),
            lost=len(dp.get("lost", [])),
            dup=len(dp.get("duplicated", [])),
        ),
        "",
        "| lane | baseline clusters | chaos clusters | elections |",
        "|---|---|---|---|",
    ]
    for label, lane in (
        ("handover", ho), ("split-brain", sb), ("deposed-root", dp)
    ):
        lines.append(
            f"| {label} | {lane.get('baseline_clusters', '-')} "
            f"| {lane.get('chaos_clusters', '-')} "
            f"| {len(lane.get('elections', []))} |"
        )
    if report.failures:
        lines += ["", "## Failures", ""]
        lines += [f"- {f}" for f in report.failures]
    return "\n".join(lines) + "\n"


def run_peer_gate(args) -> int:
    from tpuslo.federation.sweep import run_peer_sweep

    report = run_peer_sweep(
        peers=args.peer_count,
        regions=args.global_regions,
        nodes_per_region=args.global_nodes_per_region,
        seed=args.global_seed,
        round_s=args.global_round_s,
        replay_budget=args.global_replay_budget,
        gossip_latency_rounds=args.peer_gossip_latency_rounds,
        root_dark_rounds=args.root_dark_rounds,
        deposed_dark_rounds=args.peer_deposed_dark_rounds,
        ingest_regions=args.global_ingest_regions,
        ingest_nodes_per_region=args.global_ingest_nodes_per_region,
        min_ingest_events_per_sec=args.global_min_ingest,
        measure_ingest_lane=not args.peer_no_ingest,
        log=lambda msg: print(f"m5gate: {msg}", file=sys.stderr),
    )
    if args.summary_json:
        Path(args.summary_json).write_text(
            json.dumps(report.to_dict(), indent=2) + "\n"
        )
    if args.summary_md:
        Path(args.summary_md).write_text(render_peer_markdown(report))
    print(
        f"m5gate: peer-sweep "
        f"{'PASS' if report.passed else 'FAIL'}"
        + ("" if report.passed else f" ({'; '.join(report.failures)})"),
        file=sys.stderr,
    )
    return 0 if report.passed else 1


def render_live_markdown(report) -> str:
    lines = [
        "# Live deployment-plane gate (process tree over real sockets)",
        "",
        f"**Overall: {'PASS' if report.passed else 'FAIL'}**",
        "",
        "- topology: agent -> cluster fleetagg -> region fleetagg over "
        "livenet frames, plus the supervised front door; every run "
        "audits the incident ledgers content-wise (unique ids, full "
        "member coverage at the region), the agent's cadence line, "
        "listener rejects, and warm-resume evidence",
        "",
        "| run | seed | restarts | resumed | max level | flushes/"
        "cycles | cluster inc | region inc | dup | lost | rejected | "
        "dropped B | pass |",
        "|---|---|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    for run in report.runs:
        cadence = run.cadence or {}
        flushes = (
            f"{cadence.get('flushes', '-')}/{cadence.get('cycles', '-')}"
        )
        lines.append(
            f"| {run.target} | {run.seed} | {run.restarts} "
            f"| {','.join(run.restored_evidence) or '-'} "
            f"| {cadence.get('max_level', '-')} | {flushes} "
            f"| {run.cluster_incidents} | {run.region_incidents} "
            f"| {run.duplicate_incident_ids} | {run.lost_members} "
            f"| {run.frames_rejected} | {run.dropped_bytes} "
            f"| {run.passed} |"
        )
    flips = [
        r for r in report.runs if r.target == "frontdoor"
    ]
    if flips:
        run = flips[0]
        lines += [
            "",
            f"- front door: remediation applied = "
            f"{run.remediation_applied}, admission order flipped = "
            f"{run.order_flipped} (and survived the kill -9)",
        ]
    if report.failures:
        lines += ["", "## Failures", ""]
        lines += [f"- {f}" for f in report.failures]
    return "\n".join(lines) + "\n"


def run_live_gate(args) -> int:
    from tpuslo.chaos.procs import run_live_sweep

    targets = tuple(
        t.strip() for t in args.live_chaos_targets.split(",") if t.strip()
    )
    report = run_live_sweep(
        args.live_chaos_root,
        targets=targets,
        seed=args.live_chaos_seed,
        log=lambda msg: print(f"m5gate: {msg}", file=sys.stderr),
    )
    if args.summary_json:
        Path(args.summary_json).write_text(
            json.dumps(report.to_dict(), indent=2) + "\n"
        )
    if args.summary_md:
        Path(args.summary_md).write_text(render_live_markdown(report))
    print(
        f"m5gate: live-chaos {'PASS' if report.passed else 'FAIL'}"
        + ("" if report.passed else f" ({'; '.join(report.failures)})"),
        file=sys.stderr,
    )
    return 0 if report.passed else 1


def render_chaos_markdown(report) -> str:
    lines = [
        "# Telemetry chaos-sweep gate",
        "",
        f"**Overall: {'PASS' if report.passed else 'FAIL'}**",
        "",
        f"- scenario: `{report.scenario}` x{report.count} "
        f"(seed {report.seed}, {report.hosts} hosts)",
        f"- no-chaos baseline macro-F1: {report.baseline_macro_f1:.4f}",
        f"- tolerance at <= moderate intensity: "
        f"{100 * report.rel_tolerance:.0f}% relative",
        "",
        "| intensity | gated F1 | ungated F1 | quarantined | dup | "
        "late | skew-corrected |",
        "|---|---|---|---|---|---|---|",
    ]
    for point in report.points:
        gate = point.gate_snapshot
        lines.append(
            f"| {point.intensity:g} | {point.gated_macro_f1:.4f} "
            f"| {point.ungated_macro_f1:.4f} "
            f"| {gate.get('quarantined', 0)} "
            f"| {gate.get('duplicates', 0)} "
            f"| {gate.get('late_admitted', 0)} "
            f"| {gate.get('skew_corrected', 0)} |"
        )
    if report.failures:
        lines += ["", "## Failures", ""]
        lines += [f"- {f}" for f in report.failures]
    return "\n".join(lines) + "\n"


def run_chaos_gate(args) -> int:
    from tpuslo.attribution.pipeline import run_chaos_sweep

    intensities = tuple(
        float(v) for v in args.chaos_intensities.split(",") if v.strip()
    )
    report = run_chaos_sweep(
        scenario=args.chaos_scenario,
        count=args.chaos_count,
        seed=args.chaos_seed,
        intensities=intensities,
        hosts=args.chaos_hosts,
        rel_tolerance=args.chaos_rel_tolerance,
    )
    if args.summary_json:
        Path(args.summary_json).write_text(
            json.dumps(report.to_dict(), indent=2) + "\n"
        )
    if args.summary_md:
        Path(args.summary_md).write_text(render_chaos_markdown(report))
    for point in report.points:
        print(
            f"m5gate: chaos intensity {point.intensity:g}: "
            f"gated F1={point.gated_macro_f1:.4f} "
            f"ungated F1={point.ungated_macro_f1:.4f}",
            file=sys.stderr,
        )
    print(
        f"m5gate: chaos-sweep {'PASS' if report.passed else 'FAIL'}"
        + ("" if report.passed else f" ({'; '.join(report.failures)})"),
        file=sys.stderr,
    )
    return 0 if report.passed else 1


def render_deviceplane_markdown(report) -> str:
    lines = [
        "# Device-plane truth gate (ledger + roofline + heldout)",
        "",
        f"**Overall: {'PASS' if report.passed else 'FAIL'}**",
        "",
        f"- seed: {report.seed}",
        "",
        "## Ledger (synthetic-xprof lane)",
        "",
        "| variant | launches | substantive join | raw join | "
        "unexplained share | idle gap (ms) | buckets sum |",
        "|---|---|---|---|---|---|---|",
    ]
    for run in report.ledger_runs:
        led = run["ledger"]
        buckets = led["buckets_ms"]
        lines.append(
            f"| {run['variant']} | {led['launches']} "
            f"| {led['substantive_join_rate']:.4f} "
            f"| {led['raw_join_rate']:.4f} "
            f"| {led['unexplained_share']:.4f} "
            f"| {buckets.get('idle_gap', 0.0):.1f} "
            f"| {led['bucket_sum_ms']:.1f}/{led['total_device_time_ms']:.1f} |"
        )
    decode = report.roofline.get("decode") or {}
    prefill = report.roofline.get("prefill") or {}
    attributions = report.roofline.get("attributions") or {}
    lines += [
        "",
        "## Roofline",
        "",
        f"- decode: {decode.get('verdict', '?')} "
        f"({decode.get('hbm_bw_pct', 0)}% of HBM roof, "
        f"MFU {decode.get('mfu_pct', 0)}%)",
        f"- prefill: {prefill.get('verdict', '?')} "
        f"(MFU {prefill.get('mfu_pct', 0)}%, "
        f"{prefill.get('hbm_bw_pct', 0)}% of HBM roof)",
        f"- serving attributions with verdict: "
        f"{attributions.get('with_verdict', 0)}/"
        f"{attributions.get('total', 0)} "
        f"(top-1 correct {attributions.get('top1_correct', 0)})",
    ]
    if report.heldout:
        lines += [
            "",
            "## Heldout (with tpu_preemption + host_noisy_neighbor)",
            "",
            f"- full-domain macro-F1: {report.heldout.get('full_domain')}",
            f"- new-domain F1 at sigma 1.0: "
            f"{report.heldout.get('new_domain_f1')}",
        ]
    if report.failures:
        lines += ["", "## Failures", ""]
        lines += [f"- {f}" for f in report.failures]
    return "\n".join(lines) + "\n"


def run_deviceplane_gate(args) -> int:
    from tpuslo.deviceplane.sweep import run_deviceplane_sweep

    report = run_deviceplane_sweep(
        seed=args.deviceplane_seed,
        steps=args.deviceplane_steps,
        heldout_count=args.deviceplane_heldout_count,
        skip_heldout=args.deviceplane_skip_heldout,
    )
    if args.summary_json:
        Path(args.summary_json).write_text(
            json.dumps(report.to_dict(), indent=2) + "\n"
        )
    if args.summary_md:
        Path(args.summary_md).write_text(
            render_deviceplane_markdown(report)
        )
    for run in report.ledger_runs:
        led = run["ledger"]
        print(
            f"m5gate: deviceplane {run['variant']}: substantive "
            f"join {led['substantive_join_rate']:.4f} (raw "
            f"{led['raw_join_rate']:.4f}), unexplained "
            f"{led['unexplained_share']:.4f}",
            file=sys.stderr,
        )
    if report.heldout:
        print(
            "m5gate: deviceplane heldout full-domain "
            f"{report.heldout.get('full_domain')}",
            file=sys.stderr,
        )
    print(
        f"m5gate: deviceplane-sweep {'PASS' if report.passed else 'FAIL'}"
        + ("" if report.passed else f" ({'; '.join(report.failures)})"),
        file=sys.stderr,
    )
    return 0 if report.passed else 1


def render_profiler_markdown(report) -> str:
    lines = [
        "# Continuous-profiler gate (overhead + governor + joins + "
        "parity + preemption)",
        "",
        f"**Overall: {'PASS' if report.passed else 'FAIL'}**",
        "",
        f"- seed: {report.seed}",
        "",
        "## Overhead",
        "",
        f"- EMA {report.overhead.get('overhead_ema_pct', 0)}% of "
        f"{report.overhead.get('budget_pct', 0)}% budget over "
        f"{report.overhead.get('windows', 0)} windows "
        f"(mean capture cost "
        f"{report.overhead.get('mean_capture_cost_ms', 0)} ms)",
        "",
        "## Governor",
        "",
        f"- degraded at cycle "
        f"{report.governor.get('degraded_at_cycle')}, stride -> "
        f"{report.governor.get('stride_after_degrade')}; forced "
        "eviction capture carried "
        f"{report.governor.get('forced_capture_evictions', 0)} "
        "eviction(s); re-engaged after "
        f"{report.governor.get('reengaged_after_cycles')} cycle(s) "
        f"({report.governor.get('degradations', 0)} degradation(s), "
        f"{report.governor.get('reengagements', 0)} reengagement(s))",
        "",
        "## Joins (per window)",
        "",
        f"- min substantive "
        f"{report.joins.get('min_substantive_join_rate', 0)} "
        f"(floor {report.joins.get('floor', 0)}); mean raw "
        f"{report.joins.get('mean_raw_join_rate', 0)} reported "
        "alongside",
        "",
        "## Window/full-capture parity",
        "",
        f"- worst bucket drift "
        f"{report.parity.get('worst_bucket_drift_us', 0)} us "
        f"({report.parity.get('worst_bucket', '?')}) over "
        f"{report.parity.get('windows', 0)} windows; total drift "
        f"{report.parity.get('total_drift_us', 0)} us",
        "",
        "## Preemption e2e",
        "",
        f"- window #{report.preemption.get('window_index', '?')}: "
        f"idle gap {report.preemption.get('idle_gap_ms', 0)} ms vs "
        f"baseline {report.preemption.get('baseline_max_idle_gap_ms', 0)} "
        f"ms -> {report.preemption.get('top_domain', '?')} "
        f"(posterior {report.preemption.get('posterior', 0)}), "
        f"window verdict {report.preemption.get('verdict', '?')}",
    ]
    if report.failures:
        lines += ["", "## Failures", ""]
        lines += [f"- {f}" for f in report.failures]
    return "\n".join(lines) + "\n"


def run_profiler_gate(args) -> int:
    from tpuslo.deviceplane.profiler import run_profiler_sweep

    report = run_profiler_sweep(
        seed=args.profiler_seed,
        cycles=args.profiler_cycles,
        parity_windows=args.profiler_parity_windows,
    )
    if args.summary_json:
        Path(args.summary_json).write_text(
            json.dumps(report.to_dict(), indent=2) + "\n"
        )
    if args.summary_md:
        Path(args.summary_md).write_text(render_profiler_markdown(report))
    print(
        "m5gate: profiler overhead EMA "
        f"{report.overhead.get('overhead_ema_pct', 0)}% of "
        f"{report.overhead.get('budget_pct', 0)}% budget; min "
        "substantive join "
        f"{report.joins.get('min_substantive_join_rate', 0)}; parity "
        f"drift {report.parity.get('worst_bucket_drift_us', 0)}us; "
        "preemption -> "
        f"{report.preemption.get('top_domain', '?')} "
        f"({report.preemption.get('posterior', 0)})",
        file=sys.stderr,
    )
    print(
        f"m5gate: profiler-sweep {'PASS' if report.passed else 'FAIL'}"
        + ("" if report.passed else f" ({'; '.join(report.failures)})"),
        file=sys.stderr,
    )
    return 0 if report.passed else 1


def render_markdown(summary: releasegate.Summary) -> str:
    lines = [
        "# M5 release gate summary",
        "",
        f"**Overall: {'PASS' if summary.passed else 'FAIL'}**",
        "",
        f"- candidate: `{summary.candidate_root}`",
        f"- baseline: `{summary.baseline_root}`",
        "",
        "## B5 overhead",
        f"- pass: {summary.overhead.passed}",
        f"- max node p95: {summary.overhead.max_node_p95_pct:.4f}% "
        f"({summary.overhead.max_node_p95_node}) vs "
        f"threshold {summary.overhead.threshold_pct:.2f}%",
        f"- mean: {summary.overhead.mean_observed_pct:.4f}% over "
        f"{summary.overhead.sample_count} samples",
        "",
        "## D3 rerun variance",
        "",
        "| scenario | runs | ttft CV% | tokens CV% | err CV% | pass |",
        "|---|---|---|---|---|---|",
    ]
    for row in summary.variance.scenarios:
        lines.append(
            f"| {row.scenario} | {row.run_count} | {row.variance_pct:.2f} "
            f"| {row.tokens_variance_pct:.2f} "
            f"| {row.error_rate_variance_pct:.2f} | {row.passed} |"
        )
    lines += [
        "",
        "## E3 significance",
        "",
        "| scenario | n | regression % | p | CI95 | Cliff's δ | verdict |",
        "|---|---|---|---|---|---|---|",
    ]
    for row in summary.significance.scenarios:
        verdict = (
            "informational"
            if row.informational_only
            else ("pass" if row.passed else "FAIL")
        )
        ci = f"[{row.bootstrap_delta_ci95[0]:.2f}, {row.bootstrap_delta_ci95[1]:.2f}]"
        lines.append(
            f"| {row.scenario} | {row.candidate_n}/{row.baseline_n} "
            f"| {row.ttft_regression_pct:.2f} | {row.mann_whitney_p_value:.4f} "
            f"| {ci} | {row.cliffs_delta:.3f} | {verdict} |"
        )
    if summary.failures:
        lines += ["", "## Failures", ""]
        lines += [f"- {f}" for f in summary.failures]
    return "\n".join(lines) + "\n"


def run_lint_gate() -> int:
    from tpuslo.analysis.__main__ import main as lint_main

    rc = lint_main([])
    print(f"m5gate: lint {'PASS' if rc == 0 else 'FAIL'}", file=sys.stderr)
    return rc


def run_racecheck_gate() -> int:
    import os
    import subprocess

    from tpuslo.analysis.racecheck import ENV_FLAG, SMOKE_SUITES

    env = dict(os.environ, **{ENV_FLAG: "1"})
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", *SMOKE_SUITES, "-q"], env=env
    )
    print(
        f"m5gate: racecheck-smoke "
        f"{'PASS' if proc.returncode == 0 else 'FAIL'}",
        file=sys.stderr,
    )
    return proc.returncode


def run_jitcheck_gate() -> int:
    import os
    import subprocess

    from tpuslo.analysis.jitaudit import ENV_FLAG, SMOKE_SUITES

    env = dict(os.environ, **{ENV_FLAG: "1"})
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", *SMOKE_SUITES, "-q"], env=env
    )
    print(
        f"m5gate: jitcheck-smoke "
        f"{'PASS' if proc.returncode == 0 else 'FAIL'}",
        file=sys.stderr,
    )
    return proc.returncode


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.lint:
        return run_lint_gate()
    if args.racecheck_smoke:
        return run_racecheck_gate()
    if args.jitcheck_smoke:
        return run_jitcheck_gate()
    if args.burn_sweep:
        return run_burn_gate(args)
    if args.remediation_sweep:
        return run_remediation_gate(args)
    if args.frontdoor_bench:
        return run_frontdoor_gate(args)
    if args.router_bench:
        return run_router_gate(args)
    if args.deviceplane_sweep:
        return run_deviceplane_gate(args)
    if args.profiler_sweep:
        return run_profiler_gate(args)
    if args.fleet_sweep:
        return run_fleet_gate(args)
    if args.federation_sweep:
        return run_federation_gate(args)
    if args.global_sweep:
        return run_global_gate(args)
    if args.peer_sweep:
        return run_peer_gate(args)
    if args.live_chaos_sweep:
        return run_live_gate(args)
    if args.crash_sweep:
        return run_crash_gate(args)
    if args.chaos_sweep:
        return run_chaos_gate(args)
    cfg = releasegate.Config(
        candidate_root=args.candidate_root,
        baseline_root=args.baseline_root,
        baseline_manifest_path=args.baseline_manifest,
        candidate_ref=args.candidate_ref,
        candidate_commit=args.candidate_commit,
        require_baseline_manifest=args.require_baseline_manifest,
        scenarios=[s.strip() for s in args.scenarios.split(",") if s.strip()],
        max_overhead_pct=args.max_overhead_pct,
        max_variance_pct=args.max_variance_pct,
        min_runs_per_scenario=args.min_runs,
        regression_pct_limit=args.regression_pct_limit,
        significance_alpha=args.alpha,
        bootstrap_iterations=args.bootstrap_iterations,
        bootstrap_seed=args.bootstrap_seed,
        min_samples_per_scenario=args.min_samples,
        min_cliffs_delta_for_failure=args.min_cliffs_delta,
    )
    summary = releasegate.evaluate(cfg)
    if args.summary_json:
        Path(args.summary_json).write_text(
            json.dumps(summary.to_dict(), indent=2) + "\n"
        )
    if args.summary_md:
        Path(args.summary_md).write_text(render_markdown(summary))
    print(
        f"m5gate: {'PASS' if summary.passed else 'FAIL'}"
        + ("" if summary.passed else f" ({'; '.join(summary.failures)})"),
        file=sys.stderr,
    )
    return 0 if summary.passed else 1


if __name__ == "__main__":
    raise SystemExit(main())
