"""L9 CLI surface — twelve binaries behind one dispatcher.

Reference: ``cmd/`` (agent, collector, attributor, benchgen,
faultreplay, faultinject, correlationeval, m5gate, sloctl, loadgen,
schemavalidate; ``docs/ARCHITECTURE.md:60-74``).  Invoke as
``python -m tpuslo <binary> [flags]``.
"""
