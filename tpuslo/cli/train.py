"""``tpuslo train`` — demo training runs with checkpoint/resume.

The operator surface over :mod:`tpuslo.models.trainer`: a deterministic
training session on the demo Llama family, sharded over whatever mesh
the host offers (dp/fsdp/tp factorization via
:func:`tpuslo.parallel.mesh.plan_for_devices`), emitting one JSON line
per step so the agent/collector pipeline can observe loss progress and
checkpoint-write stalls (the ``host_offload_stall`` fault domain).

No reference counterpart — the reference has no training path at all
(SURVEY.md §2.5); this exists because the TPU rebuild's observed
workload includes training-shaped jobs.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tpuslo train",
        description="deterministic demo training with checkpoint/resume",
    )
    parser.add_argument(
        "--model",
        choices=(
            "llama_tiny", "llama32_1b", "llama32_3b",
            "mixtral_tiny", "mixtral_2b6",
        ),
        default="llama_tiny",
        help="mixtral_* trains the MoE family over a dp x ep mesh "
        "(experts sharded; GSPMD token exchanges)",
    )
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--corpus", help="text file, one document per line")
    parser.add_argument("--checkpoint-dir", default="")
    parser.add_argument("--ckpt-every", type=int, default=0)
    parser.add_argument(
        "--slices", type=int, default=1,
        help="multi-slice plan: factor a dcn data-parallel axis out "
        "first (cross-slice gradient psum is the only DCN collective)",
    )
    parser.add_argument(
        "--cpu-mesh",
        type=int,
        default=0,
        metavar="N",
        help="force an N-device virtual CPU mesh (tests/CI)",
    )
    args = parser.parse_args(argv)

    if args.cpu_mesh:
        import os
        import re

        flags = os.environ.get("XLA_FLAGS", "")
        flag = f"--xla_force_host_platform_device_count={args.cpu_mesh}"
        if "xla_force_host_platform_device_count" in flags:
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", flag, flags
            )
        else:
            flags = f"{flags} {flag}".strip()
        os.environ["XLA_FLAGS"] = flags
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    from tpuslo.models.trainer import TrainerConfig, train
    from tpuslo.parallel.mesh import make_mesh, plan_for_devices

    step_builder = None
    if args.model.startswith("mixtral"):
        import math

        import numpy as np
        from jax.sharding import Mesh

        from tpuslo.models import mixtral

        if args.slices > 1:
            parser.error("--slices applies to the llama dp/fsdp/tp plan")
        cfg = getattr(mixtral, args.model)(max_seq_len=max(args.seq_len, 64))
        n = len(jax.devices())
        ep = math.gcd(n, cfg.n_experts)
        dp = n // ep
        mesh = Mesh(np.array(jax.devices()).reshape(dp, ep), ("dp", "ep"))
        mesh_summary = {"dp": dp, "ep": ep}
        step_builder = mixtral.build_moe_train_step
    else:
        from tpuslo.models import llama

        cfg = getattr(llama, args.model)(max_seq_len=max(args.seq_len, 64))
        plan = plan_for_devices(len(jax.devices()), slices=args.slices)
        mesh = make_mesh(plan)
        mesh_summary = {
            "dcn": plan.dcn, "dp": plan.dp,
            "fsdp": plan.fsdp, "tp": plan.tp,
        }

    if args.corpus:
        with open(args.corpus, encoding="utf-8") as fh:
            texts = [line.rstrip("\n") for line in fh if line.strip()]
    else:
        texts = [
            f"synthetic document {i}: the five boxing wizards jump quickly"
            for i in range(200)
        ]

    tcfg = TrainerConfig(
        steps=args.steps,
        batch=args.batch,
        seq_len=args.seq_len,
        seed=args.seed,
        ckpt_every=args.ckpt_every,
    )
    result = train(
        cfg, mesh, texts, tcfg,
        checkpoint_dir=args.checkpoint_dir or None,
        step_builder=step_builder,
    )
    for i, loss in enumerate(result["losses"]):
        print(
            json.dumps(
                {"step": result["first_step"] + i + 1, "loss": round(loss, 6)}
            )
        )
    print(
        json.dumps(
            {
                "done": True,
                "model": args.model,
                "mesh": mesh_summary,
                "first_step": result["first_step"],
                "last_step": result["last_step"],
                "final_loss": round(result["losses"][-1], 6)
                if result["losses"]
                else None,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
