"""Benchgen: wraps the benchmark artifact-bundle generator.

Reference: ``cmd/benchgen/main.go``.
"""

from __future__ import annotations

import argparse
import sys

from tpuslo import benchmark
from tpuslo.faultreplay import supported_scenarios


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpuslo benchgen", description=__doc__)
    p.add_argument("--output-dir", default="artifacts/benchmark")
    p.add_argument("--scenario", default="tpu_mixed", choices=supported_scenarios())
    p.add_argument("--count", type=int, default=55)
    p.add_argument("--mode", default="bayes", choices=["bayes", "rule"])
    p.add_argument("--input", default="", help="fault samples JSONL override")
    p.add_argument("--node", default="tpu-vm-0")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    bundle = benchmark.generate_artifacts(
        benchmark.Options(
            output_dir=args.output_dir,
            scenario=args.scenario,
            count=args.count,
            mode=args.mode,
            input_samples=args.input,
            node=args.node,
        )
    )
    print(
        f"benchgen: bundle at {bundle.output_dir} "
        f"(accuracy={bundle.summary['accuracy']:.4f}, "
        f"macro_f1={bundle.summary['macro_f1']:.4f})",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
