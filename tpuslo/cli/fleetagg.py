"""fleetagg: aggregator-role binary of the fleet observability plane.

One process hosts one or more :class:`~tpuslo.fleet.AggregatorShard`\\ s
behind a consistent hash ring and consumes node-agent shipment logs
(``agent --fleet-upstream`` output, the JSONL form of the TPL104-
governed wire contract).  Each shipment decodes zero-copy, dedups by
per-node sequence, merges, gates, and folds; closed windows attribute
through the shared Bayesian posterior and collapse through the fleet
rollup into one incident per (fault domain x blast radius).

Outputs:

* ``--incidents-out`` — fleet incidents as JSONL (``sloctl fleet
  incidents`` renders the table).
* ``--provenance-out`` — one ProvenanceRecord per fleet incident with
  the ``members`` block (``sloctl explain`` drills a fleet page down
  to its contributing node incidents).
* ``--state-out`` — shard/node state snapshot (``sloctl fleet nodes``
  renders per-node reporting/stale status; a restarted aggregator
  absorbs it via the PR 4 runtime registry shape).

One binary also hosts the two federation tiers above the cluster:
``--region`` folds per-cluster envelope logs into fleet pages with
cross-cluster identity (``--global-out`` ships the region→global
envelope), and ``--global-tier`` folds per-region envelope logs into
globally-identified pages (``sloctl fleet incidents --global``
renders them; ``--merge-peer`` is the partition-heal handshake).

The global tier also runs as a symmetric N-peer mesh (``--peer``):
peers gossip mergeable emitted-window registries and elect one root
by stable rank, epoch-fenced.  Batch runs exchange
``--peer-gossip-out`` files as anti-entropy rounds; ``--peer
--listen`` is the live mesh front door, accepting region envelopes
and peer gossip on one socket.
"""

from __future__ import annotations

import argparse
import json
import sys
from datetime import datetime, timezone
from typing import Any

from tpuslo.fleet.aggregator import AggregatorShard
from tpuslo.fleet.ring import HashRing
from tpuslo.fleet.rollup import FleetIncident, FleetRollup
from tpuslo.fleet.wire import WireContractError
from tpuslo.ingest.gate import GateConfig


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpuslo fleetagg", description=__doc__
    )
    p.add_argument(
        "inputs",
        nargs="*",
        help="shipment logs written by `agent --fleet-upstream` "
        "(omitted in live mode: --listen replaces the file hop)",
    )
    p.add_argument(
        "--shards",
        type=int,
        default=1,
        help="aggregator shards to host in this process (placement by "
        "the same consistent hash ring the agents compute)",
    )
    p.add_argument("--shard-prefix", default="agg")
    p.add_argument(
        "--window-ns",
        type=int,
        default=2_000_000_000,
        help="attribution window width",
    )
    p.add_argument(
        "--rollup-gap-ns",
        type=int,
        default=5_000_000_000,
        help="session gap closing a (tenant, domain) rollup group",
    )
    p.add_argument(
        "--min-confidence",
        type=float,
        default=0.5,
        help="attribution confidence floor for a node incident",
    )
    p.add_argument("--incidents-out", default="")
    p.add_argument("--provenance-out", default="")
    p.add_argument("--state-out", default="")
    p.add_argument(
        "--restore-state",
        default="",
        help="absorb a prior --state-out snapshot before ingesting "
        "(failover re-home: each node fragment lands on whichever "
        "shard the ring owns now; in --region mode, restore the "
        "region rollup + per-cluster cursors)",
    )
    # ---- federation tree (tpuslo.federation) --------------------------
    p.add_argument(
        "--cluster-id",
        default="",
        help="run as ONE cluster of the federation tree: emitted node "
        "incidents carry this cluster identity and the state "
        "snapshot is scoped to it (sloctl fleet nodes --cluster)",
    )
    p.add_argument(
        "--region-out",
        default="",
        help="write this cluster's region-envelope JSONL (the "
        "cluster->region wire hop; feed it to `fleetagg --region`)",
    )
    p.add_argument(
        "--region-seq",
        type=int,
        default=0,
        help="monotonic per-cluster envelope sequence for "
        "--region-out (bump per run so the region's seq dedup "
        "admits it)",
    )
    p.add_argument(
        "--region",
        action="store_true",
        help="run as the REGION aggregator: inputs are region-envelope "
        "JSONL logs written by per-cluster `fleetagg --region-out` "
        "runs; incidents collapse with cross-cluster identity",
    )
    p.add_argument("--region-id", default="region-0")
    # ---- global tier (region -> global hop) ---------------------------
    p.add_argument(
        "--global-out",
        default="",
        help="--region mode: also write this region's global-envelope "
        "JSONL (the region->global wire hop; feed it to "
        "`fleetagg --global-tier`)",
    )
    p.add_argument(
        "--global-seq",
        type=int,
        default=0,
        help="per-region envelope sequence for --global-out (bump per "
        "run; the global tier's gap-tolerant cursor accepts each "
        "seq exactly once, in any arrival order)",
    )
    p.add_argument(
        "--global-tier",
        action="store_true",
        help="run as the GLOBAL aggregator: inputs are global-envelope "
        "JSONL logs written by per-region `fleetagg --region "
        "--global-out` runs; pages gain cross-region identity and "
        "partition scope",
    )
    p.add_argument("--global-id", default="global-0")
    p.add_argument(
        "--merge-peer",
        default="",
        help="--global-tier/--peer: a peer's --state-out snapshot; "
        "union its emitted-window registry before ingesting (the "
        "one-shot partition-heal handshake — under --peer this is "
        "just one round of the gossip fold without liveness)",
    )
    # ---- symmetric global peer mesh (gossip + election) ---------------
    p.add_argument(
        "--peer",
        action="store_true",
        help="run as ONE peer of the symmetric global mesh: inputs "
        "are global-envelope JSONL logs (this peer's home regions) "
        "and/or peer-envelope JSONL gossip logs written by other "
        "peers' --peer-gossip-out; with --listen the process is the "
        "live mesh front door (region frames + gossip frames on one "
        "socket)",
    )
    p.add_argument(
        "--peer-ids",
        default="",
        help="comma-separated full mesh membership (sorted order = "
        "stable election rank); defaults to just --global-id — a "
        "mesh of one behaves exactly like --global-tier",
    )
    p.add_argument(
        "--peer-gossip-out",
        default="",
        help="batch --peer: write one outbound peer envelope per "
        "remote peer as JSONL (feed it to the other peers' next "
        "batch run — the file-hop form of an anti-entropy round; "
        "supersedes the one-shot --merge-peer handshake)",
    )
    p.add_argument(
        "--peer-upstream",
        action="append",
        default=[],
        metavar="PEER=tcp://HOST:PORT",
        help="live --peer: one remote mesh peer's front door "
        "(repeatable); each gets a spool-backed gossip client "
        "under --spool-dir",
    )
    p.add_argument(
        "--peer-stale-after-ns",
        type=int,
        default=180_000_000_000,
        help="--peer: a mesh peer unheard (directly or transitively) "
        "for longer than this is presumed dead and the bully rule "
        "elects past it",
    )
    p.add_argument(
        "--region-stale-after-ns",
        type=int,
        default=120_000_000_000,
        help="--global-tier: a region whose head lags the fleet head "
        "by more than this is unreachable — it ages out of the "
        "session-close clock and pages emit partition-scoped",
    )
    # ---- live deployment plane (tpuslo.livenet) -----------------------
    p.add_argument(
        "--listen",
        default="",
        help="HOST:PORT — run live: accept shipment frames (cluster "
        "mode) or region-envelope frames (--region mode) over the "
        "livenet socket transport instead of reading input logs",
    )
    p.add_argument(
        "--region-upstream",
        default="",
        help="live mode: ship region envelopes here each tick — "
        "tcp://host:port (livenet client, spool-backed) or a JSONL "
        "path appended per tick (the file-hop fallback)",
    )
    p.add_argument(
        "--run-for-s",
        type=float,
        default=0.0,
        help="live mode: stop after this many seconds (0 = run until "
        "SIGTERM/SIGINT)",
    )
    p.add_argument(
        "--tick-s",
        type=float,
        default=0.5,
        help="live mode: window-close / envelope-ship / heartbeat "
        "cadence",
    )
    p.add_argument(
        "--pressure-out",
        default="",
        help="publish this aggregator's PressureSignal sidecar here "
        "(each tick live, once at end of a batch run); agents on "
        "the file hop poll it to coarsen shipment cadence",
    )
    p.add_argument(
        "--pressure-capacity",
        type=int,
        default=5000,
        help="PressureController capacity (events at a cluster, "
        "incidents at a region) backing --pressure-out and live acks",
    )
    p.add_argument(
        "--spool-dir",
        default="",
        help="durable dir for the --region-upstream socket spool and "
        "envelope seq journal",
    )
    p.add_argument(
        "--status-out",
        default="",
        help="live mode: per-tick status JSONL; doubles as the "
        "supervisor's heartbeat artifact",
    )
    p.add_argument(
        "--snapshot-interval-s",
        type=float,
        default=1.0,
        help="live mode: StateStore snapshot cadence for --state-out",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="print the run summary as JSON instead of text",
    )
    return p


def incident_provenance(incident: FleetIncident) -> dict[str, Any]:
    """FleetIncident → ProvenanceRecord dict with the members block."""
    from tpuslo.obs.provenance import ProvenanceRecord

    correlation = {
        "tenant": incident.namespace,
        "window_start_ns": incident.window_start_ns,
        "window_end_ns": incident.window_end_ns,
        "nodes": len(incident.nodes),
        "slices": len(incident.slices),
    }
    if incident.region or incident.clusters:
        correlation["region"] = incident.region
        correlation["clusters"] = list(incident.clusters)
    return ProvenanceRecord(
        incident_id=incident.incident_id,
        recorded_at=datetime.now(timezone.utc).isoformat(
            timespec="seconds"
        ),
        predicted_fault_domain=incident.domain,
        confidence=incident.confidence,
        correlation=correlation,
        members=[dict(m) for m in incident.members],
        blast_radius=incident.blast_radius,
    ).to_dict()


def run_region(args) -> int:
    """``fleetagg --region``: envelope logs → federated incidents."""
    from tpuslo.federation.region import RegionAggregator
    from tpuslo.federation.wire import RegionWireError

    region = RegionAggregator(
        region_id=args.region_id, rollup_gap_ns=args.rollup_gap_ns
    )
    if args.restore_state:
        try:
            with open(args.restore_state, encoding="utf-8") as fh:
                snapshot = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            print(
                f"fleetagg: cannot restore {args.restore_state}: {exc}",
                file=sys.stderr,
            )
            return 1
        region.restore_state(snapshot.get("region") or {})
        print(
            f"fleetagg: restored region state from "
            f"{args.restore_state}",
            file=sys.stderr,
        )
    rejected = 0
    for path in args.inputs:
        try:
            fh = open(path, encoding="utf-8")
        except OSError as exc:
            print(
                f"fleetagg: cannot read {path}: {exc.strerror or exc}",
                file=sys.stderr,
            )
            return 1
        with fh:
            for lineno, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    raw = json.loads(line)
                except json.JSONDecodeError as exc:
                    rejected += 1
                    print(
                        f"fleetagg: {path}:{lineno}: rejected: {exc}",
                        file=sys.stderr,
                    )
                    continue
                try:
                    region.ingest(raw)
                except RegionWireError as exc:
                    rejected += 1
                    print(
                        f"fleetagg: {path}:{lineno}: rejected: {exc}",
                        file=sys.stderr,
                    )
    region.pump(flush=True)
    incidents = region.incidents
    if args.global_out:
        # Mirror of the cluster --region-out hop one level up: one
        # envelope per batch run, seq supplied by the caller so the
        # global tier's per-region cursor accepts it exactly once.
        from tpuslo.federation.wire import (
            encode_global_envelope,
            global_envelope_json_line,
        )

        envelope = encode_global_envelope(
            args.region_id,
            args.global_seq,
            incidents,
            watermark_ns=region.watermark_ns(),
            head_ns=region.head_ns(),
            pressure_level=region.pressure.level,
        )
        with open(args.global_out, "w", encoding="utf-8") as fh:
            fh.write(global_envelope_json_line(envelope))
    if args.incidents_out:
        with open(args.incidents_out, "w", encoding="utf-8") as fh:
            for incident in incidents:
                fh.write(
                    json.dumps(
                        incident.to_dict(), separators=(",", ":")
                    )
                    + "\n"
                )
    if args.provenance_out:
        with open(args.provenance_out, "w", encoding="utf-8") as fh:
            for incident in incidents:
                fh.write(
                    json.dumps(
                        incident_provenance(incident),
                        separators=(",", ":"),
                    )
                    + "\n"
                )
    if args.state_out:
        state = {
            "saved_at": datetime.now(timezone.utc).isoformat(
                timespec="seconds"
            ),
            "region": region.export_state(),
            "snapshot": region.snapshot(),
        }
        with open(args.state_out, "w", encoding="utf-8") as fh:
            json.dump(state, fh, indent=2)
            fh.write("\n")
    snapshot = region.snapshot()
    summary = {
        "region": args.region_id,
        "envelopes": region.envelopes,
        "duplicate_envelopes": region.duplicate_envelopes,
        "rejected_envelopes": rejected,
        "clusters": sorted(region.clusters),
        "node_incidents": region.ingested_incidents,
        "incidents": len(incidents),
        "max_staleness_ms": snapshot["max_staleness_ms"],
    }
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(
            "fleetagg: region {region}: {envelopes} envelopes "
            "({dups} seq-dups, {rejected} rejected) from "
            "{clusters} clusters -> {node_incidents} node incidents "
            "-> {incidents} federated incidents".format(
                region=summary["region"],
                envelopes=summary["envelopes"],
                dups=summary["duplicate_envelopes"],
                rejected=summary["rejected_envelopes"],
                clusters=len(summary["clusters"]),
                node_incidents=summary["node_incidents"],
                incidents=summary["incidents"],
            )
        )
        for incident in incidents:
            print(
                f"  {incident.incident_id}: {incident.domain} "
                f"[{incident.blast_radius}] tenant="
                f"{incident.namespace} clusters="
                f"{','.join(incident.clusters) or '-'} "
                f"confidence={incident.confidence:.3f}"
            )
    return 0


def run_global_tier(args) -> int:
    """``fleetagg --global-tier``: envelope logs → global incidents.

    Batch form of the tree root: per-region ``--global-out`` logs in
    any order (WAN replays included — the gap-tolerant cursor accepts
    each seq exactly once), globally-identified pages out.  A region
    absent past ``--region-stale-after-ns`` ages out of the
    session-close clock and the pages emit partition-scoped rather
    than wedging the healthy side.
    """
    from tpuslo.federation.global_tier import GlobalAggregator
    from tpuslo.federation.wire import GlobalWireError

    agg = GlobalAggregator(
        global_id=args.global_id,
        rollup_gap_ns=args.rollup_gap_ns,
        region_stale_after_ns=args.region_stale_after_ns,
        capacity_incidents=args.pressure_capacity,
    )
    if args.restore_state:
        try:
            with open(args.restore_state, encoding="utf-8") as fh:
                snapshot = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            print(
                f"fleetagg: cannot restore {args.restore_state}: {exc}",
                file=sys.stderr,
            )
            return 1
        agg.restore_state(snapshot.get("global") or {})
        print(
            f"fleetagg: restored global state from "
            f"{args.restore_state}",
            file=sys.stderr,
        )
    if args.merge_peer:
        try:
            with open(args.merge_peer, encoding="utf-8") as fh:
                peer_snapshot = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            print(
                f"fleetagg: cannot merge {args.merge_peer}: {exc}",
                file=sys.stderr,
            )
            return 1
        merged = agg.merge_peer(peer_snapshot.get("global") or {})
        print(
            f"fleetagg: merged {merged} emitted windows from peer "
            f"{args.merge_peer}",
            file=sys.stderr,
        )
    rejected = 0
    for path in args.inputs:
        try:
            fh = open(path, encoding="utf-8")
        except OSError as exc:
            print(
                f"fleetagg: cannot read {path}: {exc.strerror or exc}",
                file=sys.stderr,
            )
            return 1
        with fh:
            for lineno, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    raw = json.loads(line)
                except json.JSONDecodeError as exc:
                    rejected += 1
                    print(
                        f"fleetagg: {path}:{lineno}: rejected: {exc}",
                        file=sys.stderr,
                    )
                    continue
                try:
                    agg.ingest(raw)
                except GlobalWireError as exc:
                    rejected += 1
                    print(
                        f"fleetagg: {path}:{lineno}: rejected: {exc}",
                        file=sys.stderr,
                    )
    agg.pump(flush=True)
    incidents = agg.incidents
    if args.incidents_out:
        with open(args.incidents_out, "w", encoding="utf-8") as fh:
            for incident in incidents:
                fh.write(
                    json.dumps(
                        incident.to_dict(), separators=(",", ":")
                    )
                    + "\n"
                )
    if args.state_out:
        state = {
            "saved_at": datetime.now(timezone.utc).isoformat(
                timespec="seconds"
            ),
            "global": agg.export_state(),
            "snapshot": agg.snapshot(),
        }
        with open(args.state_out, "w", encoding="utf-8") as fh:
            json.dump(state, fh, indent=2)
            fh.write("\n")
    snapshot = agg.snapshot()
    summary = {
        "global_id": args.global_id,
        "envelopes": agg.envelopes,
        "duplicate_envelopes": agg.duplicate_envelopes,
        "rejected_envelopes": rejected,
        "regions": sorted(agg.regions),
        "unreachable_regions": sorted(agg.unreachable_regions()),
        "fleet_incidents": agg.ingested_incidents,
        "incidents": len(incidents),
        "partition_scoped": sum(
            1 for i in incidents if i.partition_scoped
        ),
        "duplicates_suppressed": snapshot["duplicates_suppressed"],
        "max_staleness_ms": snapshot["max_staleness_ms"],
    }
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(
            "fleetagg: global {gid}: {envelopes} envelopes "
            "({dups} seq-dups, {rejected} rejected) from "
            "{regions} regions -> {fleet} fleet pages -> "
            "{incidents} global incidents "
            "({partition} partition-scoped)".format(
                gid=summary["global_id"],
                envelopes=summary["envelopes"],
                dups=summary["duplicate_envelopes"],
                rejected=summary["rejected_envelopes"],
                regions=len(summary["regions"]),
                fleet=summary["fleet_incidents"],
                incidents=summary["incidents"],
                partition=summary["partition_scoped"],
            )
        )
        for incident in incidents:
            print(
                f"  {incident.incident_id}: {incident.domain} "
                f"[{incident.blast_radius}] tenant="
                f"{incident.namespace} regions="
                f"{','.join(incident.regions) or '-'} "
                f"scope={incident.scope} "
                f"confidence={incident.confidence:.3f}"
            )
    return 0


def _mesh_membership(args) -> list[str]:
    ids = {p.strip() for p in args.peer_ids.split(",") if p.strip()}
    for entry in args.peer_upstream:
        pid = entry.partition("=")[0].strip()
        if pid:
            ids.add(pid)
    ids.add(args.global_id)
    return sorted(ids)


def run_peer(args) -> int:
    """``fleetagg --peer``: one batch round of a symmetric mesh peer.

    The batch form of the anti-entropy protocol: global-envelope logs
    (this peer's home regions) and peer-envelope gossip logs (other
    peers' ``--peer-gossip-out``) fold in, one election tick and one
    pump run on the event clock, and ``--peer-gossip-out`` writes the
    next round's outbound envelopes.  Iterating runs across peers IS
    the gossip loop on the file hop — it converges for the same
    lattice-merge reasons the live mesh does.  Pages a mesh of more
    than one cannot confirm yet stay honestly in the outbox (reported,
    not emitted); the next round's gossip releases them.
    """
    from tpuslo.federation.global_tier import GlobalPeer
    from tpuslo.federation.wire import peer_envelope_json_line

    membership = _mesh_membership(args)
    peer = GlobalPeer(
        args.global_id,
        membership,
        rollup_gap_ns=args.rollup_gap_ns,
        region_stale_after_ns=args.region_stale_after_ns,
        peer_stale_after_ns=args.peer_stale_after_ns,
        capacity_incidents=args.pressure_capacity,
    )
    if args.restore_state:
        try:
            with open(args.restore_state, encoding="utf-8") as fh:
                snapshot = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            print(
                f"fleetagg: cannot restore {args.restore_state}: {exc}",
                file=sys.stderr,
            )
            return 1
        if snapshot.get("peer"):
            peer.restore_state(snapshot["peer"])
        else:
            # A plain --global-tier snapshot restores the agg half.
            peer.agg.restore_state(snapshot.get("global") or {})
        print(
            f"fleetagg: restored peer state from {args.restore_state}",
            file=sys.stderr,
        )
    if args.merge_peer:
        try:
            with open(args.merge_peer, encoding="utf-8") as fh:
                peer_snapshot = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            print(
                f"fleetagg: cannot merge {args.merge_peer}: {exc}",
                file=sys.stderr,
            )
            return 1
        merged = peer.merge_peer(
            peer_snapshot.get("peer")
            or peer_snapshot.get("global")
            or {}
        )
        print(
            f"fleetagg: merged {merged} emitted windows from peer "
            f"{args.merge_peer}",
            file=sys.stderr,
        )
    rejected = 0
    gossip_frames = 0
    for path in args.inputs:
        try:
            fh = open(path, encoding="utf-8")
        except OSError as exc:
            print(
                f"fleetagg: cannot read {path}: {exc.strerror or exc}",
                file=sys.stderr,
            )
            return 1
        with fh:
            for lineno, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    raw = json.loads(line)
                except json.JSONDecodeError as exc:
                    rejected += 1
                    print(
                        f"fleetagg: {path}:{lineno}: rejected: {exc}",
                        file=sys.stderr,
                    )
                    continue
                try:
                    if "peer_wire_version" in raw:
                        peer.gossip_in(raw)
                        gossip_frames += 1
                    else:
                        peer.ingest(raw)
                except WireContractError as exc:
                    rejected += 1
                    print(
                        f"fleetagg: {path}:{lineno}: rejected: {exc}",
                        file=sys.stderr,
                    )
    # Event clock only: the freshest stream head anyone reported is
    # "now" for liveness and the election.
    now_ns = peer.agg.head_ns()
    for view in peer.views.values():
        if view.head_ns > now_ns:
            now_ns = view.head_ns
    took = peer.election_tick(now_ns)
    if took:
        print(
            f"fleetagg: peer {peer.peer_id} took leadership at "
            f"epoch {peer.epoch}",
            file=sys.stderr,
        )
    peer.pump(flush=True)
    peer.reconcile()
    if args.peer_gossip_out:
        with open(args.peer_gossip_out, "w", encoding="utf-8") as fh:
            for pid in membership:
                if pid == peer.peer_id:
                    continue
                fh.write(
                    peer_envelope_json_line(
                        peer.gossip_out(pid, now_ns)
                    )
                )
        peer.begin_gossip_round()
    if args.incidents_out:
        with open(args.incidents_out, "w", encoding="utf-8") as fh:
            for page in peer.pages:
                fh.write(
                    json.dumps(page, separators=(",", ":")) + "\n"
                )
    if args.state_out:
        state = {
            "saved_at": datetime.now(timezone.utc).isoformat(
                timespec="seconds"
            ),
            "peer": peer.export_state(),
            "global": peer.agg.export_state(),
            "snapshot": peer.snapshot(),
        }
        with open(args.state_out, "w", encoding="utf-8") as fh:
            json.dump(state, fh, indent=2)
            fh.write("\n")
    snap = peer.snapshot()
    summary = {
        "peer_id": peer.peer_id,
        "mesh": membership,
        "rank": peer.rank,
        "epoch": peer.epoch,
        "leader": peer.leader_id,
        "is_leader": peer.is_leader,
        "elections": peer.elections,
        "envelopes": peer.agg.envelopes,
        "duplicate_envelopes": peer.agg.duplicate_envelopes,
        "rejected_frames": rejected,
        "gossip_frames": gossip_frames,
        "gossip_duplicates": peer.gossip_duplicates,
        "registry_merged": peer.registry_merged,
        "regions": sorted(peer.agg.regions),
        "unreachable_regions": sorted(peer.agg.unreachable_regions()),
        "pages": len(peer.pages),
        "pages_released": peer.pages_released,
        "outbox_unconfirmed": len(peer.outbox),
        "deferred": len(peer.deferred),
        "stale_epoch_rejections": peer.stale_epoch_rejections,
        "stale_pages_dropped": peer.stale_pages_dropped,
        "duplicates_suppressed": snap["agg"]["duplicates_suppressed"],
    }
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(
            "fleetagg: peer {pid} (rank {rank}) epoch {epoch} "
            "leader={leader}: {envelopes} envelopes, "
            "{gossip} gossip frames -> {pages} pages held "
            "({released} released, {outbox} awaiting confirmation, "
            "{rej} stale-epoch rejections)".format(
                pid=summary["peer_id"],
                rank=summary["rank"],
                epoch=summary["epoch"],
                leader=summary["leader"],
                envelopes=summary["envelopes"],
                gossip=summary["gossip_frames"],
                pages=summary["pages"],
                released=summary["pages_released"],
                outbox=summary["outbox_unconfirmed"],
                rej=summary["stale_epoch_rejections"],
            )
        )
        for page in peer.pages:
            print(
                f"  {page.get('incident_id')}: {page.get('domain')} "
                f"[{page.get('blast_radius')}] tenant="
                f"{page.get('namespace')} "
                f"epoch={page.get('epoch')} peer={page.get('peer')} "
                f"scope={page.get('scope')}"
            )
    return 0


def run_peer_live(args) -> int:
    """``fleetagg --peer --listen``: the live mesh front door.

    One socket accepts both frame kinds — region global-envelopes and
    mesh peer-envelopes — and one spool-backed client per
    ``--peer-upstream`` carries gossip out every tick.  Election,
    pump and anti-entropy all run on the tick cadence; released pages
    append to ``--incidents-out`` the moment their window row gossips
    back (the commit-then-page fence).
    """
    import os
    import time as time_mod

    from tpuslo.federation.livemesh import LivePeerNode
    from tpuslo.metrics import AgentMetrics
    from tpuslo.runtime import DrainSignal, install_drain_handler

    host, _, port_s = args.listen.rpartition(":")
    host = host or "127.0.0.1"
    try:
        port = int(port_s)
    except ValueError:
        print(
            f"fleetagg: --listen {args.listen!r} must be HOST:PORT",
            file=sys.stderr,
        )
        return 2
    peer_addrs: dict[str, str] = {}
    for entry in args.peer_upstream:
        pid, sep, url = entry.partition("=")
        if not sep or not pid.strip() or not url.strip():
            print(
                f"fleetagg: --peer-upstream {entry!r} must be "
                "PEER=tcp://HOST:PORT",
                file=sys.stderr,
            )
            return 2
        peer_addrs[pid.strip()] = url.strip()
    if peer_addrs and not args.spool_dir:
        print(
            "fleetagg: --peer-upstream needs --spool-dir for the "
            "gossip spools",
            file=sys.stderr,
        )
        return 2

    metrics = AgentMetrics()
    membership = _mesh_membership(args)
    sink_path = args.incidents_out
    sink_seen: set[str] = set()
    sink_written = [0]
    sink_fh = None
    if sink_path:
        try:
            with open(sink_path, encoding="utf-8") as fh:
                for line in fh:
                    try:
                        rid = json.loads(line).get("incident_id")
                    except (json.JSONDecodeError, AttributeError):
                        continue
                    if isinstance(rid, str):
                        sink_seen.add(rid)
        except OSError:
            pass
        sink_fh = open(sink_path, "a", encoding="utf-8")

    def _sink_page(page: dict[str, Any]) -> None:
        rid = str(page.get("incident_id", ""))
        if rid in sink_seen:
            return
        sink_seen.add(rid)
        sink_written[0] += 1
        if sink_fh is not None:
            sink_fh.write(
                json.dumps(page, separators=(",", ":")) + "\n"
            )
            sink_fh.flush()

    try:
        node = LivePeerNode(
            args.global_id,
            peer_addrs,
            args.spool_dir or ".",
            peer_ids=membership,
            host=host,
            port=port,
            rollup_gap_ns=args.rollup_gap_ns,
            region_stale_after_ns=args.region_stale_after_ns,
            peer_stale_after_ns=args.peer_stale_after_ns,
            capacity_incidents=args.pressure_capacity,
            observer=metrics.global_observer(),
            livenet_observer=metrics.livenet_observer(),
            log=lambda msg: print(f"fleetagg: {msg}", file=sys.stderr),
        )
    except (OSError, ValueError) as exc:
        print(
            f"fleetagg: cannot start peer node: {exc}",
            file=sys.stderr,
        )
        return 1
    if args.restore_state:
        try:
            with open(args.restore_state, encoding="utf-8") as fh:
                snapshot = json.load(fh)
            node.restore_state(snapshot.get("peer") or {})
            print(
                f"fleetagg: restored peer state from "
                f"{args.restore_state}",
                file=sys.stderr,
            )
        except (OSError, json.JSONDecodeError) as exc:
            print(
                f"fleetagg: cannot restore {args.restore_state}: "
                f"{exc}",
                file=sys.stderr,
            )
    print(
        f"fleetagg: live peer {args.global_id} (mesh "
        f"{','.join(membership)}) listening on {node.address}",
        file=sys.stderr,
    )
    status_fh = None
    if args.status_out:
        status_fh = open(args.status_out, "a", encoding="utf-8")
    ticks = [0]

    def _heartbeat() -> None:
        if status_fh is None:
            return
        snap = node.snapshot()
        line = {
            "role": "peer",
            "ts": time_mod.time(),
            "tick": ticks[0],
            "epoch": snap["epoch"],
            "leader": snap["leader"],
            "is_leader": snap["is_leader"],
            "pages": snap["pages"],
            "outbox": snap["outbox"],
            "pages_written": sink_written[0],
        }
        status_fh.write(
            json.dumps(line, separators=(",", ":")) + "\n"
        )
        status_fh.flush()

    restore_handlers = install_drain_handler()
    deadline = (
        time_mod.monotonic() + args.run_for_s
        if args.run_for_s > 0
        else float("inf")
    )
    try:
        while time_mod.monotonic() < deadline:
            time_mod.sleep(max(0.01, args.tick_s))
            ticks[0] += 1
            for page in node.tick(time_mod.time_ns()):
                _sink_page(page)
            _heartbeat()
    except (KeyboardInterrupt, DrainSignal):
        pass
    finally:
        restore_handlers()
        ticks[0] += 1
        for page in node.tick(time_mod.time_ns(), flush=True):
            _sink_page(page)
        if args.state_out:
            state = {
                "saved_at": datetime.now(timezone.utc).isoformat(
                    timespec="seconds"
                ),
                "peer": node.export_state(),
                "snapshot": node.snapshot(),
            }
            try:
                with open(
                    args.state_out, "w", encoding="utf-8"
                ) as fh:
                    json.dump(state, fh, indent=2)
                    fh.write("\n")
            except OSError as exc:
                print(
                    f"fleetagg: cannot write {args.state_out}: {exc}",
                    file=sys.stderr,
                )
        _heartbeat()
        if status_fh is not None:
            status_fh.close()
        node.close()
        if sink_fh is not None:
            sink_fh.close()
    snap = node.snapshot()
    summary = {
        "peer_id": args.global_id,
        "epoch": snap["epoch"],
        "leader": snap["leader"],
        "elections": snap["elections"],
        "listener_frames": snap["listener_frames"],
        "frames_rejected": snap["frames_rejected"],
        "gossip_frames": snap["gossip_frames"],
        "pages": snap["pages"],
        "pages_written": sink_written[0],
        "outbox_unconfirmed": snap["outbox"],
        "stale_epoch_rejections": snap["stale_epoch_rejections"],
    }
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(
            "fleetagg: live peer {pid}: epoch {epoch} "
            "leader={leader}, {frames} frames ({gossip} gossip), "
            "{written} pages written, {outbox} awaiting "
            "confirmation".format(
                pid=summary["peer_id"],
                epoch=summary["epoch"],
                leader=summary["leader"],
                frames=summary["listener_frames"],
                gossip=summary["gossip_frames"],
                written=summary["pages_written"],
                outbox=summary["outbox_unconfirmed"],
            )
        )
    return 0


class _IncidentSink:
    """Append-only incident JSONL with cross-restart id dedup.

    Live aggregators append incidents the moment the rollup emits
    them (a kill -9 between ticks loses at most the un-emitted open
    groups, which the restored rollup state re-opens).  Incident ids
    are content-derived, so a restored rollup re-emitting a page it
    already wrote is suppressed here — the zero-duplicate half of the
    chaos gate's invariant lives in this set.
    """

    def __init__(self, path: str):
        self.path = path
        self.seen: set[str] = set()
        self.incidents: list[FleetIncident] = []
        self.written = 0
        self.suppressed = 0
        self._fh = None
        if path:
            try:
                with open(path, encoding="utf-8") as fh:
                    for line in fh:
                        try:
                            rid = json.loads(line).get("incident_id")
                        except (json.JSONDecodeError, AttributeError):
                            continue
                        if isinstance(rid, str):
                            self.seen.add(rid)
            except OSError:
                pass
            self._fh = open(path, "a", encoding="utf-8")

    def emit(self, incident: FleetIncident) -> None:
        if incident.incident_id in self.seen:
            self.suppressed += 1
            return
        self.seen.add(incident.incident_id)
        self.incidents.append(incident)
        self.written += 1
        if self._fh is not None:
            self._fh.write(
                json.dumps(incident.to_dict(), separators=(",", ":"))
                + "\n"
            )
            self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None


def run_live(args) -> int:
    """``fleetagg --listen``: the live (socket) aggregator role.

    One process, either tree level: a cluster accepts shipment frames
    from node agents and ships region envelopes upstream each tick; a
    region (``--region --listen``) accepts envelope frames and emits
    federated incidents.  Durability is the PR 4 runtime shape —
    StateStore snapshots each tick, auto-restored on restart under
    the ProcessSupervisor — and every inbound hop stays behind the
    wire contracts' seq dedup, so a kill -9 anywhere re-delivers but
    never duplicates.
    """
    import os
    import threading
    import time as time_mod

    from tpuslo.federation.backpressure import PressureController
    from tpuslo.livenet import (
        LiveListener,
        ReconnectingClient,
        SeqJournal,
        parse_socket_url,
        write_pressure_file,
    )
    from tpuslo.metrics import AgentMetrics
    from tpuslo.runtime import (
        AgentRuntime,
        DrainSignal,
        StateStore,
        install_drain_handler,
    )

    host, _, port_s = args.listen.rpartition(":")
    host = host or "127.0.0.1"
    try:
        port = int(port_s)
    except ValueError:
        print(
            f"fleetagg: --listen {args.listen!r} must be HOST:PORT",
            file=sys.stderr,
        )
        return 2

    role = "region" if args.region else "cluster"
    source_id = args.region_id if args.region else (
        args.cluster_id or "cluster-0"
    )
    metrics = AgentMetrics()
    lv_observer = metrics.livenet_observer()
    controller = PressureController(args.pressure_capacity)
    state_lock = threading.Lock()
    sink = _IncidentSink(args.incidents_out)
    stats = {"frames": 0, "ticks": 0, "shipped_incidents": 0}

    # ---- upstream hop (cluster role only) -----------------------------
    upstream_client = None
    upstream_path = ""
    seq_journal = None
    if args.region_upstream:
        durable_dir = args.spool_dir or (
            os.path.dirname(args.state_out) if args.state_out else ""
        )
        try:
            upstream_addr = parse_socket_url(args.region_upstream)
        except ValueError as exc:
            print(f"fleetagg: {exc}", file=sys.stderr)
            return 2
        if upstream_addr is not None:
            if not durable_dir:
                print(
                    "fleetagg: tcp:// --region-upstream needs "
                    "--spool-dir (or --state-out) for the envelope "
                    "spool and seq journal",
                    file=sys.stderr,
                )
                return 2
            upstream_client = ReconnectingClient(
                upstream_addr,
                os.path.join(durable_dir, "region-spool"),
                peer="region",
                observer=lv_observer,
                log=lambda msg: print(
                    f"fleetagg: {msg}", file=sys.stderr
                ),
            )
        else:
            upstream_path = args.region_upstream
        if durable_dir:
            seq_journal = SeqJournal(
                os.path.join(durable_dir, "region-seq.json")
            )
    envelope_seq = (
        seq_journal.last_recorded_seq(source_id)
        if seq_journal is not None
        else args.region_seq - 1
    )

    # ---- aggregation state + runtime registry -------------------------
    store = None
    if args.state_out:
        store = StateStore(
            args.state_out, interval_s=args.snapshot_interval_s
        )
    runtime = AgentRuntime(
        store,
        log=lambda msg: print(f"fleetagg: {msg}", file=sys.stderr),
    )

    if args.region:
        from tpuslo.federation.region import RegionAggregator
        from tpuslo.federation.wire import RegionWireError  # noqa: F401

        region = RegionAggregator(
            region_id=args.region_id,
            rollup_gap_ns=args.rollup_gap_ns,
            capacity_incidents=args.pressure_capacity,
            on_incident=sink.emit,
        )
        runtime.register(
            "region", region.export_state, region.restore_state
        )

        def _handle(raw: dict[str, Any]) -> None:
            if region.ingest(raw):
                stats["frames"] += 1

        def _tick(flush: bool) -> dict[str, Any]:
            with state_lock:
                region.pump(flush=flush)
                backlog = region.backlog_incidents()
                level = region.observe_pressure()
                line = {
                    "role": role,
                    "level": level,
                    "backlog": backlog,
                    "clusters": len(region.clusters),
                    "envelopes": region.envelopes,
                    "duplicate_envelopes": region.duplicate_envelopes,
                    "node_incidents": region.ingested_incidents,
                    "incidents_written": sink.written,
                    "incidents_suppressed": sink.suppressed,
                }
            if args.pressure_out:
                try:
                    write_pressure_file(
                        args.pressure_out,
                        region.pressure.signal(source_id, backlog),
                    )
                except OSError:
                    pass
            return line

    else:
        shard_ids = [
            f"{args.shard_prefix}-{i}" for i in range(max(1, args.shards))
        ]
        ring = HashRing(shard_ids)
        shards = {
            sid: AggregatorShard(
                sid,
                gate_config=GateConfig(),
                window_ns=args.window_ns,
                min_confidence=args.min_confidence,
            )
            for sid in shard_ids
        }
        rollup = FleetRollup(
            gap_ns=args.rollup_gap_ns, on_incident=sink.emit
        )
        runtime.register(
            "rollup", rollup.export_state, rollup.restore_state
        )

        def _export_shards() -> dict[str, Any]:
            return {
                sid: shard.export_state()
                for sid, shard in shards.items()
            }

        def _restore_shards(state: dict[str, Any]) -> None:
            # Failover re-homing, same as --restore-state: each node
            # fragment lands on whichever shard the ring owns now.
            restored = 0
            for section in (state or {}).values():
                for node, fragment in (
                    section.get("nodes") or {}
                ).items():
                    slice_id = str(fragment.get("slice_id", ""))
                    owner = ring.shard_for_node(str(node), slice_id)
                    shards[owner].absorb_node_state(
                        str(node), fragment
                    )
                    restored += 1
            print(
                f"fleetagg: re-homed {restored} node fragments",
                file=sys.stderr,
            )

        runtime.register("shards", _export_shards, _restore_shards)

        def _handle(raw: dict[str, Any]) -> None:
            node = raw.get("node") if isinstance(raw, dict) else None
            if not isinstance(node, str) or not node:
                raise WireContractError(
                    "not a shipment object (missing node)"
                )
            owner = ring.shard_for_node(
                node, str(raw.get("slice_id") or "")
            )
            if shards[owner].ingest(raw):
                stats["frames"] += 1

        def _ship_envelope(
            node_incidents: list, level: int
        ) -> None:
            nonlocal envelope_seq
            from tpuslo.federation.wire import (
                encode_region_envelope,
                region_envelope_json_line,
            )

            marks = [
                s.watermark_ns() for s in shards.values() if s.nodes
            ]
            heads = [s.fleet_head_ns() for s in shards.values()]
            envelope_seq += 1
            envelope = encode_region_envelope(
                source_id,
                envelope_seq,
                node_incidents,
                watermark_ns=min(marks) if marks else 0,
                head_ns=max(heads) if heads else 0,
                pressure_level=level,
            )
            if upstream_client is not None:
                # Journal BEFORE send: a crash burns the seq (gap),
                # never reuses one the region would eat as a dup.
                if seq_journal is not None:
                    seq_journal.record(source_id, envelope_seq)
                upstream_client.send(envelope)
            else:
                with open(
                    upstream_path, "a", encoding="utf-8"
                ) as fh:
                    fh.write(region_envelope_json_line(envelope))
                if seq_journal is not None:
                    seq_journal.record(source_id, envelope_seq)
            stats["shipped_incidents"] += len(node_incidents)

        def _tick(flush: bool) -> dict[str, Any]:
            with state_lock:
                backlog = sum(
                    s.backlog_events() for s in shards.values()
                )
                level = controller.observe(backlog)
                node_incidents = [
                    ni
                    for shard in shards.values()
                    for ni in shard.close_windows(flush=flush)
                ]
                node_incidents.sort(key=lambda ni: ni.ts_unix_nano)
                if args.cluster_id:
                    for ni in node_incidents:
                        ni.cluster = args.cluster_id
                if args.region_upstream:
                    # Ship every tick, incidents or not: the region's
                    # session-close clock is min(cluster watermarks),
                    # so a quiet cluster must still heartbeat its
                    # watermark/head/pressure or it freezes
                    # close_up_to for the whole tree.
                    _ship_envelope(node_incidents, level)
                rollup.observe(node_incidents)
                if flush:
                    rollup.flush()
            if args.pressure_out:
                try:
                    write_pressure_file(
                        args.pressure_out,
                        controller.signal(source_id, backlog),
                    )
                except OSError:
                    pass
            return {
                "role": role,
                "level": level,
                "backlog": backlog,
                "shipments": stats["frames"],
                "duplicate_shipments": sum(
                    s.duplicate_shipments for s in shards.values()
                ),
                "ingested_events": sum(
                    s.ingested_events for s in shards.values()
                ),
                "shipped_incidents": stats["shipped_incidents"],
                "incidents_written": sink.written,
                "incidents_suppressed": sink.suppressed,
            }

    # Restore AFTER every component registered its hooks; the printed
    # line is the chaos lane's warm-resume evidence.
    restore_outcome = runtime.restore()
    if runtime.enabled:
        detail = ""
        if restore_outcome == "restored":
            detail = (
                f" (age {runtime.restored_age_s:.1f}s, components: "
                f"{','.join(runtime.restored_components) or 'none'})"
            )
        print(
            f"fleetagg: runtime: snapshot {restore_outcome}{detail}",
            file=sys.stderr,
        )

    status_fh = None
    if args.status_out:
        status_fh = open(args.status_out, "a", encoding="utf-8")

    def _heartbeat(line: dict[str, Any]) -> None:
        if status_fh is None:
            return
        line["ts"] = time_mod.time()
        line["tick"] = stats["ticks"]
        status_fh.write(
            json.dumps(line, separators=(",", ":")) + "\n"
        )
        status_fh.flush()

    try:
        listener = LiveListener(
            _handle,
            host=host,
            port=port,
            name=role,
            pressure=lambda: controller.level
            if not args.region
            else region.pressure.level,
            observer=lv_observer,
            log=lambda msg: print(f"fleetagg: {msg}", file=sys.stderr),
            # Peer threads ingest into the same region/shard objects
            # the tick loop pumps and closes; sharing state_lock makes
            # socket ingest and tick work mutually exclusive (the
            # zero-lost-incident invariant the chaos gate audits).
            ingest_lock=state_lock,
        )
    except OSError as exc:
        print(
            f"fleetagg: cannot listen on {args.listen}: {exc}",
            file=sys.stderr,
        )
        return 1
    print(
        f"fleetagg: live {role} {source_id} listening on "
        f"{listener.address}"
        + (
            f", upstream -> {args.region_upstream}"
            if args.region_upstream
            else ""
        ),
        file=sys.stderr,
    )

    restore_handlers = install_drain_handler()
    deadline = (
        time_mod.monotonic() + args.run_for_s
        if args.run_for_s > 0
        else float("inf")
    )
    last = {}
    try:
        while time_mod.monotonic() < deadline:
            time_mod.sleep(max(0.01, args.tick_s))
            stats["ticks"] += 1
            last = _tick(flush=False)
            _heartbeat(dict(last))
            runtime.maybe_snapshot()
    except (KeyboardInterrupt, DrainSignal):
        pass
    finally:
        restore_handlers()
        listener.close()
        stats["ticks"] += 1
        last = _tick(flush=True)
        if upstream_client is not None:
            upstream_client.replay_spool()
        runtime.snapshot_now()
        last["final"] = True
        if upstream_client is not None:
            last["spool_pending"] = upstream_client.pending_spooled()
        _heartbeat(dict(last))
        if status_fh is not None:
            status_fh.close()
        if upstream_client is not None:
            upstream_client.close()
        sink.close()
    summary = dict(last)
    summary["listener_frames"] = listener.frames_total
    summary["frames_rejected"] = listener.frames_rejected
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(
            f"fleetagg: live {role} {source_id}: "
            f"{listener.frames_total} frames "
            f"({listener.frames_rejected} rejected), "
            f"{sink.written} incidents written "
            f"({sink.suppressed} suppressed as dups)"
        )
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.peer:
        if args.global_tier or args.region or args.cluster_id:
            print(
                "fleetagg: --peer is its own tier; drop "
                "--global-tier/--region/--cluster-id",
                file=sys.stderr,
            )
            return 2
        if args.listen:
            if args.inputs:
                print(
                    "fleetagg: live mode (--listen) takes no input "
                    "logs",
                    file=sys.stderr,
                )
                return 2
            return run_peer_live(args)
        if args.peer_upstream:
            print(
                "fleetagg: --peer-upstream is live-only; batch "
                "rounds exchange --peer-gossip-out files",
                file=sys.stderr,
            )
            return 2
        if not (
            args.inputs
            or args.restore_state
            or args.merge_peer
            or args.peer_gossip_out
        ):
            print(
                "fleetagg: --peer needs envelope/gossip logs (or "
                "state to restore/merge)",
                file=sys.stderr,
            )
            return 2
        return run_peer(args)
    if args.peer_ids or args.peer_upstream or args.peer_gossip_out:
        print(
            "fleetagg: --peer-ids/--peer-upstream/--peer-gossip-out "
            "belong to --peer runs",
            file=sys.stderr,
        )
        return 2
    if args.global_tier and args.listen:
        print(
            "fleetagg: --global-tier is batch-only; the live mesh "
            "front door is --peer --listen",
            file=sys.stderr,
        )
        return 2
    if args.global_tier:
        if args.region or args.region_out or args.cluster_id:
            print(
                "fleetagg: --global-tier consumes global envelopes; "
                "--region/--region-out/--cluster-id belong to lower "
                "tiers",
                file=sys.stderr,
            )
            return 2
        if args.global_out:
            print(
                "fleetagg: --global-out belongs to --region runs "
                "(the tree root has no upstream)",
                file=sys.stderr,
            )
            return 2
        if not args.inputs:
            print(
                "fleetagg: --global-tier needs global-envelope logs",
                file=sys.stderr,
            )
            return 2
        return run_global_tier(args)
    if args.merge_peer:
        print(
            "fleetagg: --merge-peer belongs to --global-tier or "
            "--peer runs",
            file=sys.stderr,
        )
        return 2
    if args.global_out and not args.region:
        print(
            "fleetagg: --global-out belongs to --region runs (the "
            "region->global wire hop)",
            file=sys.stderr,
        )
        return 2
    if args.listen:
        if args.inputs:
            print(
                "fleetagg: live mode (--listen) takes no input logs",
                file=sys.stderr,
            )
            return 2
        if args.region and args.region_upstream:
            print(
                "fleetagg: --region is the tree root; "
                "--region-upstream belongs to cluster runs",
                file=sys.stderr,
            )
            return 2
        if not args.region and args.region_upstream and not args.cluster_id:
            print(
                "fleetagg: --region-upstream requires --cluster-id "
                "(the envelope's per-cluster identity and seq-dedup "
                "cursor)",
                file=sys.stderr,
            )
            return 2
        return run_live(args)
    if not args.inputs:
        print(
            "fleetagg: provide input logs or --listen",
            file=sys.stderr,
        )
        return 2
    if args.region:
        if args.region_out or args.cluster_id:
            print(
                "fleetagg: --region consumes envelopes; "
                "--region-out/--cluster-id belong to cluster runs",
                file=sys.stderr,
            )
            return 2
        return run_region(args)
    if args.region_out and not args.cluster_id:
        # A fallback identity would collide across cluster runs at the
        # region (shared seq cursor = one cluster's envelope silently
        # dropped as a duplicate) and leave members unstamped.
        print(
            "fleetagg: --region-out requires --cluster-id (the "
            "envelope's per-cluster identity and seq-dedup cursor)",
            file=sys.stderr,
        )
        return 2
    if args.shards < 1:
        print("fleetagg: --shards must be >= 1", file=sys.stderr)
        return 2
    shard_ids = [f"{args.shard_prefix}-{i}" for i in range(args.shards)]
    ring = HashRing(shard_ids)
    shards = {
        sid: AggregatorShard(
            sid,
            gate_config=GateConfig(),
            window_ns=args.window_ns,
            min_confidence=args.min_confidence,
        )
        for sid in shard_ids
    }
    incidents: list[FleetIncident] = []
    rollup = FleetRollup(
        gap_ns=args.rollup_gap_ns, on_incident=incidents.append
    )

    if args.restore_state:
        try:
            with open(args.restore_state, encoding="utf-8") as fh:
                snapshot = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            print(
                f"fleetagg: cannot restore {args.restore_state}: {exc}",
                file=sys.stderr,
            )
            return 1
        rollup.restore_state(snapshot.get("rollup") or {})
        restored = 0
        for section in (snapshot.get("shards") or {}).values():
            for node, fragment in (section.get("nodes") or {}).items():
                slice_id = str(fragment.get("slice_id", ""))
                owner = ring.shard_for_node(str(node), slice_id)
                shards[owner].absorb_node_state(str(node), fragment)
                restored += 1
        print(
            f"fleetagg: restored {restored} node fragments from "
            f"{args.restore_state}",
            file=sys.stderr,
        )

    shipments = 0
    rejected = 0
    for path in args.inputs:
        try:
            fh = open(path, encoding="utf-8")
        except OSError as exc:
            print(
                f"fleetagg: cannot read {path}: {exc.strerror or exc}",
                file=sys.stderr,
            )
            return 1
        with fh:
            for lineno, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                # Hand ingest the raw dict: its header peek drops seq
                # duplicates (spool replays, a log listed twice)
                # before paying the O(events) decode; a malformed
                # shipment still raises the contract error from there.
                try:
                    raw = json.loads(line)
                except json.JSONDecodeError as exc:
                    rejected += 1
                    print(
                        f"fleetagg: {path}:{lineno}: rejected: {exc}",
                        file=sys.stderr,
                    )
                    continue
                node = (
                    raw.get("node") if isinstance(raw, dict) else None
                )
                if not isinstance(node, str) or not node:
                    rejected += 1
                    print(
                        f"fleetagg: {path}:{lineno}: rejected: "
                        "not a shipment object (missing node)",
                        file=sys.stderr,
                    )
                    continue
                owner = ring.shard_for_node(
                    node, str(raw.get("slice_id") or "")
                )
                try:
                    shards[owner].ingest(raw)
                except WireContractError as exc:
                    rejected += 1
                    print(
                        f"fleetagg: {path}:{lineno}: rejected: {exc}",
                        file=sys.stderr,
                    )
                    continue
                shipments += 1

    if args.pressure_out:
        # The file hop's backpressure channel: publish the post-ingest
        # backlog as a PressureSignal sidecar.  Point this at
        # `<shipment-log>.pressure` and the shipping agent's next run
        # coarsens its cadence (tpuslo.livenet.pressure).
        from tpuslo.federation.backpressure import PressureController
        from tpuslo.livenet import write_pressure_file

        controller = PressureController(args.pressure_capacity)
        backlog = sum(s.backlog_events() for s in shards.values())
        controller.observe(backlog)
        try:
            write_pressure_file(
                args.pressure_out,
                controller.signal(
                    args.cluster_id or "fleetagg", backlog
                ),
            )
        except OSError as exc:
            print(
                f"fleetagg: cannot write {args.pressure_out}: {exc}",
                file=sys.stderr,
            )

    # End of logs == end of stream: flush every window and group.
    # Shards flush their whole history one after another, so merge the
    # per-shard node incidents into one time-ordered stream first —
    # members of the same fault that hashed to different shards must
    # coalesce before any session closes.
    node_incidents = [
        ni
        for shard in shards.values()
        for ni in shard.close_windows(flush=True)
    ]
    node_incidents.sort(key=lambda ni: ni.ts_unix_nano)
    if args.cluster_id:
        for ni in node_incidents:
            ni.cluster = args.cluster_id
    if args.region_out:
        from tpuslo.federation.wire import (
            encode_region_envelope,
            region_envelope_json_line,
        )

        marks = [s.watermark_ns() for s in shards.values() if s.nodes]
        heads = [s.fleet_head_ns() for s in shards.values()]
        envelope = encode_region_envelope(
            args.cluster_id,
            args.region_seq,
            node_incidents,
            watermark_ns=min(marks) if marks else 0,
            head_ns=max(heads) if heads else 0,
        )
        with open(args.region_out, "w", encoding="utf-8") as fh:
            fh.write(region_envelope_json_line(envelope))
    rollup.observe(node_incidents)
    rollup.flush()

    if args.incidents_out:
        with open(args.incidents_out, "w", encoding="utf-8") as fh:
            for incident in incidents:
                fh.write(
                    json.dumps(
                        incident.to_dict(), separators=(",", ":")
                    )
                    + "\n"
                )
    if args.provenance_out:
        with open(args.provenance_out, "w", encoding="utf-8") as fh:
            for incident in incidents:
                fh.write(
                    json.dumps(
                        incident_provenance(incident),
                        separators=(",", ":"),
                    )
                    + "\n"
                )
    if args.state_out:
        state = {
            "saved_at": datetime.now(timezone.utc).isoformat(
                timespec="seconds"
            ),
            "cluster": args.cluster_id,
            "ring": ring.export_state(),
            "rollup": rollup.export_state(),
            "shards": {
                sid: shard.export_state()
                for sid, shard in shards.items()
            },
            "snapshots": {
                sid: shard.snapshot()
                for sid, shard in shards.items()
            },
        }
        with open(args.state_out, "w", encoding="utf-8") as fh:
            json.dump(state, fh, indent=2)
            fh.write("\n")

    summary = {
        "shards": args.shards,
        "shipments": shipments,
        "rejected_shipments": rejected,
        "duplicate_shipments": sum(
            s.duplicate_shipments for s in shards.values()
        ),
        "ingested_events": sum(
            s.ingested_events for s in shards.values()
        ),
        "admitted_events": sum(
            s.admitted_events for s in shards.values()
        ),
        "nodes": sum(len(s.nodes) for s in shards.values()),
        "incidents": len(incidents),
        "incidents_by_radius": {
            radius: sum(
                1 for i in incidents if i.blast_radius == radius
            )
            for radius in sorted({i.blast_radius for i in incidents})
        },
    }
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(
            "fleetagg: {shipments} shipments ({rejected} rejected, "
            "{dups} seq-dups) from {nodes} nodes -> "
            "{admitted}/{ingested} events admitted -> "
            "{incidents} fleet incidents".format(
                shipments=summary["shipments"],
                rejected=summary["rejected_shipments"],
                dups=summary["duplicate_shipments"],
                nodes=summary["nodes"],
                admitted=summary["admitted_events"],
                ingested=summary["ingested_events"],
                incidents=summary["incidents"],
            )
        )
        for incident in incidents:
            print(
                f"  {incident.incident_id}: {incident.domain} "
                f"[{incident.blast_radius}] tenant="
                f"{incident.namespace} nodes={len(incident.nodes)} "
                f"confidence={incident.confidence:.3f}"
            )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
