"""fleetagg: aggregator-role binary of the fleet observability plane.

One process hosts one or more :class:`~tpuslo.fleet.AggregatorShard`\\ s
behind a consistent hash ring and consumes node-agent shipment logs
(``agent --fleet-upstream`` output, the JSONL form of the TPL104-
governed wire contract).  Each shipment decodes zero-copy, dedups by
per-node sequence, merges, gates, and folds; closed windows attribute
through the shared Bayesian posterior and collapse through the fleet
rollup into one incident per (fault domain x blast radius).

Outputs:

* ``--incidents-out`` — fleet incidents as JSONL (``sloctl fleet
  incidents`` renders the table).
* ``--provenance-out`` — one ProvenanceRecord per fleet incident with
  the ``members`` block (``sloctl explain`` drills a fleet page down
  to its contributing node incidents).
* ``--state-out`` — shard/node state snapshot (``sloctl fleet nodes``
  renders per-node reporting/stale status; a restarted aggregator
  absorbs it via the PR 4 runtime registry shape).
"""

from __future__ import annotations

import argparse
import json
import sys
from datetime import datetime, timezone
from typing import Any

from tpuslo.fleet.aggregator import AggregatorShard
from tpuslo.fleet.ring import HashRing
from tpuslo.fleet.rollup import FleetIncident, FleetRollup
from tpuslo.fleet.wire import WireContractError
from tpuslo.ingest.gate import GateConfig


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpuslo fleetagg", description=__doc__
    )
    p.add_argument(
        "inputs",
        nargs="+",
        help="shipment logs written by `agent --fleet-upstream`",
    )
    p.add_argument(
        "--shards",
        type=int,
        default=1,
        help="aggregator shards to host in this process (placement by "
        "the same consistent hash ring the agents compute)",
    )
    p.add_argument("--shard-prefix", default="agg")
    p.add_argument(
        "--window-ns",
        type=int,
        default=2_000_000_000,
        help="attribution window width",
    )
    p.add_argument(
        "--rollup-gap-ns",
        type=int,
        default=5_000_000_000,
        help="session gap closing a (tenant, domain) rollup group",
    )
    p.add_argument(
        "--min-confidence",
        type=float,
        default=0.5,
        help="attribution confidence floor for a node incident",
    )
    p.add_argument("--incidents-out", default="")
    p.add_argument("--provenance-out", default="")
    p.add_argument("--state-out", default="")
    p.add_argument(
        "--restore-state",
        default="",
        help="absorb a prior --state-out snapshot before ingesting "
        "(failover re-home: each node fragment lands on whichever "
        "shard the ring owns now; in --region mode, restore the "
        "region rollup + per-cluster cursors)",
    )
    # ---- federation tree (tpuslo.federation) --------------------------
    p.add_argument(
        "--cluster-id",
        default="",
        help="run as ONE cluster of the federation tree: emitted node "
        "incidents carry this cluster identity and the state "
        "snapshot is scoped to it (sloctl fleet nodes --cluster)",
    )
    p.add_argument(
        "--region-out",
        default="",
        help="write this cluster's region-envelope JSONL (the "
        "cluster->region wire hop; feed it to `fleetagg --region`)",
    )
    p.add_argument(
        "--region-seq",
        type=int,
        default=0,
        help="monotonic per-cluster envelope sequence for "
        "--region-out (bump per run so the region's seq dedup "
        "admits it)",
    )
    p.add_argument(
        "--region",
        action="store_true",
        help="run as the REGION aggregator: inputs are region-envelope "
        "JSONL logs written by per-cluster `fleetagg --region-out` "
        "runs; incidents collapse with cross-cluster identity",
    )
    p.add_argument("--region-id", default="region-0")
    p.add_argument(
        "--json",
        action="store_true",
        help="print the run summary as JSON instead of text",
    )
    return p


def incident_provenance(incident: FleetIncident) -> dict[str, Any]:
    """FleetIncident → ProvenanceRecord dict with the members block."""
    from tpuslo.obs.provenance import ProvenanceRecord

    correlation = {
        "tenant": incident.namespace,
        "window_start_ns": incident.window_start_ns,
        "window_end_ns": incident.window_end_ns,
        "nodes": len(incident.nodes),
        "slices": len(incident.slices),
    }
    if incident.region or incident.clusters:
        correlation["region"] = incident.region
        correlation["clusters"] = list(incident.clusters)
    return ProvenanceRecord(
        incident_id=incident.incident_id,
        recorded_at=datetime.now(timezone.utc).isoformat(
            timespec="seconds"
        ),
        predicted_fault_domain=incident.domain,
        confidence=incident.confidence,
        correlation=correlation,
        members=[dict(m) for m in incident.members],
        blast_radius=incident.blast_radius,
    ).to_dict()


def run_region(args) -> int:
    """``fleetagg --region``: envelope logs → federated incidents."""
    from tpuslo.federation.region import RegionAggregator
    from tpuslo.federation.wire import RegionWireError

    region = RegionAggregator(
        region_id=args.region_id, rollup_gap_ns=args.rollup_gap_ns
    )
    if args.restore_state:
        try:
            with open(args.restore_state, encoding="utf-8") as fh:
                snapshot = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            print(
                f"fleetagg: cannot restore {args.restore_state}: {exc}",
                file=sys.stderr,
            )
            return 1
        region.restore_state(snapshot.get("region") or {})
        print(
            f"fleetagg: restored region state from "
            f"{args.restore_state}",
            file=sys.stderr,
        )
    rejected = 0
    for path in args.inputs:
        try:
            fh = open(path, encoding="utf-8")
        except OSError as exc:
            print(
                f"fleetagg: cannot read {path}: {exc.strerror or exc}",
                file=sys.stderr,
            )
            return 1
        with fh:
            for lineno, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    raw = json.loads(line)
                except json.JSONDecodeError as exc:
                    rejected += 1
                    print(
                        f"fleetagg: {path}:{lineno}: rejected: {exc}",
                        file=sys.stderr,
                    )
                    continue
                try:
                    region.ingest(raw)
                except RegionWireError as exc:
                    rejected += 1
                    print(
                        f"fleetagg: {path}:{lineno}: rejected: {exc}",
                        file=sys.stderr,
                    )
    region.pump(flush=True)
    incidents = region.incidents
    if args.incidents_out:
        with open(args.incidents_out, "w", encoding="utf-8") as fh:
            for incident in incidents:
                fh.write(
                    json.dumps(
                        incident.to_dict(), separators=(",", ":")
                    )
                    + "\n"
                )
    if args.provenance_out:
        with open(args.provenance_out, "w", encoding="utf-8") as fh:
            for incident in incidents:
                fh.write(
                    json.dumps(
                        incident_provenance(incident),
                        separators=(",", ":"),
                    )
                    + "\n"
                )
    if args.state_out:
        state = {
            "saved_at": datetime.now(timezone.utc).isoformat(
                timespec="seconds"
            ),
            "region": region.export_state(),
            "snapshot": region.snapshot(),
        }
        with open(args.state_out, "w", encoding="utf-8") as fh:
            json.dump(state, fh, indent=2)
            fh.write("\n")
    snapshot = region.snapshot()
    summary = {
        "region": args.region_id,
        "envelopes": region.envelopes,
        "duplicate_envelopes": region.duplicate_envelopes,
        "rejected_envelopes": rejected,
        "clusters": sorted(region.clusters),
        "node_incidents": region.ingested_incidents,
        "incidents": len(incidents),
        "max_staleness_ms": snapshot["max_staleness_ms"],
    }
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(
            "fleetagg: region {region}: {envelopes} envelopes "
            "({dups} seq-dups, {rejected} rejected) from "
            "{clusters} clusters -> {node_incidents} node incidents "
            "-> {incidents} federated incidents".format(
                region=summary["region"],
                envelopes=summary["envelopes"],
                dups=summary["duplicate_envelopes"],
                rejected=summary["rejected_envelopes"],
                clusters=len(summary["clusters"]),
                node_incidents=summary["node_incidents"],
                incidents=summary["incidents"],
            )
        )
        for incident in incidents:
            print(
                f"  {incident.incident_id}: {incident.domain} "
                f"[{incident.blast_radius}] tenant="
                f"{incident.namespace} clusters="
                f"{','.join(incident.clusters) or '-'} "
                f"confidence={incident.confidence:.3f}"
            )
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.region:
        if args.region_out or args.cluster_id:
            print(
                "fleetagg: --region consumes envelopes; "
                "--region-out/--cluster-id belong to cluster runs",
                file=sys.stderr,
            )
            return 2
        return run_region(args)
    if args.region_out and not args.cluster_id:
        # A fallback identity would collide across cluster runs at the
        # region (shared seq cursor = one cluster's envelope silently
        # dropped as a duplicate) and leave members unstamped.
        print(
            "fleetagg: --region-out requires --cluster-id (the "
            "envelope's per-cluster identity and seq-dedup cursor)",
            file=sys.stderr,
        )
        return 2
    if args.shards < 1:
        print("fleetagg: --shards must be >= 1", file=sys.stderr)
        return 2
    shard_ids = [f"{args.shard_prefix}-{i}" for i in range(args.shards)]
    ring = HashRing(shard_ids)
    shards = {
        sid: AggregatorShard(
            sid,
            gate_config=GateConfig(),
            window_ns=args.window_ns,
            min_confidence=args.min_confidence,
        )
        for sid in shard_ids
    }
    incidents: list[FleetIncident] = []
    rollup = FleetRollup(
        gap_ns=args.rollup_gap_ns, on_incident=incidents.append
    )

    if args.restore_state:
        try:
            with open(args.restore_state, encoding="utf-8") as fh:
                snapshot = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            print(
                f"fleetagg: cannot restore {args.restore_state}: {exc}",
                file=sys.stderr,
            )
            return 1
        rollup.restore_state(snapshot.get("rollup") or {})
        restored = 0
        for section in (snapshot.get("shards") or {}).values():
            for node, fragment in (section.get("nodes") or {}).items():
                slice_id = str(fragment.get("slice_id", ""))
                owner = ring.shard_for_node(str(node), slice_id)
                shards[owner].absorb_node_state(str(node), fragment)
                restored += 1
        print(
            f"fleetagg: restored {restored} node fragments from "
            f"{args.restore_state}",
            file=sys.stderr,
        )

    shipments = 0
    rejected = 0
    for path in args.inputs:
        try:
            fh = open(path, encoding="utf-8")
        except OSError as exc:
            print(
                f"fleetagg: cannot read {path}: {exc.strerror or exc}",
                file=sys.stderr,
            )
            return 1
        with fh:
            for lineno, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                # Hand ingest the raw dict: its header peek drops seq
                # duplicates (spool replays, a log listed twice)
                # before paying the O(events) decode; a malformed
                # shipment still raises the contract error from there.
                try:
                    raw = json.loads(line)
                except json.JSONDecodeError as exc:
                    rejected += 1
                    print(
                        f"fleetagg: {path}:{lineno}: rejected: {exc}",
                        file=sys.stderr,
                    )
                    continue
                node = (
                    raw.get("node") if isinstance(raw, dict) else None
                )
                if not isinstance(node, str) or not node:
                    rejected += 1
                    print(
                        f"fleetagg: {path}:{lineno}: rejected: "
                        "not a shipment object (missing node)",
                        file=sys.stderr,
                    )
                    continue
                owner = ring.shard_for_node(
                    node, str(raw.get("slice_id") or "")
                )
                try:
                    shards[owner].ingest(raw)
                except WireContractError as exc:
                    rejected += 1
                    print(
                        f"fleetagg: {path}:{lineno}: rejected: {exc}",
                        file=sys.stderr,
                    )
                    continue
                shipments += 1

    # End of logs == end of stream: flush every window and group.
    # Shards flush their whole history one after another, so merge the
    # per-shard node incidents into one time-ordered stream first —
    # members of the same fault that hashed to different shards must
    # coalesce before any session closes.
    node_incidents = [
        ni
        for shard in shards.values()
        for ni in shard.close_windows(flush=True)
    ]
    node_incidents.sort(key=lambda ni: ni.ts_unix_nano)
    if args.cluster_id:
        for ni in node_incidents:
            ni.cluster = args.cluster_id
    if args.region_out:
        from tpuslo.federation.wire import (
            encode_region_envelope,
            region_envelope_json_line,
        )

        marks = [s.watermark_ns() for s in shards.values() if s.nodes]
        heads = [s.fleet_head_ns() for s in shards.values()]
        envelope = encode_region_envelope(
            args.cluster_id,
            args.region_seq,
            node_incidents,
            watermark_ns=min(marks) if marks else 0,
            head_ns=max(heads) if heads else 0,
        )
        with open(args.region_out, "w", encoding="utf-8") as fh:
            fh.write(region_envelope_json_line(envelope))
    rollup.observe(node_incidents)
    rollup.flush()

    if args.incidents_out:
        with open(args.incidents_out, "w", encoding="utf-8") as fh:
            for incident in incidents:
                fh.write(
                    json.dumps(
                        incident.to_dict(), separators=(",", ":")
                    )
                    + "\n"
                )
    if args.provenance_out:
        with open(args.provenance_out, "w", encoding="utf-8") as fh:
            for incident in incidents:
                fh.write(
                    json.dumps(
                        incident_provenance(incident),
                        separators=(",", ":"),
                    )
                    + "\n"
                )
    if args.state_out:
        state = {
            "saved_at": datetime.now(timezone.utc).isoformat(
                timespec="seconds"
            ),
            "cluster": args.cluster_id,
            "ring": ring.export_state(),
            "rollup": rollup.export_state(),
            "shards": {
                sid: shard.export_state()
                for sid, shard in shards.items()
            },
            "snapshots": {
                sid: shard.snapshot()
                for sid, shard in shards.items()
            },
        }
        with open(args.state_out, "w", encoding="utf-8") as fh:
            json.dump(state, fh, indent=2)
            fh.write("\n")

    summary = {
        "shards": args.shards,
        "shipments": shipments,
        "rejected_shipments": rejected,
        "duplicate_shipments": sum(
            s.duplicate_shipments for s in shards.values()
        ),
        "ingested_events": sum(
            s.ingested_events for s in shards.values()
        ),
        "admitted_events": sum(
            s.admitted_events for s in shards.values()
        ),
        "nodes": sum(len(s.nodes) for s in shards.values()),
        "incidents": len(incidents),
        "incidents_by_radius": {
            radius: sum(
                1 for i in incidents if i.blast_radius == radius
            )
            for radius in sorted({i.blast_radius for i in incidents})
        },
    }
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(
            "fleetagg: {shipments} shipments ({rejected} rejected, "
            "{dups} seq-dups) from {nodes} nodes -> "
            "{admitted}/{ingested} events admitted -> "
            "{incidents} fleet incidents".format(
                shipments=summary["shipments"],
                rejected=summary["rejected_shipments"],
                dups=summary["duplicate_shipments"],
                nodes=summary["nodes"],
                admitted=summary["admitted_events"],
                ingested=summary["ingested_events"],
                incidents=summary["incidents"],
            )
        )
        for incident in incidents:
            print(
                f"  {incident.incident_id}: {incident.domain} "
                f"[{incident.blast_radius}] tenant="
                f"{incident.namespace} nodes={len(incident.nodes)} "
                f"confidence={incident.confidence:.3f}"
            )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
