"""Slicecorr: cross-host collective straggler attribution.

Joins per-host agent probe-event JSONL streams for a TPU pod slice and
attributes collective stragglers to a host (compute) or ICI link.

TPU-native addition — no reference counterpart (the reference's 11
binaries are all single-host; see SURVEY.md §2.5 "multi-host
correlation" and BASELINE.json config 4).
"""

from __future__ import annotations

import argparse
import json
import sys

from tpuslo.correlation.multihost import (
    DEFAULT_RETRY_THRESHOLD,
    DEFAULT_RETRY_WINDOW_NS,
    DEFAULT_SKEW_FLOOR_MS,
    DEFAULT_SKEW_RATIO,
    SliceJoiner,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpuslo slicecorr", description=__doc__)
    p.add_argument(
        "inputs",
        nargs="*",
        help="per-host probe-event JSONL files ('-' or empty = stdin)",
    )
    p.add_argument(
        "--xprof-dir",
        default="",
        help="profiler log dir: extract per-host collective signals "
        "from the newest xprof run instead of reading JSONL "
        "(requires a trace captured with ops; see tpuslo.otel.xla_spans)",
    )
    p.add_argument(
        "--xprof-anchor-ns",
        type=int,
        default=0,
        help="wall-clock ns of profiling start; 0 emits trace-relative "
        "timestamps, internally consistent for the launch-id join but "
        "NOT time-joinable with wall-clock agent JSONL or retry "
        "evidence",
    )
    p.add_argument("--slice-id", default="slice-0")
    p.add_argument("--output", default="-", help="incidents JSONL ('-' = stdout)")
    p.add_argument("--expected-hosts", type=int, default=0)
    p.add_argument("--min-hosts", type=int, default=2)
    p.add_argument("--skew-ratio", type=float, default=DEFAULT_SKEW_RATIO)
    p.add_argument("--skew-floor-ms", type=float, default=DEFAULT_SKEW_FLOOR_MS)
    p.add_argument("--retry-threshold", type=float, default=DEFAULT_RETRY_THRESHOLD)
    p.add_argument("--retry-window-ns", type=int, default=DEFAULT_RETRY_WINDOW_NS)
    p.add_argument(
        "--summary", default="", help="optional summary JSON output path"
    )
    p.add_argument(
        "--gate",
        action="store_true",
        help="route events through the telemetry ingest gate "
        "(dedup, quarantine, clock-skew correction, watermark) "
        "before joining",
    )
    p.add_argument(
        "--quarantine-dir",
        default="",
        help="with --gate: write malformed events here (capped JSONL)",
    )
    p.add_argument("--watermark-lateness-ms", type=int, default=2000)
    p.add_argument(
        "--coordinator-host",
        type=int,
        default=0,
        help="host index whose clock anchors skew correction",
    )
    return p


def _read_events(paths: list[str]):
    for path in paths or ["-"]:
        fh = sys.stdin if path == "-" else open(path, encoding="utf-8")
        try:
            for line in fh:
                line = line.strip()
                if line:
                    yield json.loads(line)
        finally:
            if fh is not sys.stdin:
                fh.close()


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    joiner = SliceJoiner(
        expected_hosts=args.expected_hosts,
        skew_ratio=args.skew_ratio,
        skew_floor_ms=args.skew_floor_ms,
        retry_window_ns=args.retry_window_ns,
        retry_threshold=args.retry_threshold,
    )
    gate = None
    if args.gate:
        from tpuslo.ingest import GateConfig, TelemetryGate

        gate = TelemetryGate(
            GateConfig(
                watermark_lateness_ms=args.watermark_lateness_ms,
                coordinator_host=args.coordinator_host,
                quarantine_dir=args.quarantine_dir,
            )
        )
    # ValueError covers malformed JSONL (e.g. an agent killed mid-write
    # truncating a line — exactly the crash-consistency shape this
    # tool's inputs come from); same contract as attributor/collector.
    try:
        if args.xprof_dir:
            if args.inputs:
                print(
                    "slicecorr: --xprof-dir and JSONL inputs are mutually "
                    "exclusive",
                    file=sys.stderr,
                )
                return 2
            from tpuslo.otel.xla_spans import (
                extract_collective_signals_by_host,
                load_latest_trace_by_host,
            )

            by_host = load_latest_trace_by_host(
                args.xprof_dir, include_ops=True
            )
            if not by_host:
                # Silent zero-incidents here would read as "healthy".
                print(
                    f"slicecorr: no xprof profile runs under "
                    f"{args.xprof_dir!r} (expected plugins/profile/"
                    f"<run>/*.trace.json.gz)",
                    file=sys.stderr,
                )
                return 2
            if args.xprof_anchor_ns == 0:
                print(
                    "slicecorr: --xprof-anchor-ns not set; emitting "
                    "trace-relative timestamps (launch-id joins are "
                    "valid, but incidents cannot be time-joined with "
                    "wall-clock agent JSONL)",
                    file=sys.stderr,
                )
            events = extract_collective_signals_by_host(
                by_host, args.xprof_anchor_ns, slice_id=args.slice_id
            )
        else:
            events = _read_events(args.inputs)
        if gate is None:
            joiner.add_all(events)
        else:
            # Launch-id joins are exact identity, so late events still
            # join — the gate's contribution here is dedup, quarantine
            # and putting every host's evidence on one clock.
            batch = gate.admit_all(events)
            joiner.add_all(batch.all_events())
        incidents = joiner.incidents(min_hosts=args.min_hosts)

        sink = (
            sys.stdout
            if args.output == "-"
            else open(args.output, "w", encoding="utf-8")
        )
        try:
            for incident in incidents:
                sink.write(json.dumps(incident.to_dict(), sort_keys=True) + "\n")
        finally:
            if sink is not sys.stdout:
                sink.close()

        summary = {
            "ingested": joiner.ingested,
            "skipped": joiner.skipped,
            "skipped_by_reason": dict(
                sorted(joiner.skipped_by_reason.items())
            ),
            "incidents": len(incidents),
            "by_cause": {},
        }
        if gate is not None:
            summary["gate"] = gate.snapshot()
            gate.close()
        for incident in incidents:
            summary["by_cause"][incident.cause] = (
                summary["by_cause"].get(incident.cause, 0) + 1
            )
        if args.summary:
            with open(args.summary, "w", encoding="utf-8") as fh:
                json.dump(summary, fh, indent=2, sort_keys=True)
    except BrokenPipeError:
        raise  # dispatcher-level handling (exit 141, no traceback)
    except (OSError, ValueError) as exc:
        print(f"slicecorr: {exc}", file=sys.stderr)
        return 2
    print(f"slicecorr: {json.dumps(summary, sort_keys=True)}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
