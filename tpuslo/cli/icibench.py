"""`tpuslo icibench` — active ICI collective latency prober.

Runs measured XLA collectives over the device mesh and emits
schema-validated ``ici_collective_latency_ms`` probe events (JSONL),
plus a human summary on stderr.  TPU-native addition with no reference
counterpart: the reference's signals are all passive kernel probes;
TPU interconnect health benefits from an active prober that works even
when the serving workload is idle.

    # real devices (one chip: collectives compile to on-chip no-ops)
    python -m tpuslo icibench --reps 10

    # 8-device virtual CPU mesh (CI / laptops)
    python -m tpuslo icibench --force-cpu-devices 8

    # REAL cross-process collectives: N OS processes in one
    # jax.distributed runtime (the DCN-analog multi-host path);
    # optionally delay one host and let SliceJoiner attribute it
    python -m tpuslo icibench --multiprocess 2 --delay-host 1
"""

from __future__ import annotations

import argparse
import json
import sys

from tpuslo.cli.common import validate_probe


def _write_jsonl(lines: list[str], output: str) -> None:
    """'-' → stdout; else atomic write (artifact exists complete or
    not at all)."""
    if output == "-":
        sys.stdout.writelines(lines)
        return
    from tpuslo.utils import write_text_atomic

    write_text_atomic(output, "".join(lines))


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="icibench", description=__doc__)
    p.add_argument("--payload-kb", type=int, default=1024)
    p.add_argument("--reps", type=int, default=20)
    p.add_argument(
        "--ops", default="psum,all_gather,reduce_scatter,ppermute",
        help="comma-separated collective ops to probe "
        "(--multiprocess measures psum only)",
    )
    p.add_argument("--output", default="-", help="'-' for stdout or a JSONL path")
    p.add_argument("--node", default="tpu-vm-0")
    p.add_argument("--namespace", default="llm")
    p.add_argument("--slice-id", default="")
    p.add_argument("--host-index", type=int, default=-1)
    p.add_argument(
        "--force-cpu-devices", type=int, default=0,
        help="N>0 probes an N-device virtual CPU mesh (no TPU touched)",
    )
    p.add_argument(
        "--multiprocess", type=int, default=0,
        help="N>1 probes REAL cross-process collectives: N OS processes "
        "join one jax.distributed runtime (gloo) and measure psum "
        "launches over the global mesh — the DCN-analog multi-host path",
    )
    p.add_argument(
        "--delay-host", type=int, default=-1,
        help="with --multiprocess: delay this host per launch so the "
        "collective genuinely stalls the punctual hosts; SliceJoiner "
        "must attribute it",
    )
    p.add_argument("--delay-ms", type=float, default=150.0)
    p.add_argument(
        "--n-slices", type=int, default=1,
        help="with --multiprocess: partition hosts into slices; each "
        "launch measures intra-slice AND global rounds and emits the "
        "difference as dcn_transfer_latency_ms (the measured "
        "cross-slice component)",
    )
    p.add_argument(
        "--report", default="",
        help="with --multiprocess: also write the straggler-join report "
        "(incidents, attribution verdicts) as JSON here",
    )
    args = p.parse_args(argv)

    # Flag validation happens BEFORE any jax backend init (which can be
    # slow or hang) and regardless of mode — the multiprocess path must
    # not silently accept flags the single-process path rejects.
    ops = tuple(o.strip() for o in args.ops.split(",") if o.strip())
    from tpuslo.parallel.collectives import DEFAULT_OPS

    unknown = [o for o in ops if o not in DEFAULT_OPS]
    if unknown or not ops:
        print(
            f"icibench: unknown ops {unknown or '(none given)'}; "
            f"valid: {', '.join(DEFAULT_OPS)}",
            file=sys.stderr,
        )
        return 2

    if args.multiprocess > 1:
        return _run_multiprocess(args, ops)

    if args.force_cpu_devices > 0:
        # Must happen before the first jax backend touch; jax.config
        # (not the JAX_PLATFORMS env var) per the tunnel-hang gotcha.
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.force_cpu_devices}"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")

    from tpuslo.parallel.collectives import bench_collectives, probes_to_events

    probes = bench_collectives(
        payload_bytes=args.payload_kb * 1024, reps=args.reps, ops=ops
    )
    events = probes_to_events(
        probes,
        node=args.node,
        namespace=args.namespace,
        slice_id=args.slice_id,
        host_index=args.host_index,
    )

    # Validate EVERY event before writing ANY output: a mid-loop abort
    # used to leave a partial JSONL artifact that downstream consumers
    # (CI line-count check, weekly artifact upload) could read as a
    # complete capture.
    lines = []
    for probe, event in zip(probes, events):
        if not validate_probe(event):
            print(
                f"icibench: schema-invalid event for {probe.op}; "
                "no output written",
                file=sys.stderr,
            )
            return 1
        lines.append(json.dumps(event.to_dict()) + "\n")

    _write_jsonl(lines, args.output)
    for probe in probes:
        print(
            f"icibench: {probe.op:>14} n={probe.n_devices} "
            f"payload={probe.payload_bytes_per_device >> 10}KiB/dev "
            f"p50={probe.p50_ms:.3f}ms p95={probe.p95_ms:.3f}ms",
            file=sys.stderr,
        )
    return 0


def _run_multiprocess(args, ops) -> int:
    """Cross-process collective probe; same output contract as the
    single-process path (schema-validated probe-event JSONL)."""
    from tpuslo.schema import validate_probe_payload

    if args.n_slices > 1 and args.multiprocess % args.n_slices:
        print(
            f"icibench: --n-slices {args.n_slices} must divide "
            f"--multiprocess {args.multiprocess} (slices are process "
            "groups)",
            file=sys.stderr,
        )
        return 2
    if args.delay_host >= args.multiprocess:
        print(
            f"icibench: --delay-host {args.delay_host} is out of range "
            f"for --multiprocess {args.multiprocess} (hosts are "
            f"0..{args.multiprocess - 1})",
            file=sys.stderr,
        )
        return 2
    if set(ops) != {"psum"} and tuple(ops) != (
        "psum", "all_gather", "reduce_scatter", "ppermute",
    ):
        print(
            "icibench: --multiprocess measures psum only; other --ops "
            "are ignored",
            file=sys.stderr,
        )

    from tpuslo.parallel.distributed import run_distributed_probe

    report = run_distributed_probe(
        n_processes=args.multiprocess,
        launches=args.reps,
        payload_kb=args.payload_kb,
        delay_ms=args.delay_ms if args.delay_host >= 0 else 0.0,
        delayed_host=args.delay_host,
        n_slices=args.n_slices,
    )
    lines = []
    for event_dict in report["events"]:
        # Dict-level hot-path validation (structural fast path with a
        # jsonschema fallback): high-rep probe runs emit thousands of
        # events per report.
        if not validate_probe_payload(event_dict):
            print(
                "icibench: schema-invalid cross-process event; "
                "no output written",
                file=sys.stderr,
            )
            return 1
        lines.append(json.dumps(event_dict) + "\n")
    _write_jsonl(lines, args.output)
    if args.report:
        summary = {k: v for k, v in report.items() if k != "events"}
        _write_jsonl([json.dumps(summary) + "\n"], args.report)
    print(
        f"icibench: {report['events_measured']} cross-process events "
        f"over {args.multiprocess} hosts, "
        f"{len(report['incidents'])} straggler incidents",
        file=sys.stderr,
    )
    return 0 if not report["errors"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
