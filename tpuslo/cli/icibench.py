"""`tpuslo icibench` — active ICI collective latency prober.

Runs measured XLA collectives over the device mesh and emits
schema-validated ``ici_collective_latency_ms`` probe events (JSONL),
plus a human summary on stderr.  TPU-native addition with no reference
counterpart: the reference's signals are all passive kernel probes;
TPU interconnect health benefits from an active prober that works even
when the serving workload is idle.

    # real devices (one chip: collectives compile to on-chip no-ops)
    python -m tpuslo icibench --reps 10

    # 8-device virtual CPU mesh (CI / laptops)
    python -m tpuslo icibench --force-cpu-devices 8
"""

from __future__ import annotations

import argparse
import json
import sys

from tpuslo.cli.common import validate_probe


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="icibench", description=__doc__)
    p.add_argument("--payload-kb", type=int, default=1024)
    p.add_argument("--reps", type=int, default=20)
    p.add_argument(
        "--ops", default="psum,all_gather,reduce_scatter,ppermute",
        help="comma-separated collective ops to probe",
    )
    p.add_argument("--output", default="-", help="'-' for stdout or a JSONL path")
    p.add_argument("--node", default="tpu-vm-0")
    p.add_argument("--namespace", default="llm")
    p.add_argument("--slice-id", default="")
    p.add_argument("--host-index", type=int, default=-1)
    p.add_argument(
        "--force-cpu-devices", type=int, default=0,
        help="N>0 probes an N-device virtual CPU mesh (no TPU touched)",
    )
    args = p.parse_args(argv)

    ops = tuple(o.strip() for o in args.ops.split(",") if o.strip())
    from tpuslo.parallel.collectives import DEFAULT_OPS

    unknown = [o for o in ops if o not in DEFAULT_OPS]
    if unknown or not ops:
        # Fail before any jax backend init (which can be slow or hang).
        print(
            f"icibench: unknown ops {unknown or '(none given)'}; "
            f"valid: {', '.join(DEFAULT_OPS)}",
            file=sys.stderr,
        )
        return 2

    if args.force_cpu_devices > 0:
        # Must happen before the first jax backend touch; jax.config
        # (not the JAX_PLATFORMS env var) per the tunnel-hang gotcha.
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.force_cpu_devices}"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")

    from tpuslo.parallel.collectives import bench_collectives, probes_to_events

    probes = bench_collectives(
        payload_bytes=args.payload_kb * 1024, reps=args.reps, ops=ops
    )
    events = probes_to_events(
        probes,
        node=args.node,
        namespace=args.namespace,
        slice_id=args.slice_id,
        host_index=args.host_index,
    )

    # Validate EVERY event before writing ANY output: a mid-loop abort
    # used to leave a partial JSONL artifact that downstream consumers
    # (CI line-count check, weekly artifact upload) could read as a
    # complete capture.
    lines = []
    for probe, event in zip(probes, events):
        if not validate_probe(event):
            print(
                f"icibench: schema-invalid event for {probe.op}; "
                "no output written",
                file=sys.stderr,
            )
            return 1
        lines.append(json.dumps(event.to_dict()) + "\n")

    if args.output == "-":
        sys.stdout.writelines(lines)
    else:
        # Temp file + atomic rename: the artifact either exists complete
        # or not at all.
        import os
        import tempfile

        out_dir = os.path.dirname(os.path.abspath(args.output)) or "."
        fd, tmp = tempfile.mkstemp(dir=out_dir, suffix=".tmp")
        try:
            # mkstemp creates 0600; match what plain open() would have
            # produced so cross-user artifact consumers keep working.
            umask = os.umask(0)
            os.umask(umask)
            os.fchmod(fd, 0o666 & ~umask)
            with os.fdopen(fd, "w") as fh:
                fh.writelines(lines)
            os.replace(tmp, args.output)
            tmp = None
        finally:
            if tmp is not None:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
    for probe in probes:
        print(
            f"icibench: {probe.op:>14} n={probe.n_devices} "
            f"payload={probe.payload_bytes_per_device >> 10}KiB/dev "
            f"p50={probe.p50_ms:.3f}ms p95={probe.p95_ms:.3f}ms",
            file=sys.stderr,
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
