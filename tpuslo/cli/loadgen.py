"""Loadgen: deterministic request-trace JSONL generator.

Reference: ``cmd/loadgen/main.go`` — request profiles with expected
TTFT ranges; generates traces, does not drive HTTP.  The TPU-native
build adds a ``context_128k`` profile for long-context serving, and
``--slo-out`` emits a parallel ``RequestOutcome`` JSONL (the burn
engine's SLI stream) so error-budget scenarios can be rehearsed
offline: ``loadgen --slo-out out.jsonl --error-rate 0.3
--error-after-s 1800`` then ``sloctl budget --replay out.jsonl``.
"""

from __future__ import annotations

import argparse
import json
import random
import sys
from datetime import datetime, timezone

# profile -> (prompt_tokens, max_new_tokens, expected_ttft_ms_range)
PROFILES = {
    "chat_short": (64, 128, (150, 450)),
    "rag_medium": (512, 256, (300, 800)),
    "context_long": (4096, 512, (600, 1600)),
    "context_128k": (131072, 512, (2500, 8000)),
}

#: Deterministic default stream epoch for --slo-out timestamps.
DEFAULT_START = "2026-01-01T00:00:00Z"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpuslo loadgen", description=__doc__)
    p.add_argument("--profile", default="rag_medium", choices=sorted(PROFILES))
    p.add_argument("--rps", type=float, default=2.0)
    p.add_argument("--duration-s", type=float, default=30.0)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--output", default="-")
    p.add_argument(
        "--slo-out",
        default="",
        help="also emit one RequestOutcome JSONL line per request "
        "(tenant/ttft/tpot/tokens/status) — the burn engine's SLI "
        "stream, replayable via `sloctl budget --replay`",
    )
    p.add_argument(
        "--tenant",
        default="default",
        help="tenant stamped on --slo-out outcomes",
    )
    p.add_argument(
        "--error-rate",
        type=float,
        default=0.0,
        help="fraction of requests marked status=error on --slo-out",
    )
    p.add_argument(
        "--error-after-s",
        type=float,
        default=0.0,
        help="errors only start this many seconds into the run "
        "(clean warm-up, then burn — a one-command burn scenario)",
    )
    p.add_argument(
        "--slow-ttft-rate",
        type=float,
        default=0.0,
        help="fraction of requests with TTFT 2-4x past the profile's "
        "expected max (latency-objective burn on --slo-out)",
    )
    p.add_argument(
        "--start",
        default=DEFAULT_START,
        help="RFC3339 epoch of the generated stream (deterministic "
        "timestamps; the burn engine runs on event time)",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    prompt_tokens, max_new, ttft_range = PROFILES[args.profile]
    rng = random.Random(args.seed)
    count = max(1, int(args.rps * args.duration_s))
    interval_ms = 1000.0 / args.rps
    start = datetime.fromisoformat(
        args.start.replace("Z", "+00:00")
    ).astimezone(timezone.utc)
    base_ns = int(start.timestamp() * 1e9)

    sink = sys.stdout if args.output == "-" else open(args.output, "w", encoding="utf-8")
    slo_sink = (
        open(args.slo_out, "w", encoding="utf-8") if args.slo_out else None
    )
    try:
        for idx in range(count):
            jitter = rng.uniform(-0.2, 0.2) * interval_ms
            offset_ms = round(idx * interval_ms + jitter, 3)
            record = {
                "request_id": f"load-req-{idx + 1:05d}",
                "trace_id": f"load-trace-{idx + 1:05d}",
                "profile": args.profile,
                "offset_ms": offset_ms,
                "prompt_tokens": prompt_tokens,
                "max_new_tokens": max_new,
                "expected_ttft_ms_min": ttft_range[0],
                "expected_ttft_ms_max": ttft_range[1],
                "stream": True,
            }
            sink.write(json.dumps(record, separators=(",", ":")) + "\n")
            if slo_sink is not None:
                in_error_window = (
                    offset_ms / 1000.0 >= args.error_after_s
                )
                error = (
                    in_error_window and rng.random() < args.error_rate
                )
                slow = rng.random() < args.slow_ttft_rate
                ttft_ms = (
                    rng.uniform(2.0 * ttft_range[1], 4.0 * ttft_range[1])
                    if slow
                    else rng.uniform(*ttft_range)
                )
                outcome = {
                    "tenant": args.tenant,
                    "ts_unix_nano": base_ns + int(offset_ms * 1e6),
                    "ttft_ms": round(ttft_ms, 3),
                    "tpot_ms": round(rng.uniform(20.0, 60.0), 3),
                    "tokens": max_new,
                    "status": "error" if error else "ok",
                    "request_id": record["request_id"],
                }
                slo_sink.write(
                    json.dumps(outcome, separators=(",", ":")) + "\n"
                )
    finally:
        if sink is not sys.stdout:
            sink.close()
        if slo_sink is not None:
            slo_sink.close()
    print(
        f"loadgen: wrote {count} request records"
        + (f" + {count} slo outcomes to {args.slo_out}"
           if args.slo_out else ""),
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
