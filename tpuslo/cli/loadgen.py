"""Loadgen: deterministic request-trace JSONL generator.

Reference: ``cmd/loadgen/main.go`` — request profiles with expected
TTFT ranges; generates traces, does not drive HTTP.  The TPU-native
build adds a ``context_128k`` profile for long-context serving, and
``--slo-out`` emits a parallel ``RequestOutcome`` JSONL (the burn
engine's SLI stream) so error-budget scenarios can be rehearsed
offline: ``loadgen --slo-out out.jsonl --error-rate 0.3
--error-after-s 1800`` then ``sloctl budget --replay out.jsonl``.

The front-door bench (ISSUE 12) drives its admission layer from this
module's :func:`synthesize_requests`, so the arrival process is shaped
here: ``--arrival steady|burst|ramp|poisson`` picks the inter-arrival
model, ``--tenants N``/``--tenant-mix`` spreads requests over a
multi-tenant population with weighted shares, and ``--prefix-rate``
marks a fraction of each tenant's requests as sharing a per-tenant
prompt prefix (``prefix_group``) — the signal prefix-cache-aware
placement batches on.

The router bench (ISSUE 16) needs prefix traffic an affinity router
can actually be *wrong* about: one group per tenant makes affinity
routing trivially easy (any stable hash wins).  ``--prefix-groups N``
distributes each tenant's prefix hits over N distinct, fleet-wide
groups with per-tenant weighting (:func:`prefix_group_weights`): every
tenant leans on a different subset of the shared groups, so placement
quality depends on tracking *which engine is warm for which group*,
not on tenant identity.
"""

from __future__ import annotations

import argparse
import json
import math
import random
import sys
from datetime import datetime, timezone

# profile -> (prompt_tokens, max_new_tokens, expected_ttft_ms_range)
PROFILES = {
    "chat_short": (64, 128, (150, 450)),
    "rag_medium": (512, 256, (300, 800)),
    "context_long": (4096, 512, (600, 1600)),
    "context_128k": (131072, 512, (2500, 8000)),
}

#: Arrival processes the bench lanes can request by name.
ARRIVALS = ("steady", "burst", "ramp", "poisson")

#: Deterministic default stream epoch for --slo-out timestamps.
DEFAULT_START = "2026-01-01T00:00:00Z"


def parse_tenant_mix(spec: str, n_tenants: int) -> list[float]:
    """Normalized tenant weights from a ``--tenant-mix`` spec.

    ``spec`` is comma-separated positive weights (``"70,20,10"``);
    empty means uniform.  Fewer weights than tenants pad with the last
    weight; extras are an error (a silently-dropped weight would skew
    the mix the bench asserts on).
    """
    if n_tenants < 1:
        raise ValueError("--tenants must be >= 1")
    if not spec:
        weights = [1.0] * n_tenants
    else:
        # Every comma-separated entry must parse: silently dropping an
        # empty one ('70,,10') would shift later weights onto the
        # wrong tenants — the exact skew this function exists to
        # prevent.
        try:
            weights = [float(w) for w in spec.split(",")]
        except ValueError as exc:
            raise ValueError(
                f"--tenant-mix entry is not a number: {spec!r}"
            ) from exc
        if len(weights) > n_tenants:
            raise ValueError(
                f"--tenant-mix has {len(weights)} weights for "
                f"{n_tenants} tenants"
            )
        if any(w <= 0 for w in weights):
            raise ValueError("--tenant-mix weights must be positive")
        weights += [weights[-1]] * (n_tenants - len(weights))
    total = sum(weights)
    return [w / total for w in weights]


def prefix_group_weights(
    tenant_idx: int, prefix_groups: int
) -> list[float]:
    """Normalized per-tenant weights over the shared prefix groups.

    Tenant ``t`` favors group ``t % N`` and decays harmonically over
    the groups after it (cyclically): group ``(t + j) % N`` carries
    weight ``1 / (1 + j)``.  Deterministic and parameter-free, so the
    bench and the CLI agree on the mix without sharing an RNG; every
    tenant's hot set is distinct, which is exactly what makes
    prefix-affinity routing non-trivial to get right.
    """
    if prefix_groups < 1:
        raise ValueError("--prefix-groups must be >= 1")
    weights = [0.0] * prefix_groups
    for j in range(prefix_groups):
        weights[(tenant_idx + j) % prefix_groups] = 1.0 / (1 + j)
    total = sum(weights)
    return [w / total for w in weights]


def prefix_group_name(group_idx: int) -> str:
    """Fleet-wide group naming (``grp-03/sys``): groups are shared
    ACROSS tenants, unlike the legacy per-tenant ``<tenant>/sys``."""
    return f"grp-{group_idx:02d}/sys"


def arrival_offsets_ms(
    arrival: str,
    count: int,
    duration_s: float,
    rng: random.Random,
) -> list[float]:
    """Monotonic arrival offsets (ms) for ``count`` requests.

    * ``steady`` — fixed interval with ±20% jitter (the legacy shape);
    * ``burst`` — arrivals clump into square-wave bursts: 4 bursts
      over the duration, each packing 1/4 of the traffic into the
      first 20% of its window (the TTFT-p99 stressor);
    * ``ramp`` — arrival rate grows linearly from ~0 to 2x the mean
      (offsets follow sqrt(u): a warm-up then saturation);
    * ``poisson`` — exponential inter-arrivals at the mean rate.
    """
    if count < 1:
        return []
    duration_ms = max(1.0, duration_s * 1000.0)
    interval_ms = duration_ms / count
    if arrival == "steady":
        offsets = [
            i * interval_ms + rng.uniform(-0.2, 0.2) * interval_ms
            for i in range(count)
        ]
    elif arrival == "burst":
        n_bursts = 4
        window_ms = duration_ms / n_bursts
        offsets = []
        for i in range(count):
            burst = i % n_bursts
            offsets.append(
                burst * window_ms
                + rng.random() * 0.2 * window_ms
            )
    elif arrival == "ramp":
        offsets = [
            math.sqrt(rng.random()) * duration_ms for _ in range(count)
        ]
    elif arrival == "poisson":
        t = 0.0
        offsets = []
        for _ in range(count):
            t += rng.expovariate(1.0 / interval_ms)
            offsets.append(t)
    else:
        raise ValueError(
            f"unknown arrival model {arrival!r} (one of {ARRIVALS})"
        )
    return [round(v, 3) for v in sorted(max(0.0, o) for o in offsets)]


def synthesize_requests(
    profile: str = "rag_medium",
    rps: float = 2.0,
    duration_s: float = 30.0,
    seed: int = 42,
    arrival: str = "steady",
    tenants: int = 1,
    tenant_mix: str = "",
    prefix_rate: float = 0.0,
    prefix_groups: int = 1,
) -> list[dict]:
    """Deterministic multi-tenant request records (offset-sorted).

    Each record carries the legacy trace fields plus ``tenant`` and —
    for the ``prefix_rate`` fraction of a tenant's requests —
    ``prefix_group``: requests in one group share a prompt prefix, the
    unit prefix caching snapshots once and the front-door scheduler
    batches together.  With ``prefix_groups == 1`` (the default) the
    group is the legacy per-tenant ``"<tenant>/sys"``; with ``N > 1``
    hits spread over N fleet-wide groups (``grp-00/sys``..) under
    :func:`prefix_group_weights` per-tenant weighting.
    """
    prompt_tokens, max_new, ttft_range = PROFILES[profile]
    rng = random.Random(seed)
    count = max(1, int(rps * duration_s))
    weights = parse_tenant_mix(tenant_mix, tenants)
    tenant_names = [f"tenant-{i:02d}" for i in range(tenants)]
    group_weights = [
        prefix_group_weights(t, prefix_groups) for t in range(tenants)
    ]
    group_names = [prefix_group_name(g) for g in range(prefix_groups)]
    offsets = arrival_offsets_ms(arrival, count, duration_s, rng)
    records = []
    for idx, offset_ms in enumerate(offsets):
        tenant = rng.choices(tenant_names, weights=weights)[0]
        record = {
            "request_id": f"load-req-{idx + 1:05d}",
            "trace_id": f"load-trace-{idx + 1:05d}",
            "profile": profile,
            "offset_ms": offset_ms,
            "tenant": tenant,
            "prompt_tokens": prompt_tokens,
            "max_new_tokens": max_new,
            "expected_ttft_ms_min": ttft_range[0],
            "expected_ttft_ms_max": ttft_range[1],
            "stream": True,
        }
        if rng.random() < prefix_rate:
            if prefix_groups == 1:
                record["prefix_group"] = f"{tenant}/sys"
            else:
                tenant_idx = tenant_names.index(tenant)
                record["prefix_group"] = rng.choices(
                    group_names, weights=group_weights[tenant_idx]
                )[0]
        records.append(record)
    return records


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpuslo loadgen", description=__doc__)
    p.add_argument("--profile", default="rag_medium", choices=sorted(PROFILES))
    p.add_argument("--rps", type=float, default=2.0)
    p.add_argument("--duration-s", type=float, default=30.0)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--output", default="-")
    p.add_argument(
        "--arrival",
        default="steady",
        choices=ARRIVALS,
        help="inter-arrival model: steady (jittered fixed rate), "
        "burst (4 square-wave bursts — the TTFT-p99 stressor), ramp "
        "(rate grows to 2x mean), poisson (exponential gaps)",
    )
    p.add_argument(
        "--tenants",
        type=int,
        default=1,
        help="number of synthetic tenants (tenant-00..); requests "
        "spread per --tenant-mix",
    )
    p.add_argument(
        "--tenant-mix",
        default="",
        help="comma-separated positive tenant weights, e.g. "
        "'70,20,10' (default uniform; short lists pad with the last "
        "weight)",
    )
    p.add_argument(
        "--prefix-rate",
        type=float,
        default=0.0,
        help="fraction of each tenant's requests stamped with a "
        "shared prefix_group (prefix-cache-aware placement batches "
        "these onto snapshot-reusing slots)",
    )
    p.add_argument(
        "--prefix-groups",
        type=int,
        default=1,
        help="number of distinct fleet-wide prefix groups the "
        "--prefix-rate hits spread over (grp-00/sys..), weighted per "
        "tenant so every tenant leans on a different hot set; 1 keeps "
        "the legacy per-tenant '<tenant>/sys' group",
    )
    p.add_argument(
        "--slo-out",
        default="",
        help="also emit one RequestOutcome JSONL line per request "
        "(tenant/ttft/tpot/tokens/status) — the burn engine's SLI "
        "stream, replayable via `sloctl budget --replay`",
    )
    p.add_argument(
        "--tenant",
        default="default",
        help="tenant stamped on --slo-out outcomes when --tenants is "
        "1 (multi-tenant runs stamp each record's own tenant)",
    )
    p.add_argument(
        "--error-rate",
        type=float,
        default=0.0,
        help="fraction of requests marked status=error on --slo-out",
    )
    p.add_argument(
        "--error-after-s",
        type=float,
        default=0.0,
        help="errors only start this many seconds into the run "
        "(clean warm-up, then burn — a one-command burn scenario)",
    )
    p.add_argument(
        "--slow-ttft-rate",
        type=float,
        default=0.0,
        help="fraction of requests with TTFT 2-4x past the profile's "
        "expected max (latency-objective burn on --slo-out)",
    )
    p.add_argument(
        "--start",
        default=DEFAULT_START,
        help="RFC3339 epoch of the generated stream (deterministic "
        "timestamps; the burn engine runs on event time)",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    _prompt_tokens, max_new, ttft_range = PROFILES[args.profile]
    rng = random.Random(args.seed ^ 0x5105)  # outcome noise stream
    records = synthesize_requests(
        profile=args.profile,
        rps=args.rps,
        duration_s=args.duration_s,
        seed=args.seed,
        arrival=args.arrival,
        tenants=args.tenants,
        tenant_mix=args.tenant_mix,
        prefix_rate=args.prefix_rate,
        prefix_groups=args.prefix_groups,
    )
    start = datetime.fromisoformat(
        args.start.replace("Z", "+00:00")
    ).astimezone(timezone.utc)
    base_ns = int(start.timestamp() * 1e9)

    sink = sys.stdout if args.output == "-" else open(args.output, "w", encoding="utf-8")
    slo_sink = (
        open(args.slo_out, "w", encoding="utf-8") if args.slo_out else None
    )
    try:
        for record in records:
            if args.tenants == 1:
                record = {**record, "tenant": args.tenant}
            sink.write(json.dumps(record, separators=(",", ":")) + "\n")
            if slo_sink is not None:
                offset_ms = record["offset_ms"]
                in_error_window = (
                    offset_ms / 1000.0 >= args.error_after_s
                )
                error = (
                    in_error_window and rng.random() < args.error_rate
                )
                slow = rng.random() < args.slow_ttft_rate
                ttft_ms = (
                    rng.uniform(2.0 * ttft_range[1], 4.0 * ttft_range[1])
                    if slow
                    else rng.uniform(*ttft_range)
                )
                outcome = {
                    "tenant": record["tenant"],
                    "ts_unix_nano": base_ns + int(offset_ms * 1e6),
                    "ttft_ms": round(ttft_ms, 3),
                    "tpot_ms": round(rng.uniform(20.0, 60.0), 3),
                    "tokens": max_new,
                    "status": "error" if error else "ok",
                    "request_id": record["request_id"],
                }
                slo_sink.write(
                    json.dumps(outcome, separators=(",", ":")) + "\n"
                )
    finally:
        if sink is not sys.stdout:
            sink.close()
        if slo_sink is not None:
            slo_sink.close()
    print(
        f"loadgen: wrote {len(records)} request records"
        + (f" + {len(records)} slo outcomes to {args.slo_out}"
           if args.slo_out else ""),
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
