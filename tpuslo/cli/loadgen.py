"""Loadgen: deterministic request-trace JSONL generator.

Reference: ``cmd/loadgen/main.go`` — request profiles with expected
TTFT ranges; generates traces, does not drive HTTP.  The TPU-native
build adds a ``context_128k`` profile for long-context serving.
"""

from __future__ import annotations

import argparse
import json
import random
import sys

# profile -> (prompt_tokens, max_new_tokens, expected_ttft_ms_range)
PROFILES = {
    "chat_short": (64, 128, (150, 450)),
    "rag_medium": (512, 256, (300, 800)),
    "context_long": (4096, 512, (600, 1600)),
    "context_128k": (131072, 512, (2500, 8000)),
}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpuslo loadgen", description=__doc__)
    p.add_argument("--profile", default="rag_medium", choices=sorted(PROFILES))
    p.add_argument("--rps", type=float, default=2.0)
    p.add_argument("--duration-s", type=float, default=30.0)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--output", default="-")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    prompt_tokens, max_new, ttft_range = PROFILES[args.profile]
    rng = random.Random(args.seed)
    count = max(1, int(args.rps * args.duration_s))
    interval_ms = 1000.0 / args.rps

    sink = sys.stdout if args.output == "-" else open(args.output, "w", encoding="utf-8")
    try:
        for idx in range(count):
            jitter = rng.uniform(-0.2, 0.2) * interval_ms
            record = {
                "request_id": f"load-req-{idx + 1:05d}",
                "trace_id": f"load-trace-{idx + 1:05d}",
                "profile": args.profile,
                "offset_ms": round(idx * interval_ms + jitter, 3),
                "prompt_tokens": prompt_tokens,
                "max_new_tokens": max_new,
                "expected_ttft_ms_min": ttft_range[0],
                "expected_ttft_ms_max": ttft_range[1],
                "stream": True,
            }
            sink.write(json.dumps(record, separators=(",", ":")) + "\n")
    finally:
        if sink is not sys.stdout:
            sink.close()
    print(f"loadgen: wrote {count} request records", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
