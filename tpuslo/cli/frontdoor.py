"""frontdoor: the serving front door as a supervised live process.

Runs one :class:`~tpuslo.models.frontdoor.FrontDoorEngine` (llama-tiny
target/draft pair) under the PR 4 crash-safe runtime, serving a
two-tenant traffic loop, with its own co-located remediation agent:
the tenant whose requests keep failing burns its error budget, the
burn trips fast-burn, a real hbm_pressure fault sample attributes
through the Bayesian posterior, the remediation policy demotes the
tenant, and the **live** admission order flips — the healthy tenant
admitted ahead of the demoted one on the very next cycle.

Every cycle appends one status JSONL line (``--status-out``): that
file is simultaneously the supervisor's heartbeat artifact (mtime)
and the chaos lane's audit record (burn state, admission order,
remediation phase, restore evidence).  kill -9 at any point and a
restart with the same argv resumes from the runtime snapshot —
in-flight streams, burn windows, and the remediation ledger included
— without ever applying the same action twice.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from datetime import datetime, timezone
from typing import Any


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpuslo frontdoor", description=__doc__
    )
    p.add_argument(
        "--cycles", type=int, default=0, help="0 = until --run-for-s"
    )
    p.add_argument(
        "--run-for-s",
        type=float,
        default=0.0,
        help="stop after this many seconds (0 = until --cycles or "
        "SIGTERM)",
    )
    p.add_argument("--interval-s", type=float, default=0.2)
    p.add_argument(
        "--tenant",
        default="burny",
        help="the tenant whose traffic burns budget (the remediation "
        "target); the healthy tenant is always 'steady'",
    )
    p.add_argument("--max-new-tokens", type=int, default=3)
    p.add_argument(
        "--status-out",
        default="",
        help="per-cycle status JSONL; doubles as the supervisor's "
        "heartbeat artifact",
    )
    p.add_argument(
        "--state-dir",
        default="",
        help="crash-safe runtime snapshots land here "
        "(frontdoor-state.json)",
    )
    p.add_argument("--snapshot-interval-s", type=float, default=0.0)
    p.add_argument(
        "--json",
        action="store_true",
        help="print the run summary as JSON instead of text",
    )
    return p


def _prefeed_burn(burn, tenant: str, now_s: float) -> None:
    """Backfill ~25 minutes of failing history for ``tenant`` so the
    fast-burn window trips within the first live cycles instead of
    after a real hour of traffic."""
    from tpuslo.sloengine import RequestOutcome

    for j in range(600):
        ts = now_s - 1500.0 + j * 2.5
        burn.record(
            RequestOutcome(
                tenant=tenant,
                ts_unix_nano=int(ts * 1e9),
                ttft_ms=50.0,
                tpot_ms=10.0,
                tokens=8,
                status="error" if j % 2 == 0 else "ok",
            )
        )


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    from tpuslo.attribution.bayesian import BayesianAttributor
    from tpuslo.faultreplay.generator import generate_fault_samples
    from tpuslo.models.frontdoor import (
        FrontDoorEngine,
        FrontDoorObserver,
    )
    from tpuslo.models.llama import llama_tiny
    from tpuslo.models.serve import ServeEngine
    from tpuslo.remediation.actions import ActionBindings
    from tpuslo.remediation.engine import RemediationEngine
    from tpuslo.remediation.policy import AttributionContext
    from tpuslo.runtime import (
        AgentRuntime,
        DrainSignal,
        StateStore,
        install_drain_handler,
    )
    from tpuslo.sloengine import BurnEngine, RequestOutcome

    tenant = args.tenant
    healthy = "steady"

    cfg = llama_tiny(max_seq_len=128)
    target = ServeEngine(cfg=cfg, rng_seed=0)
    # Same seed => self-draft: acceptance 1.0, deterministic and fast.
    draft = ServeEngine(cfg=cfg, rng_seed=0)

    order: list[str] = []

    class _OrderObserver(FrontDoorObserver):
        def admitted(self, t: str) -> None:
            order.append(t)

    burn = BurnEngine()
    fd = FrontDoorEngine(
        target,
        draft,
        k=3,
        max_slots=1,
        burn_engine=burn,
        observer=_OrderObserver(),
    )
    remediation = RemediationEngine(
        bindings=ActionBindings(burn_engine=burn),
        log=lambda msg: print(f"frontdoor: {msg}", file=sys.stderr),
    )

    progress = {"next_cycle": 0, "prefed": False}
    store = None
    if args.state_dir:
        import os

        store = StateStore(
            os.path.join(args.state_dir, "frontdoor-state.json"),
            interval_s=args.snapshot_interval_s,
        )
    runtime = AgentRuntime(
        store,
        log=lambda msg: print(f"frontdoor: {msg}", file=sys.stderr),
    )
    runtime.register(
        "progress",
        lambda: dict(progress),
        lambda s: progress.update(s or {}),
    )
    runtime.register("burn", burn.export_state, burn.restore_state)
    runtime.register(
        "frontdoor", fd.export_state, fd.restore_state
    )
    runtime.register(
        "remediation",
        remediation.export_state,
        remediation.restore_state,
    )

    restore_outcome = runtime.restore()
    if runtime.enabled:
        detail = ""
        if restore_outcome == "restored":
            detail = (
                f" (age {runtime.restored_age_s:.1f}s, components: "
                f"{','.join(runtime.restored_components) or 'none'})"
            )
        print(
            f"frontdoor: runtime: snapshot {restore_outcome}{detail}; "
            f"resuming at cycle {progress['next_cycle']}",
            file=sys.stderr,
        )
    if not progress.get("prefed"):
        _prefeed_burn(burn, tenant, time.time())
        progress["prefed"] = True

    status_fh = None
    if args.status_out:
        status_fh = open(args.status_out, "a", encoding="utf-8")

    def _status(line: dict[str, Any]) -> None:
        if status_fh is None:
            return
        status_fh.write(
            json.dumps(line, separators=(",", ":")) + "\n"
        )
        status_fh.flush()

    print(
        f"frontdoor: serving tenants [{tenant}, {healthy}] "
        f"(max_slots=1, k=3); remediation loop armed",
        file=sys.stderr,
    )

    restore_handlers = install_drain_handler()
    deadline = (
        time.monotonic() + args.run_for_s
        if args.run_for_s > 0
        else float("inf")
    )
    flips = 0
    applied_record = None
    try:
        cycle = progress["next_cycle"]
        while time.monotonic() < deadline:
            if args.cycles and cycle >= args.cycles:
                break
            now_s = time.time()
            order.clear()
            demoted = any(
                rec.kind == "demote_tenant"
                and rec.phase
                in ("applying", "verifying", "confirmed")
                for rec in remediation.records()
            )
            # The burning tenant queued FIRST: pre-demotion FIFO
            # admits it first; post-demotion priority admits it last.
            fd.submit(
                f"cycle {cycle} {tenant}",
                tenant=tenant,
                max_new_tokens=args.max_new_tokens,
                stop_at_eos=False,
            )
            fd.submit(
                f"cycle {cycle} {healthy}",
                tenant=healthy,
                max_new_tokens=args.max_new_tokens,
                stop_at_eos=False,
            )
            fd.run()
            admitted = list(order)
            # Live outcomes keep the budget honest: the burning tenant
            # fails until the demotion lands, then recovers (so the
            # verifier can confirm the action helped).
            for t, status in (
                (tenant, "ok" if demoted else "error"),
                (healthy, "ok"),
            ):
                burn.record(
                    RequestOutcome(
                        tenant=t,
                        ts_unix_nano=int(now_s * 1e9),
                        ttft_ms=50.0,
                        tpot_ms=10.0,
                        tokens=args.max_new_tokens,
                        status=status,
                    )
                )
            burn.evaluate(now_s)
            burn_state = burn.tenant_burn_state(tenant)

            record = None
            if burn_state == "fast_burn" and not demoted:
                # The co-located agent: a real fault sample, the real
                # posterior, the real policy — nothing scripted.
                sample = generate_fault_samples(
                    "hbm_pressure",
                    1,
                    start=datetime.fromtimestamp(
                        now_s, tz=timezone.utc
                    ),
                )[0]
                attribution = BayesianAttributor().attribute_sample(
                    sample
                )
                record = remediation.consider(
                    AttributionContext(
                        incident_id=f"inc-live-hbm-{tenant}",
                        domain=attribution.predicted_fault_domain,
                        confidence=attribution.confidence,
                        burn_state=burn_state,
                        burn_rate=burn.max_active_burn(),
                        tenant=tenant,
                        at_s=now_s,
                    ),
                    now_s,
                )
                if record is not None:
                    applied_record = record
                    print(
                        f"frontdoor: remediation {record.kind} -> "
                        f"{record.target} phase={record.phase}",
                        file=sys.stderr,
                    )
            if cycle and cycle % 25 == 0:
                # Verification windows are minutes-long in production;
                # one tick per ~25 sub-second serve cycles keeps the
                # 6-window budget from burning in seconds of wallclock.
                remediation.tick(
                    now_s, lambda rec: burn.max_active_burn()
                )
            order_flipped = admitted == [healthy, tenant]
            if order_flipped:
                flips += 1
            _status(
                {
                    "ts": now_s,
                    "cycle": cycle,
                    "burn_state": burn_state,
                    "priority": burn.admission_priority(tenant),
                    "admitted": admitted,
                    "remediation_applied": demoted
                    or record is not None,
                    "order_flipped": order_flipped,
                    "restored": restore_outcome,
                }
            )
            cycle += 1
            progress["next_cycle"] = cycle
            runtime.maybe_snapshot()
            if args.interval_s > 0:
                time.sleep(args.interval_s)
    except (KeyboardInterrupt, DrainSignal):
        pass
    finally:
        restore_handlers()
        runtime.snapshot_now()
        if status_fh is not None:
            status_fh.close()

    summary = {
        "cycles": progress["next_cycle"],
        "burn_state": burn.tenant_burn_state(tenant),
        "priority": burn.admission_priority(tenant),
        "remediation_phase": (
            applied_record.phase if applied_record else ""
        ),
        "order_flips": flips,
    }
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(
            f"frontdoor: {summary['cycles']} cycles, tenant {tenant} "
            f"{summary['burn_state']} priority={summary['priority']}, "
            f"{flips} flipped-admission cycles"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
