"""sloctl: operator CLI — ``prereq check``, ``cdgate check``,
``explain <incident>`` and ``budget``.

Reference: ``cmd/sloctl`` — prereq text/json with ``--strict``; cdgate
thresholds with ``--fail-open`` post-processing
(``cmd/sloctl/cdgate.go:92-95``).  ``explain`` is the self-observability
addition: it prints the recorded provenance chain behind one incident
page (probe events → correlation tier/confidence → fault-domain
posterior → alert delivery outcome) from the agent's provenance log.
``budget`` renders the burn engine's per-tenant error-budget table
(windowed SLI, budget remaining, burn rates, alert state) from the
agent's durable state snapshot — or replays a ``RequestOutcome`` JSONL
(``loadgen --slo-out``) through a fresh engine offline.
``remediation list`` renders the auto-remediation action history from
the same snapshot (``explain`` shows the remediation block inside each
remediated incident's provenance chain).
"""

from __future__ import annotations

import argparse
import json
import sys

from tpuslo import cdgate, prereq
from tpuslo.cli.common import resolve_config


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpuslo sloctl", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    pr = sub.add_parser("prereq", help="host prerequisite checks")
    pr_sub = pr.add_subparsers(dest="subcommand", required=True)
    pr_check = pr_sub.add_parser("check")
    pr_check.add_argument("--format", default="text", choices=["text", "json"])
    pr_check.add_argument(
        "--strict", action="store_true", help="warnings also fail the check"
    )

    cd = sub.add_parser("cdgate", help="CD pipeline SLO gate")
    cd_sub = cd.add_subparsers(dest="subcommand", required=True)
    cd_check = cd_sub.add_parser("check")
    cd_check.add_argument("--config", default="")
    cd_check.add_argument("--prometheus-url", default="")
    cd_check.add_argument("--ttft-p95-ms", type=float, default=0.0)
    cd_check.add_argument("--error-rate", type=float, default=0.0)
    cd_check.add_argument("--burn-rate", type=float, default=0.0)
    cd_check.add_argument(
        "--fail-open",
        action="store_true",
        help="treat query failures as pass (availability over strictness)",
    )
    cd_check.add_argument(
        "--fail-closed",
        action="store_true",
        help="query failures fail the gate, overriding config fail_open",
    )

    ex = sub.add_parser(
        "explain",
        help="print the recorded provenance chain behind one incident",
    )
    ex.add_argument(
        "incident_id",
        nargs="?",
        default="",
        help="incident id (e.g. agent-inc-0005); omit to list known ids",
    )
    ex.add_argument("--config", default="")
    ex.add_argument(
        "--provenance",
        default="",
        help="provenance JSONL written by `agent --trace` (default: "
        "config observability.provenance_path, then "
        "<runtime.state_dir>/provenance.jsonl)",
    )
    ex.add_argument(
        "--json",
        action="store_true",
        help="emit the raw provenance record instead of the chain text",
    )

    fl = sub.add_parser(
        "fleet",
        help="fleet observability plane: rolled-up incidents and "
        "per-node reporting status from fleetagg outputs",
    )
    fl_sub = fl.add_subparsers(dest="subcommand", required=True)
    fl_inc = fl_sub.add_parser(
        "incidents",
        help="fleet incident table (one page per fault domain x "
        "blast radius, with member-node counts)",
    )
    fl_inc.add_argument(
        "--incidents",
        default="",
        help="fleet-incident JSONL written by "
        "`fleetagg --incidents-out` (required)",
    )
    fl_inc.add_argument(
        "--radius",
        default="",
        choices=["", "pod", "node", "slice", "fleet", "global"],
        help="filter to one blast radius",
    )
    fl_inc.add_argument(
        "--global",
        dest="global_scope",
        action="store_true",
        help="read GLOBAL-incident JSONL (`fleetagg --global-tier` "
        "output) instead of fleet incidents: one page per fault "
        "domain across regions, with a REGIONS column and the "
        "partition scope",
    )
    fl_inc.add_argument("--tenant", default="", help="filter to one tenant")
    fl_inc.add_argument(
        "--region",
        default="",
        help="filter to incidents emitted by one region aggregator "
        "(federation plane; `fleetagg --region` output)",
    )
    fl_inc.add_argument(
        "--cluster",
        default="",
        help="filter to incidents with at least one member node "
        "reporting through this cluster",
    )
    fl_inc.add_argument("--json", action="store_true")
    fl_nodes = fl_sub.add_parser(
        "nodes",
        help="per-node reporting/stale status across aggregator "
        "shards",
    )
    fl_nodes.add_argument(
        "--state",
        default="",
        help="aggregator state snapshot written by "
        "`fleetagg --state-out` (required)",
    )
    fl_nodes.add_argument(
        "--stale-only",
        action="store_true",
        help="show only nodes aged out of the watermark",
    )
    fl_nodes.add_argument(
        "--cluster",
        default="",
        help="filter to one cluster's nodes (federation plane; the "
        "cluster identity a `fleetagg --cluster-id` state snapshot "
        "carries)",
    )
    fl_nodes.add_argument("--json", action="store_true")

    rem = sub.add_parser(
        "remediation",
        help="auto-remediation action history from the agent's durable "
        "state snapshot",
    )
    rem_sub = rem.add_subparsers(dest="subcommand", required=True)
    rem_list = rem_sub.add_parser(
        "list",
        help="action history table (id, kind, target, phase, verify "
        "verdict, escalations); `sloctl explain <incident>` shows the "
        "full chain behind each action",
    )
    rem_list.add_argument("--config", default="")
    rem_list.add_argument(
        "--state",
        default="",
        help="agent state snapshot path (default "
        "<runtime.state_dir>/agent-state.json)",
    )
    rem_list.add_argument(
        "--in-flight-only",
        action="store_true",
        help="show only actions still applying or verifying",
    )
    rem_list.add_argument("--json", action="store_true")

    prof = sub.add_parser(
        "profiler",
        help="continuous-profiler capture windows from the agent's "
        "durable state snapshot (idle gap, MFU, unexplained share, "
        "join rates, governor state)",
    )
    prof.add_argument("--config", default="")
    prof.add_argument(
        "--state",
        default="",
        help="agent state snapshot path (default "
        "<runtime.state_dir>/agent-state.json)",
    )
    prof.add_argument(
        "--last",
        type=int,
        default=0,
        help="show only the most recent N windows (0 = all retained)",
    )
    prof.add_argument("--json", action="store_true")

    bu = sub.add_parser(
        "budget",
        help="per-tenant error-budget / burn-rate table from the "
        "agent's state snapshot (or an offline outcome replay)",
    )
    bu.add_argument("--config", default="")
    bu.add_argument(
        "--state",
        default="",
        help="agent state snapshot path (default "
        "<runtime.state_dir>/agent-state.json)",
    )
    bu.add_argument(
        "--replay",
        default="",
        help="RequestOutcome JSONL (loadgen --slo-out) to replay "
        "through a fresh engine instead of reading agent state",
    )
    bu.add_argument("--tenant", default="", help="filter to one tenant")
    bu.add_argument(
        "--json",
        action="store_true",
        help="emit the budget table as JSON",
    )
    bu.add_argument(
        "--watch",
        action="store_true",
        help="re-read the snapshot and re-render every --interval-s",
    )
    bu.add_argument("--interval-s", type=float, default=2.0)
    return p


def run_prereq(args) -> int:
    snapshot = prereq.collect_snapshot()
    results = prereq.evaluate(snapshot)
    if args.format == "json":
        print(json.dumps([r.to_dict() for r in results], indent=2))
    else:
        for r in results:
            marker = "PASS" if r.passed else ("WARN" if r.severity != "blocker" else "FAIL")
            print(f"[{marker:4s}] {r.name:18s} ({r.severity}): {r.detail}")
    blockers = [r for r in results if not r.passed and r.severity == prereq.SEVERITY_BLOCKER]
    warnings = [r for r in results if not r.passed and r.severity == prereq.SEVERITY_WARNING]
    if blockers:
        return 1
    if warnings and args.strict:
        return 1
    return 0


def run_cdgate(args) -> int:
    cfg = resolve_config(args.config)
    url = args.prometheus_url or cfg.cdgate.prometheus_url
    report = cdgate.evaluate_slo_gate(
        cdgate.HTTPQuerier(url),
        ttft_p95_ms=args.ttft_p95_ms or cfg.cdgate.ttft_p95_ms,
        error_rate=args.error_rate or cfg.cdgate.error_rate,
        burn_rate=args.burn_rate or cfg.cdgate.burn_rate,
    )
    fail_open = (args.fail_open or cfg.cdgate.fail_open) and not args.fail_closed
    # Fail-open: gate failures caused *only* by query errors pass.
    effective_pass = report.passed
    if not report.passed and fail_open:
        hard_failures = [c for c in report.checks if not c.passed and not c.error]
        if not hard_failures:
            effective_pass = True
            print("cdgate: query failures ignored (fail-open)", file=sys.stderr)
    print(json.dumps(report.to_dict() | {"effective_pass": effective_pass}, indent=2))
    return 0 if effective_pass else 1


def run_explain(args) -> int:
    import os

    from tpuslo.obs import format_chain, load_records

    path = args.provenance
    if not path:
        cfg = resolve_config(args.config)
        path = cfg.observability.provenance_path
        if not path and cfg.runtime.state_dir:
            path = os.path.join(cfg.runtime.state_dir, "provenance.jsonl")
    if not path:
        print(
            "sloctl explain: no provenance log — pass --provenance or "
            "set observability.provenance_path (the agent writes it "
            "when self-tracing is enabled)",
            file=sys.stderr,
        )
        return 1
    records = load_records(path)
    if not records:
        print(
            f"sloctl explain: no provenance records in {path}",
            file=sys.stderr,
        )
        return 1
    if not args.incident_id:
        for incident_id in sorted(records):
            rec = records[incident_id]
            print(
                f"{incident_id}  {rec.predicted_fault_domain}"
                f"  confidence={rec.confidence:.3f}"
                f"  delivery={rec.delivery.get('outcome', '?')}"
            )
        return 0
    rec = records.get(args.incident_id)
    if rec is None:
        known = ", ".join(sorted(records)[:10])
        print(
            f"sloctl explain: incident {args.incident_id!r} not in "
            f"{path} (known: {known})",
            file=sys.stderr,
        )
        return 1
    if args.json:
        print(json.dumps(rec.to_dict(), indent=2))
    else:
        print(format_chain(rec))
    return 0


def _render_table(rows: list[tuple[str, ...]]) -> str:
    """Fixed-width table; first row is the header."""
    widths = [
        max(len(row[col]) for row in rows) for col in range(len(rows[0]))
    ]
    return "\n".join(
        "  ".join(cell.ljust(width) for cell, width in zip(row, widths))
        for row in rows
    )


def _run_global_incidents(args) -> int:
    """``sloctl fleet incidents --global``: the global-page table.

    Rows are :class:`~tpuslo.federation.GlobalIncident` JSONL (the
    ``fleetagg --global-tier`` output).  REGIONS is the page's member
    span; SCOPE distinguishes a clean multi-region page from a
    ``partition`` one (some region was unreachable at emission — the
    peer side may hold the rest, and ``!<regions>`` names who was
    dark).  Drill-down stays two-level: each member entry is one
    region's fleet page, explained on that region's own logs.

    Mesh output (``fleetagg --peer``) stamps each page with the
    election epoch and emitting peer; an EMITTED column renders both
    so a failover's handover point is visible straight from the log.
    """
    from tpuslo.federation.global_tier import GlobalIncident

    pages: list[GlobalIncident] = []
    stamps: dict[str, tuple[int, str]] = {}
    try:
        with open(args.incidents, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if line:
                    raw = json.loads(line)
                    page = GlobalIncident.from_dict(raw)
                    pages.append(page)
                    if "epoch" in raw or "peer" in raw:
                        stamps[page.incident_id] = (
                            int(raw.get("epoch", 0)),
                            str(raw.get("peer", "")),
                        )
    except (OSError, json.JSONDecodeError) as exc:
        print(
            f"sloctl fleet incidents: cannot read "
            f"{args.incidents}: {exc}",
            file=sys.stderr,
        )
        return 1
    pages = [
        g
        for g in pages
        if (not args.radius or g.blast_radius == args.radius)
        and (not args.tenant or g.namespace == args.tenant)
        and (not args.region or args.region in g.regions)
        and (
            not args.cluster
            or any(
                args.cluster in (m.get("clusters") or [])
                for m in g.members
            )
        )
    ]
    if args.json:
        print(json.dumps([g.to_dict() for g in pages], indent=2))
        return 0
    if not pages:
        print("(no global incidents)")
        return 0
    header = [
        "INCIDENT", "DOMAIN", "RADIUS", "TENANT", "REGIONS",
        "SCOPE", "MEMBERS", "CONFIDENCE",
    ]
    if stamps:
        header.append("EMITTED")
    rows = [tuple(header)]
    for g in sorted(pages, key=lambda x: x.window_start_ns):
        scope = g.scope
        if g.partition_scoped and g.unreachable_regions:
            scope += " !" + ",".join(g.unreachable_regions)
        row = [
            g.incident_id,
            g.domain,
            g.blast_radius,
            g.namespace,
            ",".join(g.regions) or "-",
            scope,
            str(len(g.members)),
            f"{g.confidence:.3f}",
        ]
        if stamps:
            epoch, peer = stamps.get(g.incident_id, (0, ""))
            row.append(f"e{epoch}@{peer or '-'}")
        rows.append(tuple(row))
    print(_render_table(rows))
    print(
        f"{len(pages)} global incidents — each MEMBER is one "
        "region's fleet page; drill down with `sloctl fleet "
        "incidents --incidents <that region's log>`"
    )
    return 0


def run_fleet(args) -> int:
    from tpuslo.fleet.rollup import FleetIncident

    if args.subcommand == "incidents":
        if not args.incidents:
            print(
                "sloctl fleet incidents: pass --incidents "
                "(fleetagg --incidents-out JSONL)",
                file=sys.stderr,
            )
            return 1
        if args.global_scope:
            return _run_global_incidents(args)
        incidents: list[FleetIncident] = []
        try:
            with open(args.incidents, encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if line:
                        incidents.append(
                            FleetIncident.from_dict(json.loads(line))
                        )
        except (OSError, json.JSONDecodeError) as exc:
            print(
                f"sloctl fleet incidents: cannot read "
                f"{args.incidents}: {exc}",
                file=sys.stderr,
            )
            return 1
        incidents = [
            i
            for i in incidents
            if (not args.radius or i.blast_radius == args.radius)
            and (not args.tenant or i.namespace == args.tenant)
            and (not args.region or i.region == args.region)
            and (not args.cluster or args.cluster in i.clusters)
        ]
        if args.json:
            print(
                json.dumps([i.to_dict() for i in incidents], indent=2)
            )
            return 0
        if not incidents:
            print("(no fleet incidents)")
            return 0
        rows = [
            (
                "INCIDENT", "DOMAIN", "RADIUS", "TENANT", "REGION",
                "CLUSTERS", "NODES", "SLICES", "MEMBERS", "CONFIDENCE",
            )
        ]
        for i in sorted(incidents, key=lambda x: x.window_start_ns):
            rows.append(
                (
                    i.incident_id,
                    i.domain,
                    i.blast_radius,
                    i.namespace,
                    i.region or "-",
                    ",".join(i.clusters) or "-",
                    str(len(i.nodes)),
                    str(len(i.slices)),
                    str(len(i.members)),
                    f"{i.confidence:.3f}",
                )
            )
        print(_render_table(rows))
        print(
            f"{len(incidents)} fleet incidents — drill down with "
            "`sloctl explain <incident>` on the fleetagg provenance "
            "log"
        )
        return 0

    # fleet nodes
    if not args.state:
        print(
            "sloctl fleet nodes: pass --state "
            "(fleetagg --state-out snapshot)",
            file=sys.stderr,
        )
        return 1
    try:
        with open(args.state, encoding="utf-8") as fh:
            state = json.load(fh)
    except (OSError, json.JSONDecodeError) as exc:
        print(
            f"sloctl fleet nodes: cannot read {args.state}: {exc}",
            file=sys.stderr,
        )
        return 1
    shards = state.get("shards") or {}
    snapshots = state.get("snapshots") or {}
    state_cluster = str(state.get("cluster", ""))
    node_rows = []
    for shard_id in sorted(shards):
        section = shards[shard_id] or {}
        snap = snapshots.get(shard_id) or {}
        watermark = int(snap.get("watermark_ns", 0))
        nodes = section.get("nodes") or {}
        heads = [
            int(f.get("head_ns", 0)) for f in nodes.values()
        ]
        shard_head = max(heads) if heads else 0
        for node in sorted(nodes):
            fragment = nodes[node] or {}
            head = int(fragment.get("head_ns", 0))
            lag_ms = (shard_head - head) / 1e6
            # Prefer the shard's own verdict (exported alongside the
            # fragment); fall back to the watermark heuristic for
            # state files written before the flag existed.
            stale = bool(
                fragment.get(
                    "stale", bool(watermark and head < watermark)
                )
            )
            node_rows.append(
                {
                    "node": node,
                    "cluster": state_cluster,
                    "shard": shard_id,
                    "slice_id": str(fragment.get("slice_id", "")),
                    "seq": int(fragment.get("seq", -1)),
                    "events": int(fragment.get("events", 0)),
                    "head_lag_ms": round(lag_ms, 1),
                    "stale": stale,
                }
            )
    if args.stale_only:
        node_rows = [r for r in node_rows if r["stale"]]
    if args.cluster:
        node_rows = [
            r for r in node_rows if r["cluster"] == args.cluster
        ]
    if args.json:
        print(json.dumps(node_rows, indent=2))
        return 0
    if not node_rows:
        print("(no nodes)" if not args.stale_only else "(no stale nodes)")
        return 0
    rows = [
        (
            "NODE", "CLUSTER", "SHARD", "SLICE", "SEQ", "EVENTS",
            "LAG(ms)", "STALE",
        )
    ]
    for r in node_rows:
        rows.append(
            (
                r["node"],
                r["cluster"] or "-",
                r["shard"],
                r["slice_id"],
                str(r["seq"]),
                str(r["events"]),
                f"{r['head_lag_ms']:g}",
                "yes" if r["stale"] else "-",
            )
        )
    print(_render_table(rows))
    return 0


def run_remediation(args) -> int:
    import os

    from tpuslo.remediation import TERMINAL_PHASES

    cfg = resolve_config(args.config)
    path = args.state
    if not path and cfg.runtime.state_dir:
        path = os.path.join(cfg.runtime.state_dir, "agent-state.json")
    if not path:
        print(
            "sloctl remediation list: no state path — pass --state or "
            "set runtime.state_dir",
            file=sys.stderr,
        )
        return 1
    try:
        with open(path, encoding="utf-8") as fh:
            snapshot = json.load(fh)
    except OSError as exc:
        print(
            f"sloctl remediation list: cannot read {path}: "
            f"{exc.strerror or exc}",
            file=sys.stderr,
        )
        return 1
    except json.JSONDecodeError:
        print(
            f"sloctl remediation list: corrupt snapshot {path}",
            file=sys.stderr,
        )
        return 1
    section = (snapshot.get("components") or {}).get("remediation")
    if not isinstance(section, dict):
        print(
            f"sloctl remediation list: snapshot {path} has no "
            "remediation section — is the engine enabled (config "
            "remediation: / agent --remediate)?",
            file=sys.stderr,
        )
        return 1
    records = [
        r for r in (section.get("records") or []) if isinstance(r, dict)
    ]
    if args.in_flight_only:
        records = [
            r for r in records if r.get("phase") not in TERMINAL_PHASES
        ]
    if args.json:
        print(json.dumps(records, indent=2))
        return 0
    if not records:
        print(
            "(no remediation actions)"
            if not args.in_flight_only
            else "(no in-flight remediation actions)"
        )
        return 0
    rows = [
        (
            "ACTION", "INCIDENT", "KIND", "TARGET", "PHASE",
            "VERDICT", "WINDOWS", "ESCALATED",
        )
    ]
    for r in sorted(
        records, key=lambda r: float(r.get("applied_at_s", 0.0))
    ):
        rows.append(
            (
                str(r.get("action_id", "?")),
                str(r.get("incident_id", "?")),
                str(r.get("kind", "?")),
                str(r.get("target", "?")),
                str(r.get("phase", "?")),
                str(r.get("verdict", "?")),
                str(r.get("windows_seen", 0)),
                "yes" if r.get("escalated") else "-",
            )
        )
    print(_render_table(rows))
    print(
        f"{len(records)} remediation action(s) — drill down with "
        "`sloctl explain <incident>`"
    )
    return 0


def run_profiler(args) -> int:
    import os

    cfg = resolve_config(args.config)
    path = args.state
    if not path and cfg.runtime.state_dir:
        path = os.path.join(cfg.runtime.state_dir, "agent-state.json")
    if not path:
        print(
            "sloctl profiler: no state path — pass --state or set "
            "runtime.state_dir",
            file=sys.stderr,
        )
        return 1
    try:
        with open(path, encoding="utf-8") as fh:
            snapshot = json.load(fh)
    except OSError as exc:
        print(
            f"sloctl profiler: cannot read {path}: "
            f"{exc.strerror or exc}",
            file=sys.stderr,
        )
        return 1
    except json.JSONDecodeError:
        print(f"sloctl profiler: corrupt snapshot {path}", file=sys.stderr)
        return 1
    section = (snapshot.get("components") or {}).get("profiler")
    if not isinstance(section, dict):
        print(
            f"sloctl profiler: snapshot {path} has no profiler "
            "section — is the profiler enabled (config profiler: / "
            "agent --profile-device)?",
            file=sys.stderr,
        )
        return 1
    windows = [
        w for w in (section.get("windows") or []) if isinstance(w, dict)
    ]
    if args.last > 0:
        windows = windows[-args.last :]
    if args.json:
        print(json.dumps(section | {"windows": windows}, indent=2))
        return 0
    print(
        "profiler: source={source} windows={captured} "
        "(forced={forced}, evictions={ev}) "
        "degradations={deg} reengagements={re} stride={stride} "
        "overhead EMA {ema:.4f}% of {budget:g}% budget{state}".format(
            source=section.get("source", "?"),
            captured=section.get("windows_captured", 0),
            forced=section.get("windows_forced", 0),
            ev=section.get("eviction_windows", 0),
            deg=section.get("degradations", 0),
            re=section.get("reengagements", 0),
            stride=section.get("stride_cycles", "?"),
            ema=float(section.get("overhead_ema_pct", 0.0)),
            budget=float(section.get("overhead_budget_pct", 0.0)),
            state=" [DEGRADED]" if section.get("degraded") else "",
        )
    )
    if not windows:
        print("(no capture windows retained)")
        return 0
    rows = [
        (
            "WINDOW", "CYCLE", "IDLE-GAP-MS", "EVICT", "UNEXPL",
            "MFU%", "RAW", "SUBST", "VERDICT", "STRIDE", "FLAGS",
        )
    ]
    for w in windows:
        mfu = float(w.get("mfu_pct", -1.0))
        flags = "".join(
            (
                "D" if w.get("degraded") else "",
                "F" if w.get("forced") else "",
            )
        )
        rows.append(
            (
                str(w.get("index", "?")),
                str(w.get("cycle", "?")),
                f"{float(w.get('idle_gap_ms', 0.0)):.3f}",
                str(w.get("eviction_events", 0)),
                f"{float(w.get('unexplained_share', 0.0)):.3f}",
                f"{mfu:.2f}" if mfu >= 0 else "-",
                f"{float(w.get('raw_join_rate', 0.0)):.3f}",
                f"{float(w.get('substantive_join_rate', 0.0)):.3f}",
                str(w.get("verdict") or "-"),
                str(w.get("stride_cycles", "?")),
                flags or "-",
            )
        )
    print(_render_table(rows))
    print(
        f"{len(windows)} window(s) retained — eviction windows page; "
        "drill down with `sloctl explain <incident>`"
    )
    return 0


def _render_budget_table(statuses, tenant_filter: str = "") -> str:
    """Fixed-width per-(tenant, objective) budget table."""
    rows = [
        (
            "TENANT", "OBJECTIVE", "TARGET", "SLI(1h)", "BUDGET",
            "5m", "30m", "1h", "6h", "STATE",
        )
    ]
    for stat in statuses:
        if tenant_filter and stat.tenant != tenant_filter:
            continue
        burns = stat.burn_rates
        rows.append(
            (
                stat.tenant,
                stat.objective,
                f"{stat.target:.3%}",
                f"{stat.sli.get('1h', 1.0):.3%}",
                f"{stat.budget_remaining:.1%}",
                f"{burns.get('5m', 0.0):.1f}x",
                f"{burns.get('30m', 0.0):.1f}x",
                f"{burns.get('1h', 0.0):.1f}x",
                f"{burns.get('6h', 0.0):.1f}x",
                stat.alert_state,
            )
        )
    if len(rows) == 1:
        return "(no tenants observed)"
    return _render_table(rows)


def _budget_engine_from_state(cfg, state_path: str):
    """(engine, saved_at) from one durable agent snapshot, or None."""
    import os

    from tpuslo.sloengine import BurnEngine, EngineConfig

    path = state_path
    if not path and cfg.runtime.state_dir:
        path = os.path.join(cfg.runtime.state_dir, "agent-state.json")
    if not path:
        return None, "no state path — pass --state or set runtime.state_dir"
    try:
        with open(path, encoding="utf-8") as fh:
            snapshot = json.load(fh)
    except OSError as exc:
        return None, f"cannot read {path}: {exc.strerror or exc}"
    except json.JSONDecodeError:
        return None, f"corrupt snapshot {path}"
    section = (snapshot.get("components") or {}).get("sloengine")
    if not isinstance(section, dict):
        return None, (
            f"snapshot {path} has no sloengine section — is the burn "
            "engine enabled (config slo: / agent --burn-engine)?"
        )
    engine = BurnEngine(EngineConfig.from_toolkit(cfg.slo))
    engine.restore_state(section)
    saved_at = float(snapshot.get("saved_at", 0.0))
    # Roll the rings forward to the snapshot time so the table shows
    # the windows as of the last save — policy-free: a display read
    # must not advance clear streaks or fire transitions the agent's
    # own durable state never saw.
    engine.roll_to(saved_at)
    return engine, ""


def run_budget(args) -> int:
    import time as time_mod

    from tpuslo.sloengine import (
        BurnEngine,
        EngineConfig,
        load_outcomes,
        replay_outcomes,
    )

    cfg = resolve_config(args.config)
    if args.replay:
        engine = BurnEngine(EngineConfig.from_toolkit(cfg.slo))
        try:
            transitions = replay_outcomes(
                engine, load_outcomes(args.replay)
            )
        except OSError as exc:
            print(
                f"sloctl budget: cannot read {args.replay}: "
                f"{exc.strerror or exc}",
                file=sys.stderr,
            )
            return 1
        statuses = [
            s
            for s in engine.status()
            if not args.tenant or s.tenant == args.tenant
        ]
        transitions = [
            t
            for t in transitions
            if not args.tenant or t.tenant == args.tenant
        ]
        if args.json:
            print(
                json.dumps(
                    {
                        "budgets": [s.to_dict() for s in statuses],
                        "transitions": [
                            t.to_dict() for t in transitions
                        ],
                    },
                    indent=2,
                )
            )
        else:
            print(_render_budget_table(statuses, args.tenant))
            for t in transitions:
                print(
                    f"transition: {t.severity} {t.tenant}/{t.objective} "
                    f"{t.from_state}->{t.to_state} at +{t.at_s:.0f}s "
                    f"(burn {t.burn_long:.1f}x/{t.burn_short:.1f}x)"
                )
        return 0

    while True:
        engine, err = _budget_engine_from_state(cfg, args.state)
        if engine is None:
            print(f"sloctl budget: {err}", file=sys.stderr)
            return 1
        statuses = engine.status()
        if args.json:
            print(
                json.dumps(
                    {
                        "budgets": [
                            s.to_dict()
                            for s in statuses
                            if not args.tenant or s.tenant == args.tenant
                        ]
                    },
                    indent=2,
                )
            )
        else:
            print(_render_budget_table(statuses, args.tenant))
        if not args.watch:
            return 0
        try:
            time_mod.sleep(max(0.1, args.interval_s))
        except KeyboardInterrupt:
            return 0
        print()


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "prereq":
        return run_prereq(args)
    if args.command == "explain":
        return run_explain(args)
    if args.command == "budget":
        return run_budget(args)
    if args.command == "fleet":
        return run_fleet(args)
    if args.command == "remediation":
        return run_remediation(args)
    if args.command == "profiler":
        return run_profiler(args)
    return run_cdgate(args)


if __name__ == "__main__":
    raise SystemExit(main())
