"""sloctl: operator CLI — ``prereq check``, ``cdgate check`` and
``explain <incident>``.

Reference: ``cmd/sloctl`` — prereq text/json with ``--strict``; cdgate
thresholds with ``--fail-open`` post-processing
(``cmd/sloctl/cdgate.go:92-95``).  ``explain`` is the self-observability
addition: it prints the recorded provenance chain behind one incident
page (probe events → correlation tier/confidence → fault-domain
posterior → alert delivery outcome) from the agent's provenance log.
"""

from __future__ import annotations

import argparse
import json
import sys

from tpuslo import cdgate, prereq
from tpuslo.cli.common import resolve_config


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpuslo sloctl", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    pr = sub.add_parser("prereq", help="host prerequisite checks")
    pr_sub = pr.add_subparsers(dest="subcommand", required=True)
    pr_check = pr_sub.add_parser("check")
    pr_check.add_argument("--format", default="text", choices=["text", "json"])
    pr_check.add_argument(
        "--strict", action="store_true", help="warnings also fail the check"
    )

    cd = sub.add_parser("cdgate", help="CD pipeline SLO gate")
    cd_sub = cd.add_subparsers(dest="subcommand", required=True)
    cd_check = cd_sub.add_parser("check")
    cd_check.add_argument("--config", default="")
    cd_check.add_argument("--prometheus-url", default="")
    cd_check.add_argument("--ttft-p95-ms", type=float, default=0.0)
    cd_check.add_argument("--error-rate", type=float, default=0.0)
    cd_check.add_argument("--burn-rate", type=float, default=0.0)
    cd_check.add_argument(
        "--fail-open",
        action="store_true",
        help="treat query failures as pass (availability over strictness)",
    )
    cd_check.add_argument(
        "--fail-closed",
        action="store_true",
        help="query failures fail the gate, overriding config fail_open",
    )

    ex = sub.add_parser(
        "explain",
        help="print the recorded provenance chain behind one incident",
    )
    ex.add_argument(
        "incident_id",
        nargs="?",
        default="",
        help="incident id (e.g. agent-inc-0005); omit to list known ids",
    )
    ex.add_argument("--config", default="")
    ex.add_argument(
        "--provenance",
        default="",
        help="provenance JSONL written by `agent --trace` (default: "
        "config observability.provenance_path, then "
        "<runtime.state_dir>/provenance.jsonl)",
    )
    ex.add_argument(
        "--json",
        action="store_true",
        help="emit the raw provenance record instead of the chain text",
    )
    return p


def run_prereq(args) -> int:
    snapshot = prereq.collect_snapshot()
    results = prereq.evaluate(snapshot)
    if args.format == "json":
        print(json.dumps([r.to_dict() for r in results], indent=2))
    else:
        for r in results:
            marker = "PASS" if r.passed else ("WARN" if r.severity != "blocker" else "FAIL")
            print(f"[{marker:4s}] {r.name:18s} ({r.severity}): {r.detail}")
    blockers = [r for r in results if not r.passed and r.severity == prereq.SEVERITY_BLOCKER]
    warnings = [r for r in results if not r.passed and r.severity == prereq.SEVERITY_WARNING]
    if blockers:
        return 1
    if warnings and args.strict:
        return 1
    return 0


def run_cdgate(args) -> int:
    cfg = resolve_config(args.config)
    url = args.prometheus_url or cfg.cdgate.prometheus_url
    report = cdgate.evaluate_slo_gate(
        cdgate.HTTPQuerier(url),
        ttft_p95_ms=args.ttft_p95_ms or cfg.cdgate.ttft_p95_ms,
        error_rate=args.error_rate or cfg.cdgate.error_rate,
        burn_rate=args.burn_rate or cfg.cdgate.burn_rate,
    )
    fail_open = (args.fail_open or cfg.cdgate.fail_open) and not args.fail_closed
    # Fail-open: gate failures caused *only* by query errors pass.
    effective_pass = report.passed
    if not report.passed and fail_open:
        hard_failures = [c for c in report.checks if not c.passed and not c.error]
        if not hard_failures:
            effective_pass = True
            print("cdgate: query failures ignored (fail-open)", file=sys.stderr)
    print(json.dumps(report.to_dict() | {"effective_pass": effective_pass}, indent=2))
    return 0 if effective_pass else 1


def run_explain(args) -> int:
    import os

    from tpuslo.obs import format_chain, load_records

    path = args.provenance
    if not path:
        cfg = resolve_config(args.config)
        path = cfg.observability.provenance_path
        if not path and cfg.runtime.state_dir:
            path = os.path.join(cfg.runtime.state_dir, "provenance.jsonl")
    if not path:
        print(
            "sloctl explain: no provenance log — pass --provenance or "
            "set observability.provenance_path (the agent writes it "
            "when self-tracing is enabled)",
            file=sys.stderr,
        )
        return 1
    records = load_records(path)
    if not records:
        print(
            f"sloctl explain: no provenance records in {path}",
            file=sys.stderr,
        )
        return 1
    if not args.incident_id:
        for incident_id in sorted(records):
            rec = records[incident_id]
            print(
                f"{incident_id}  {rec.predicted_fault_domain}"
                f"  confidence={rec.confidence:.3f}"
                f"  delivery={rec.delivery.get('outcome', '?')}"
            )
        return 0
    rec = records.get(args.incident_id)
    if rec is None:
        known = ", ".join(sorted(records)[:10])
        print(
            f"sloctl explain: incident {args.incident_id!r} not in "
            f"{path} (known: {known})",
            file=sys.stderr,
        )
        return 1
    if args.json:
        print(json.dumps(rec.to_dict(), indent=2))
    else:
        print(format_chain(rec))
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "prereq":
        return run_prereq(args)
    if args.command == "explain":
        return run_explain(args)
    return run_cdgate(args)


if __name__ == "__main__":
    raise SystemExit(main())
