"""Shared CLI plumbing: event sinks and config resolution."""

from __future__ import annotations

import json
import sys
import threading
import time
from typing import IO, Any, Callable

from tpuslo.config import ToolkitConfig, default_config, load_config
from tpuslo.delivery import DeliveryChannel, DeliveryObserver, DeliveryOptions
from tpuslo.otel.exporters import ProbeEventExporter, SLOEventExporter
from tpuslo.schema import (
    SCHEMA_SLO_EVENT,
    ProbeEventV1,
    SLOEvent,
    SchemaValidationError,
    validate,
    validate_probe_event,
)

OUTPUT_STDOUT = "stdout"
OUTPUT_JSONL = "jsonl"
OUTPUT_OTLP = "otlp"


class EventWriters:
    """Multiplexed event sink: stdout JSON, JSONL file, or OTLP/HTTP.

    Reference: ``cmd/agent/main.go:68-135`` (outputWriters).
    Thread-safe for the agent's concurrent emit paths.

    With ``delivery`` enabled (a spool dir is configured), the OTLP
    network sinks route through per-sink :class:`DeliveryChannel`\\ s:
    ``emit_*`` becomes non-blocking and loss-free (queue → retry →
    breaker → disk spool → replay) instead of raising on sink failure.
    Local sinks (stdout/JSONL) stay synchronous — they fail only with
    the node itself.
    """

    def __init__(
        self,
        output: str = OUTPUT_STDOUT,
        jsonl_path: str = "",
        otlp_endpoint: str = "",
        stream: IO[str] | None = None,
        delivery: DeliveryOptions | None = None,
        observer_factory: Callable[[str], DeliveryObserver] | None = None,
    ):
        self.output = output
        self._lock = threading.Lock()
        self._stream = stream or sys.stdout
        self._jsonl: IO[str] | None = None
        self._slo_exporter: SLOEventExporter | None = None
        self._probe_exporter: ProbeEventExporter | None = None
        self._slo_channel: DeliveryChannel | None = None
        self._probe_channel: DeliveryChannel | None = None
        self._closed = False
        self.jsonl_repaired_bytes = 0
        if output == OUTPUT_JSONL:
            if not jsonl_path:
                raise ValueError("jsonl output requires --jsonl-path")
            # A previous incarnation killed mid-write leaves a torn
            # final line; appending to it would weld two records into
            # one corrupt mid-file line.  Truncate the tear first.
            from tpuslo.runtime import repair_jsonl_tail

            self.jsonl_repaired_bytes = repair_jsonl_tail(jsonl_path)
            self._jsonl = open(jsonl_path, "a", encoding="utf-8")
        elif output == OUTPUT_OTLP:
            if not otlp_endpoint:
                raise ValueError("otlp output requires an endpoint")
            self._slo_exporter = SLOEventExporter(otlp_endpoint)
            self._probe_exporter = ProbeEventExporter(otlp_endpoint)
            if delivery is not None and delivery.enabled:
                from tpuslo.delivery.sinks import OTLPRecordSink

                observer_factory = observer_factory or (
                    lambda name: DeliveryObserver()
                )
                self._slo_channel = delivery.build_channel(
                    "otlp-slo",
                    OTLPRecordSink(self._slo_exporter),
                    observer=observer_factory("otlp-slo"),
                )
                self._probe_channel = delivery.build_channel(
                    "otlp-probe",
                    OTLPRecordSink(self._probe_exporter),
                    observer=observer_factory("otlp-probe"),
                )
        elif output != OUTPUT_STDOUT:
            raise ValueError(f"unsupported output {output!r}")

    def _write_line(self, payload: dict[str, Any]) -> None:
        self._write_batch([payload])

    def _write_batch(self, payloads: list[dict[str, Any]]) -> None:
        """Serialize once per payload, then one buffered write + flush.

        Per-event write/flush under the lock was the export-side
        bottleneck on the probe spine; a flush per *batch* keeps the
        durability contract at the emit-cycle granularity the agent
        actually operates at.
        """
        if not payloads:
            return
        dumps = json.dumps
        block = "".join(
            dumps(payload, separators=(",", ":")) + "\n" for payload in payloads
        )
        with self._lock:
            sink = self._jsonl if self._jsonl is not None else self._stream
            sink.write(block)
            sink.flush()

    def emit_slo(self, events: list[SLOEvent]) -> None:
        if self._slo_channel is not None:
            if events:
                self._slo_channel.submit(
                    "slo", self._slo_exporter.to_records(events)
                )
            return
        if self._slo_exporter is not None:
            self._slo_exporter.export_batch(events)
            return
        self._write_batch([{"kind": "slo", **event.to_dict()} for event in events])

    def emit_probe(self, events: list[ProbeEventV1]) -> None:
        if self._probe_channel is not None:
            if events:
                self._probe_channel.submit(
                    "probe", self._probe_exporter.to_records(events)
                )
            return
        if self._probe_exporter is not None:
            self._probe_exporter.export_batch(events)
            return
        self._write_batch(
            [{"kind": "probe", **event.to_dict()} for event in events]
        )

    def write_probe_block(self, block: str) -> bool:
        """Emit a pre-serialized JSONL block (columnar fast path).

        The columnar spine serializes whole batches without building
        per-event dicts (``tpuslo.columnar.serialize``); local sinks
        take the block as-is with the usual one-write-one-flush
        contract.  Returns False when the active sink is OTLP — those
        exporters need typed records, so the caller must fall back to
        the ``to_rows`` adapter + :meth:`emit_probe`.
        """
        if self._probe_channel is not None or self._probe_exporter is not None:
            return False
        if not block:
            return True
        with self._lock:
            sink = self._jsonl if self._jsonl is not None else self._stream
            sink.write(block)
            sink.flush()
        return True

    @property
    def delivery_channels(self) -> list[DeliveryChannel]:
        return [c for c in (self._slo_channel, self._probe_channel) if c]

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Drain delivery queues and flush the active local stream."""
        ok = True
        for channel in self.delivery_channels:
            ok = channel.flush(timeout_s) and ok
        with self._lock:
            sink = self._jsonl if self._jsonl is not None else self._stream
            if not sink.closed:
                sink.flush()
        return ok

    def close(self, flush_timeout_s: float = 10.0) -> None:
        """Flush then release every sink; safe to call more than once.

        ``flush_timeout_s`` bounds the final flush of ALL delivery
        channels together (one deadline, not one per channel — the
        drain path shares it with the rest of shutdown); batches still
        queued when it expires are spilled to the spool, never
        dropped.
        """
        if self._closed:
            return
        self._closed = True
        deadline = time.monotonic() + flush_timeout_s
        for channel in self.delivery_channels:
            channel.close(
                flush_timeout_s=max(0.0, deadline - time.monotonic())
            )
        for exporter in (self._slo_exporter, self._probe_exporter):
            if exporter is not None:
                exporter.close()
        if self._jsonl is not None:
            self._jsonl.flush()
            self._jsonl.close()
        elif self._stream is not sys.stdout and not self._stream.closed:
            self._stream.flush()


def resolve_config(path: str) -> ToolkitConfig:
    """Config-file layer of the CLI > config > defaults precedence."""
    if path:
        return load_config(path)
    return default_config()


def validate_slo(event: SLOEvent) -> bool:
    try:
        validate(event.to_dict(), SCHEMA_SLO_EVENT)
        return True
    except SchemaValidationError:
        return False


def validate_probe(event: ProbeEventV1) -> bool:
    # Structural fast path on the known ProbeEventV1 shape; precompiled
    # jsonschema fallback keeps the answer exactly contract-equal (see
    # tpuslo/schema/fastpath.py and tests/test_validator_fastpath.py).
    return validate_probe_event(event)
