"""Shared CLI plumbing: event sinks and config resolution."""

from __future__ import annotations

import json
import sys
import threading
from typing import IO, Any

from tpuslo.config import ToolkitConfig, default_config, load_config
from tpuslo.otel.exporters import ProbeEventExporter, SLOEventExporter
from tpuslo.schema import (
    SCHEMA_SLO_EVENT,
    ProbeEventV1,
    SLOEvent,
    SchemaValidationError,
    validate,
    validate_probe_event,
)

OUTPUT_STDOUT = "stdout"
OUTPUT_JSONL = "jsonl"
OUTPUT_OTLP = "otlp"


class EventWriters:
    """Multiplexed event sink: stdout JSON, JSONL file, or OTLP/HTTP.

    Reference: ``cmd/agent/main.go:68-135`` (outputWriters).
    Thread-safe for the agent's concurrent emit paths.
    """

    def __init__(
        self,
        output: str = OUTPUT_STDOUT,
        jsonl_path: str = "",
        otlp_endpoint: str = "",
        stream: IO[str] | None = None,
    ):
        self.output = output
        self._lock = threading.Lock()
        self._stream = stream or sys.stdout
        self._jsonl: IO[str] | None = None
        self._slo_exporter: SLOEventExporter | None = None
        self._probe_exporter: ProbeEventExporter | None = None
        if output == OUTPUT_JSONL:
            if not jsonl_path:
                raise ValueError("jsonl output requires --jsonl-path")
            self._jsonl = open(jsonl_path, "a", encoding="utf-8")
        elif output == OUTPUT_OTLP:
            if not otlp_endpoint:
                raise ValueError("otlp output requires an endpoint")
            self._slo_exporter = SLOEventExporter(otlp_endpoint)
            self._probe_exporter = ProbeEventExporter(otlp_endpoint)
        elif output != OUTPUT_STDOUT:
            raise ValueError(f"unsupported output {output!r}")

    def _write_line(self, payload: dict[str, Any]) -> None:
        self._write_batch([payload])

    def _write_batch(self, payloads: list[dict[str, Any]]) -> None:
        """Serialize once per payload, then one buffered write + flush.

        Per-event write/flush under the lock was the export-side
        bottleneck on the probe spine; a flush per *batch* keeps the
        durability contract at the emit-cycle granularity the agent
        actually operates at.
        """
        if not payloads:
            return
        dumps = json.dumps
        block = "".join(
            dumps(payload, separators=(",", ":")) + "\n" for payload in payloads
        )
        with self._lock:
            sink = self._jsonl if self._jsonl is not None else self._stream
            sink.write(block)
            sink.flush()

    def emit_slo(self, events: list[SLOEvent]) -> None:
        if self._slo_exporter is not None:
            self._slo_exporter.export_batch(events)
            return
        self._write_batch([{"kind": "slo", **event.to_dict()} for event in events])

    def emit_probe(self, events: list[ProbeEventV1]) -> None:
        if self._probe_exporter is not None:
            self._probe_exporter.export_batch(events)
            return
        self._write_batch(
            [{"kind": "probe", **event.to_dict()} for event in events]
        )

    def close(self) -> None:
        if self._jsonl is not None:
            self._jsonl.close()


def resolve_config(path: str) -> ToolkitConfig:
    """Config-file layer of the CLI > config > defaults precedence."""
    if path:
        return load_config(path)
    return default_config()


def validate_slo(event: SLOEvent) -> bool:
    try:
        validate(event.to_dict(), SCHEMA_SLO_EVENT)
        return True
    except SchemaValidationError:
        return False


def validate_probe(event: ProbeEventV1) -> bool:
    # Structural fast path on the known ProbeEventV1 shape; precompiled
    # jsonschema fallback keeps the answer exactly contract-equal (see
    # tpuslo/schema/fastpath.py and tests/test_validator_fastpath.py).
    return validate_probe_event(event)
