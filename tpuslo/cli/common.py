"""Shared CLI plumbing: event sinks and config resolution."""

from __future__ import annotations

import json
import sys
import threading
from typing import IO, Any

from tpuslo.config import ToolkitConfig, default_config, load_config
from tpuslo.otel.exporters import ProbeEventExporter, SLOEventExporter
from tpuslo.schema import (
    SCHEMA_PROBE_EVENT,
    SCHEMA_SLO_EVENT,
    ProbeEventV1,
    SLOEvent,
    SchemaValidationError,
    validate,
)

OUTPUT_STDOUT = "stdout"
OUTPUT_JSONL = "jsonl"
OUTPUT_OTLP = "otlp"


class EventWriters:
    """Multiplexed event sink: stdout JSON, JSONL file, or OTLP/HTTP.

    Reference: ``cmd/agent/main.go:68-135`` (outputWriters).
    Thread-safe for the agent's concurrent emit paths.
    """

    def __init__(
        self,
        output: str = OUTPUT_STDOUT,
        jsonl_path: str = "",
        otlp_endpoint: str = "",
        stream: IO[str] | None = None,
    ):
        self.output = output
        self._lock = threading.Lock()
        self._stream = stream or sys.stdout
        self._jsonl: IO[str] | None = None
        self._slo_exporter: SLOEventExporter | None = None
        self._probe_exporter: ProbeEventExporter | None = None
        if output == OUTPUT_JSONL:
            if not jsonl_path:
                raise ValueError("jsonl output requires --jsonl-path")
            self._jsonl = open(jsonl_path, "a", encoding="utf-8")
        elif output == OUTPUT_OTLP:
            if not otlp_endpoint:
                raise ValueError("otlp output requires an endpoint")
            self._slo_exporter = SLOEventExporter(otlp_endpoint)
            self._probe_exporter = ProbeEventExporter(otlp_endpoint)
        elif output != OUTPUT_STDOUT:
            raise ValueError(f"unsupported output {output!r}")

    def _write_line(self, payload: dict[str, Any]) -> None:
        line = json.dumps(payload, separators=(",", ":"))
        with self._lock:
            sink = self._jsonl if self._jsonl is not None else self._stream
            sink.write(line + "\n")
            sink.flush()

    def emit_slo(self, events: list[SLOEvent]) -> None:
        if self._slo_exporter is not None:
            self._slo_exporter.export_batch(events)
            return
        for event in events:
            self._write_line({"kind": "slo", **event.to_dict()})

    def emit_probe(self, events: list[ProbeEventV1]) -> None:
        if self._probe_exporter is not None:
            self._probe_exporter.export_batch(events)
            return
        for event in events:
            self._write_line({"kind": "probe", **event.to_dict()})

    def close(self) -> None:
        if self._jsonl is not None:
            self._jsonl.close()


def resolve_config(path: str) -> ToolkitConfig:
    """Config-file layer of the CLI > config > defaults precedence."""
    if path:
        return load_config(path)
    return default_config()


def validate_slo(event: SLOEvent) -> bool:
    try:
        validate(event.to_dict(), SCHEMA_SLO_EVENT)
        return True
    except SchemaValidationError:
        return False


def validate_probe(event: ProbeEventV1) -> bool:
    try:
        validate(event.to_dict(), SCHEMA_PROBE_EVENT)
        return True
    except SchemaValidationError:
        return False
