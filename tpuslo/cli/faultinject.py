"""Faultinject: scenario → raw-sample JSONL (collector input).

Reference: ``cmd/faultinject/main.go``; TPU chaos scenarios (ici_drop,
hbm_pressure, xla_recompile_storm, host_offload_stall) are first-class
per BASELINE.json config 5.
"""

from __future__ import annotations

import argparse
import json
import sys
from datetime import datetime, timezone

from tpuslo.collector import (
    SampleMeta,
    generate_synthetic_samples,
    supported_synthetic_scenarios,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpuslo faultinject", description=__doc__)
    p.add_argument(
        "--scenario", default="mixed", choices=supported_synthetic_scenarios()
    )
    p.add_argument("--count", type=int, default=40)
    p.add_argument("--output", default="-")
    p.add_argument("--start", default="", help="RFC3339 start timestamp")
    p.add_argument("--cluster", default="tpu-cluster")
    p.add_argument("--namespace", default="llm")
    p.add_argument("--workload", default="rag-service")
    p.add_argument("--service", default="rag-service")
    p.add_argument("--node", default="tpu-vm-0")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    start = (
        datetime.fromisoformat(args.start.replace("Z", "+00:00"))
        if args.start
        else datetime.now(timezone.utc)
    )
    meta = SampleMeta(
        cluster=args.cluster,
        namespace=args.namespace,
        workload=args.workload,
        service=args.service,
        node=args.node,
    )
    samples = generate_synthetic_samples(args.scenario, args.count, start, meta)
    sink = sys.stdout if args.output == "-" else open(args.output, "w", encoding="utf-8")
    try:
        for sample in samples:
            sink.write(json.dumps(sample.to_dict(), separators=(",", ":")) + "\n")
    finally:
        if sink is not sys.stdout:
            sink.close()
    print(f"faultinject: wrote {len(samples)} raw samples", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
