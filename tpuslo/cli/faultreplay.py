"""Faultreplay: deterministic fault-sample JSONL emitter.

Reference: ``cmd/faultreplay/main.go``.
"""

from __future__ import annotations

import argparse
import sys
from datetime import datetime, timezone

from tpuslo import attribution, faultreplay


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpuslo faultreplay", description=__doc__)
    p.add_argument(
        "--scenario", default="mixed", choices=faultreplay.supported_scenarios()
    )
    p.add_argument("--count", type=int, default=55)
    p.add_argument("--output", default="-", help="'-' = stdout")
    p.add_argument("--start", default="", help="RFC3339 start timestamp")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    start = (
        datetime.fromisoformat(args.start.replace("Z", "+00:00"))
        if args.start
        else datetime.now(timezone.utc)
    )
    samples = faultreplay.generate_fault_samples(args.scenario, args.count, start)
    sink = sys.stdout if args.output == "-" else open(args.output, "w", encoding="utf-8")
    try:
        count = attribution.dump_samples_jsonl(samples, sink)
    finally:
        if sink is not sys.stdout:
            sink.close()
    print(f"faultreplay: wrote {count} samples", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
