"""Correlationeval: correlation quality gate on labeled pairs.

Reference: ``cmd/correlationeval/main.go`` — defaults window=2000ms,
threshold=0.7, gates P ≥ 0.90, R ≥ 0.85; exit 1 on gate failure.
"""

from __future__ import annotations

import argparse
import csv
import json
import sys
from pathlib import Path

from tpuslo import correlation

DEFAULT_DATASET = (
    Path(__file__).resolve().parent.parent
    / "correlation/testdata/labeled_pairs.jsonl"
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpuslo correlationeval", description=__doc__)
    p.add_argument("--input", default=str(DEFAULT_DATASET))
    p.add_argument("--window-ms", type=int, default=2000)
    p.add_argument("--threshold", type=float, default=0.7)
    p.add_argument("--min-precision", type=float, default=0.90)
    p.add_argument("--min-recall", type=float, default=0.85)
    p.add_argument("--report", default="", help="write JSON report here")
    p.add_argument("--predictions", default="", help="write predictions CSV here")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    pairs = correlation.load_labeled_pairs(args.input)
    report, predictions = correlation.evaluate_labeled_pairs(
        pairs, args.window_ms, args.threshold
    )
    gate = correlation.evaluate_gate(report, args.min_precision, args.min_recall)

    if args.report:
        Path(args.report).write_text(json.dumps(report.to_dict(), indent=2) + "\n")
    if args.predictions:
        with open(args.predictions, "w", newline="", encoding="utf-8") as f:
            writer = csv.DictWriter(
                f,
                fieldnames=[
                    "case_id", "expected", "predicted", "confidence",
                    "tier", "correct", "signal", "expected_tier",
                ],
            )
            writer.writeheader()
            for pred in predictions:
                writer.writerow(pred.to_dict())

    print(
        f"correlationeval: n={report.sample_size} "
        f"P={report.precision:.4f} R={report.recall:.4f} F1={report.f1:.4f} "
        f"tier_acc={report.tier_accuracy:.4f} -> "
        f"{'PASS' if gate.passed else 'FAIL'}: {gate.message}",
        file=sys.stderr,
    )
    return 0 if gate.passed else 1


if __name__ == "__main__":
    raise SystemExit(main())
