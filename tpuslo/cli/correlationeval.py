"""Correlationeval: correlation quality gate on labeled pairs.

Reference: ``cmd/correlationeval/main.go`` — defaults window=2000ms,
threshold=0.7, gates P ≥ 0.90, R ≥ 0.85; exit 1 on gate failure.

``--chaos-intensity`` perturbs the *signal* side of every pair before
evaluation (seeded skew within the moderate chaos envelope, plus
timestamp loss at the corruption rate), measuring how the matcher's
robustness changes — the missing-timestamp confidence cap and the
global window are what keep precision from collapsing here.
"""

from __future__ import annotations

import argparse
import csv
import json
import random
import sys
from dataclasses import replace
from datetime import timedelta
from pathlib import Path

from tpuslo import correlation

DEFAULT_DATASET = (
    Path(__file__).resolve().parent.parent
    / "correlation/testdata/labeled_pairs.jsonl"
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpuslo correlationeval", description=__doc__)
    p.add_argument("--input", default=str(DEFAULT_DATASET))
    p.add_argument("--window-ms", type=int, default=2000)
    p.add_argument("--threshold", type=float, default=0.7)
    p.add_argument("--min-precision", type=float, default=0.90)
    p.add_argument("--min-recall", type=float, default=0.85)
    p.add_argument("--report", default="", help="write JSON report here")
    p.add_argument("--predictions", default="", help="write predictions CSV here")
    p.add_argument(
        "--chaos-intensity",
        type=float,
        default=0.0,
        help="perturb signal timestamps before evaluating "
        "(1.0 = moderate: skew<=250ms, 1%% timestamp loss)",
    )
    p.add_argument("--chaos-seed", type=int, default=1337)
    return p


def chaos_pairs(
    pairs: list[correlation.LabeledPair], intensity: float, seed: int
) -> list[correlation.LabeledPair]:
    """Seeded timestamp perturbation of the signal side of each pair."""
    from tpuslo.chaos.telemetry import (
        MODERATE_CORRUPT_RATE,
        MODERATE_SKEW_MS,
    )

    rng = random.Random(seed)
    skew_ms = MODERATE_SKEW_MS * intensity
    loss_rate = min(0.5, MODERATE_CORRUPT_RATE * intensity)
    out = []
    for pair in pairs:
        signal = pair.signal
        if signal.timestamp is not None:
            if rng.random() < loss_rate:
                signal = replace(signal, timestamp=None)
            elif skew_ms:
                offset = rng.uniform(-skew_ms, skew_ms)
                signal = replace(
                    signal,
                    timestamp=signal.timestamp
                    + timedelta(milliseconds=offset),
                )
        out.append(replace(pair, signal=signal))
    return out


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    pairs = correlation.load_labeled_pairs(args.input)
    if args.chaos_intensity > 0:
        pairs = chaos_pairs(pairs, args.chaos_intensity, args.chaos_seed)
        print(
            f"correlationeval: chaos intensity {args.chaos_intensity:g} "
            f"(seed {args.chaos_seed}) applied to signal timestamps",
            file=sys.stderr,
        )
    report, predictions = correlation.evaluate_labeled_pairs(
        pairs, args.window_ms, args.threshold
    )
    gate = correlation.evaluate_gate(report, args.min_precision, args.min_recall)

    if args.report:
        Path(args.report).write_text(json.dumps(report.to_dict(), indent=2) + "\n")
    if args.predictions:
        with open(args.predictions, "w", newline="", encoding="utf-8") as f:
            writer = csv.DictWriter(
                f,
                fieldnames=[
                    "case_id", "expected", "predicted", "confidence",
                    "tier", "correct", "signal", "expected_tier",
                ],
            )
            writer.writeheader()
            for pred in predictions:
                writer.writerow(pred.to_dict())

    print(
        f"correlationeval: n={report.sample_size} "
        f"P={report.precision:.4f} R={report.recall:.4f} F1={report.f1:.4f} "
        f"tier_acc={report.tier_accuracy:.4f} -> "
        f"{'PASS' if gate.passed else 'FAIL'}: {gate.message}",
        file=sys.stderr,
    )
    return 0 if gate.passed else 1


if __name__ == "__main__":
    raise SystemExit(main())
