"""Collector: normalize raw samples into schema-validated SLO events.

Reference: ``cmd/collector/main.go`` — input from file/stdin JSONL or
the synthetic generator; stdout/jsonl/OTLP sinks.
"""

from __future__ import annotations

import argparse
import json
import sys
from datetime import datetime, timezone

from tpuslo.cli.common import EventWriters, validate_slo
from tpuslo.collector import (
    RawSample,
    SampleMeta,
    generate_synthetic_samples,
    normalize_sample,
    supported_synthetic_scenarios,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpuslo collector", description=__doc__)
    p.add_argument("--input", default="", help="raw samples JSONL ('-' = stdin)")
    p.add_argument(
        "--scenario",
        default="",
        choices=[""] + supported_synthetic_scenarios(),
        help="generate synthetic samples instead of reading input",
    )
    p.add_argument("--count", type=int, default=10)
    p.add_argument("--output", default="stdout", choices=["stdout", "jsonl", "otlp"])
    p.add_argument("--jsonl-path", default="")
    p.add_argument("--otlp-endpoint", default="")
    p.add_argument("--cluster", default="tpu-cluster")
    p.add_argument("--namespace", default="llm")
    p.add_argument("--workload", default="rag-service")
    p.add_argument("--service", default="rag-service")
    p.add_argument("--node", default="tpu-vm-0")
    return p


def load_input_samples(path: str) -> list[RawSample]:
    stream = sys.stdin if path == "-" else open(path, encoding="utf-8")
    try:
        samples = []
        for line in stream:
            line = line.strip()
            if line:
                samples.append(RawSample.from_dict(json.loads(line)))
        return samples
    finally:
        if stream is not sys.stdin:
            stream.close()


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    if args.scenario:
        meta = SampleMeta(
            cluster=args.cluster,
            namespace=args.namespace,
            workload=args.workload,
            service=args.service,
            node=args.node,
        )
        samples = generate_synthetic_samples(
            args.scenario, args.count, datetime.now(timezone.utc), meta
        )
    elif args.input:
        try:
            samples = load_input_samples(args.input)
        except (OSError, ValueError) as exc:
            print(f"collector: cannot load {args.input}: {exc}", file=sys.stderr)
            return 2
    else:
        print("collector: provide --input or --scenario", file=sys.stderr)
        return 2

    writers = EventWriters(
        output=args.output,
        jsonl_path=args.jsonl_path,
        otlp_endpoint=args.otlp_endpoint,
    )
    emitted = dropped = 0
    try:
        for sample in samples:
            events = [e for e in normalize_sample(sample) if validate_slo(e)]
            dropped += 4 - len(events)
            writers.emit_slo(events)
            emitted += len(events)
    finally:
        writers.close()
    print(f"collector: emitted {emitted} events, dropped {dropped}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
