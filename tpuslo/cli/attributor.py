"""Attributor: fault-sample JSONL → attributions + summary + confusion CSV.

Reference: ``cmd/attributor/main.go`` — mode bayes|rule, per-prediction
schema validation, optional webhook delivery with ``--webhook-strict``.
"""

from __future__ import annotations

import argparse
import csv
import json
import sys
from pathlib import Path

from tpuslo import attribution, webhook
from tpuslo.schema import SCHEMA_INCIDENT_ATTRIBUTION, validate


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpuslo attributor", description=__doc__)
    p.add_argument("--input", required=True, help="fault samples JSONL")
    p.add_argument("--output", default="attributions.jsonl")
    p.add_argument("--summary", default="")
    p.add_argument("--confusion", default="")
    p.add_argument("--mode", default="bayes", choices=["bayes", "rule"])
    p.add_argument(
        "--evidence",
        default="hard",
        choices=["hard", "soft", "calibrated"],
        help="bayes evidence model: hard = reference-parity binary "
        "elevation; soft = graded log-ratio weights; calibrated = soft "
        "over the noise-fitted likelihood table "
        "(tpuslo.attribution.calibrate)",
    )
    p.add_argument("--webhook-url", default="")
    p.add_argument("--webhook-secret", default="")
    p.add_argument("--webhook-format", default="generic")
    p.add_argument(
        "--webhook-strict",
        action="store_true",
        help="fail the run if any webhook delivery fails",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    try:
        samples = attribution.load_samples_jsonl(args.input)
    except (OSError, ValueError) as exc:
        print(f"attributor: cannot load {args.input}: {exc}", file=sys.stderr)
        return 2
    if args.evidence != "hard" and args.mode == "rule":
        print(
            "attributor: --evidence soft/calibrated requires --mode bayes "
            "(rule mode never consults the Bayes model)",
            file=sys.stderr,
        )
        return 2
    attributor = None
    if args.evidence == "soft":
        attributor = attribution.BayesianAttributor(evidence="soft")
    elif args.evidence == "calibrated":
        from tpuslo.attribution.calibrate import calibrated_attributor

        attributor = calibrated_attributor()
    predictions = attribution.build_attributions(
        samples, mode=args.mode, attributor=attributor
    )
    for pred in predictions:
        validate(pred.to_dict(), SCHEMA_INCIDENT_ATTRIBUTION)

    with open(args.output, "w", encoding="utf-8") as f:
        attribution.dump_attributions_jsonl(predictions, f)

    f1 = attribution.macro_f1(samples, predictions)
    summary = {
        "sample_count": len(samples),
        "mode": attribution.normalize_mode(args.mode),
        "accuracy": attribution.accuracy(samples, predictions),
        "partial_accuracy": attribution.partial_accuracy(samples, predictions),
        "coverage_accuracy": attribution.coverage_accuracy(samples, predictions),
        "macro_f1": f1.macro_f1,
        "per_domain_f1": {s.domain: s.f1 for s in f1.per_domain},
    }
    if args.summary:
        Path(args.summary).write_text(json.dumps(summary, indent=2) + "\n")

    if args.confusion:
        matrix = attribution.build_confusion_matrix(samples, predictions)
        with open(args.confusion, "w", newline="", encoding="utf-8") as f:
            writer = csv.writer(f)
            writer.writerow(["actual", "predicted", "count"])
            for (actual, predicted), count in sorted(matrix.items()):
                writer.writerow([actual, predicted, count])

    webhook_failures = 0
    if args.webhook_url:
        hook = webhook.Exporter(
            args.webhook_url,
            secret=args.webhook_secret,
            format=args.webhook_format,
        )
        for pred in predictions:
            try:
                hook.send(pred)
            except webhook.WebhookError as exc:
                webhook_failures += 1
                print(f"attributor: webhook failed: {exc}", file=sys.stderr)

    print(
        f"attributor: {len(predictions)} predictions, "
        f"accuracy={summary['accuracy']:.4f} macro_f1={summary['macro_f1']:.4f}",
        file=sys.stderr,
    )
    if webhook_failures and args.webhook_strict:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
