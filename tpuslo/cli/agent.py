"""Node agent: the toolkit's ``serve()`` loop.

Reference: ``cmd/agent/main.go`` — synthetic scenario → SLO + probe
events → stdout/jsonl/OTLP, Prometheus metrics server on :2112,
overhead-guard probe shedding, rate limiting with drop accounting,
optional webhook attribution, ``--probe-smoke`` privilege check.

The real-probe path swaps in behind ``--probe-source ring`` once the
native loader is present (closing the reference's biggest gap: its
ring-buffer consumer is never wired into the agent loop — SURVEY.md §0).
"""

from __future__ import annotations

import argparse
import sys
import time

from tpuslo import attribution, webhook
from tpuslo.cli.common import EventWriters, resolve_config, validate_probe, validate_slo
from tpuslo.collector import (
    SampleMeta,
    build_synthetic_sample,
    normalize_sample,
    supported_synthetic_scenarios,
)
from tpuslo.collector.kernel import probe_smoke_check
from tpuslo.correlation.matcher import SignalRef
from tpuslo.delivery import DeliveryOptions
from tpuslo.metrics import AgentMetrics, start_metrics_server
from tpuslo.safety import OverheadGuard, RateLimiter, ShedRecoveryPolicy
from tpuslo.signals import (
    Generator,
    Metadata,
    StaticMetadataEnricher,
    TPUMetadataEnricher,
    parse_capability_mode,
    profile_for_fault,
)
from datetime import datetime, timedelta, timezone


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpuslo agent", description=__doc__)
    p.add_argument("--config", default="", help="toolkit.yaml path")
    p.add_argument(
        "--scenario",
        default="baseline",
        choices=supported_synthetic_scenarios(),
    )
    p.add_argument("--interval-s", type=float, default=1.0)
    p.add_argument("--count", type=int, default=0, help="0 = run forever")
    p.add_argument("--event-kind", default="both", choices=["slo", "probe", "both"])
    p.add_argument("--output", default="stdout", choices=["stdout", "jsonl", "otlp"])
    p.add_argument("--jsonl-path", default="")
    p.add_argument("--otlp-endpoint", default="")
    p.add_argument("--capability-mode", default="auto")
    p.add_argument("--signal-set", default="", help="comma-separated override")
    p.add_argument("--metrics-port", type=int, default=2112, help="0 disables")
    p.add_argument("--max-overhead-pct", type=float, default=0.0)
    p.add_argument("--events-per-second", type=int, default=0)
    p.add_argument("--webhook-url", default="")
    p.add_argument("--webhook-secret", default="")
    p.add_argument("--webhook-format", default="")
    p.add_argument("--cluster", default="tpu-cluster")
    p.add_argument("--namespace", default="llm")
    p.add_argument("--workload", default="rag-service")
    p.add_argument("--service", default="rag-service")
    p.add_argument("--node", default="tpu-vm-0")
    p.add_argument("--probe-smoke", action="store_true")
    p.add_argument(
        "--columnar",
        action="store_true",
        help="batch loop on the columnar spine: each cycle generates "
        "--columnar-batch samples straight into columns, gates them "
        "vectorized, and serializes one JSONL block (fleet-scale "
        "probe-event throughput; probe events only)",
    )
    p.add_argument(
        "--columnar-batch",
        type=int,
        default=256,
        help="samples per columnar cycle (each fans out to one probe "
        "event per enabled signal)",
    )
    p.add_argument(
        "--fleet-upstream",
        default="",
        help="ship gated columnar batches upward to the fleet "
        "aggregators: append one base64-transport shipment per gated "
        "batch (versioned wire contract, monotonic per-node seq) to "
        "this JSONL log, which `tpuslo fleetagg` consumes; requires "
        "--columnar",
    )
    p.add_argument(
        "--profile-device",
        action="store_true",
        help="continuous device profiler: stride-gated capture "
        "windows (live xprof or the seeded synthetic lane) folded "
        "through the device-plane ledger under a measured-overhead "
        "governor, emitting per-window device signals into the "
        "columnar spine; requires --columnar (knobs: the `profiler:` "
        "config section)",
    )
    p.add_argument(
        "--profiler-source",
        default="",
        choices=["", "synthetic", "xprof"],
        help="capture lane override (default: profiler.source config; "
        "xprof needs importable jax and falls back to synthetic with "
        "a note when unavailable)",
    )
    p.add_argument(
        "--profiler-stride",
        type=int,
        default=0,
        help="capture every N columnar cycles "
        "(0 = profiler.stride_cycles config)",
    )
    p.add_argument(
        "--profiler-preempt-window",
        type=int,
        default=-1,
        help="synthetic lane only: inject a preemption-sized idle gap "
        "and its eviction notice into this capture window (seeded "
        "e2e evidence; -1 disables)",
    )
    # Multi-host identity for the ring loop's TPU events: a DaemonSet
    # agent knows which slice/host it runs on; SliceJoiner joins
    # per-host streams on exactly this identity.
    p.add_argument("--slice-id", default="", help="TPU slice identity")
    p.add_argument(
        "--host-index", type=int, default=0,
        help="this host's index within the slice",
    )
    p.add_argument(
        "--xla-program-id", default="",
        help="program identity stamped on collective probe events",
    )
    p.add_argument("--tpu-chip", default="accel0")
    p.add_argument(
        "--probe-source",
        default="synthetic",
        choices=["synthetic", "ring"],
        help="ring = consume the native eBPF ring buffer",
    )
    p.add_argument(
        "--ring-path",
        default="",
        help="extra userspace ring to consume (injectors/fallback); "
        "ring mode only",
    )
    p.add_argument(
        "--hello",
        action="store_true",
        help="emit hello heartbeat events through the ring (e2e evidence)",
    )
    p.add_argument(
        "--spool-dir",
        default="",
        help="enable resilient delivery: batches that cannot reach a "
        "network sink are spooled here and replayed on recovery "
        "(config: delivery.spool_dir)",
    )
    p.add_argument(
        "--restore-after-cycles",
        type=int,
        default=0,
        help="re-enable one shed probe signal after this many "
        "consecutive under-budget guard cycles "
        "(0 = config delivery.restore_after_cycles)",
    )
    p.add_argument(
        "--chaos-sink",
        default="",
        metavar="SCHEDULE",
        help="start an in-process fault-injection OTLP sink and point "
        "the exporters at it; SCHEDULE is behavior[:count],... with "
        "behaviors ok|refuse|5xx|4xx|hang|flap (e.g. 'ok:3,refuse:6,ok') "
        "— demo/chaos harness, implies --output otlp",
    )
    p.add_argument(
        "--ici-probe-interval-s",
        type=float,
        default=0.0,
        help="run the active ICI collective prober every N seconds "
        "(0 disables; needs exclusive device access — the chip must "
        "not be held by a serving workload)",
    )
    p.add_argument("--ici-probe-payload-kb", type=int, default=256)
    p.add_argument(
        "--chaos-telemetry",
        type=float,
        default=0.0,
        metavar="INTENSITY",
        help="perturb the probe stream at the source with seeded skew/"
        "reorder/dup/corrupt/drop chaos (1.0 = moderate: skew<=250ms, "
        "5%% dup, 5%% reorder, 1%% corrupt); pairs with the ingest "
        "gate (config ingest:) to rehearse telemetry-quality incidents",
    )
    p.add_argument("--chaos-telemetry-seed", type=int, default=1337)
    p.add_argument(
        "--stats-interval-cycles",
        type=int,
        default=30,
        help="emit a periodic stats line (drops, rejections by reason, "
        "gate counters) every N cycles; 0 disables",
    )
    p.add_argument(
        "--state-dir",
        default="",
        help="enable the crash-safe runtime: periodic atomic snapshots "
        "of agent state (watermark, skew, dedup digest, breaker/shed "
        "state, limiter budget) land here and are restored on restart "
        "(config: runtime.state_dir)",
    )
    p.add_argument(
        "--snapshot-interval-s",
        type=float,
        default=-1.0,
        help="seconds between periodic snapshots; 0 = every cycle, "
        "-1 = config runtime.snapshot_interval_s",
    )
    p.add_argument(
        "--cold-start",
        action="store_true",
        help="ignore any on-disk snapshot and start cold (operator "
        "escape hatch for a poisoned snapshot)",
    )
    p.add_argument(
        "--drain-timeout-s",
        type=float,
        default=0.0,
        help="deadline for the graceful SIGTERM/SIGINT drain sequence "
        "(0 = config runtime.drain_timeout_s)",
    )
    p.add_argument(
        "--trace",
        action="store_true",
        help="self-trace every agent cycle: a root span with child "
        "spans per pipeline stage (generate/ingest-gate/validate/"
        "correlate/attribute/deliver/snapshot), tail-sampled so slow "
        "and error cycles are always kept, exported as OTLP traces "
        "through the delivery layer (config: observability.enabled)",
    )
    p.add_argument(
        "--trace-endpoint",
        default="",
        help="OTLP/HTTP traces endpoint; empty derives the /v1/traces "
        "sibling of the logs endpoint when --output otlp "
        "(config: observability.trace_endpoint)",
    )
    p.add_argument(
        "--trace-sample-rate",
        type=float,
        default=-1.0,
        help="probability of keeping a fast, error-free cycle "
        "(-1 = config observability.sample_rate)",
    )
    p.add_argument(
        "--trace-slow-ms",
        type=float,
        default=0.0,
        help="cycle-duration budget: cycles at or past it are always "
        "sampled (0 = config observability.slow_cycle_ms)",
    )
    p.add_argument(
        "--provenance-path",
        default="",
        help="incident provenance JSONL (read by `sloctl explain`); "
        "empty = config observability.provenance_path, falling back "
        "to <state-dir>/provenance.jsonl",
    )
    p.add_argument(
        "--burn-engine",
        action="store_true",
        help="run the SLO error-budget / burn-rate engine over the "
        "per-request SLI stream with default targets (config: slo: "
        "section — presence implies on); burn state feeds Prometheus, "
        "incident payloads, provenance, and `sloctl budget`",
    )
    p.add_argument(
        "--tenant",
        default="",
        help="tenant identity stamped on this agent's request outcomes "
        "(default: --namespace); per-tenant SLO targets come from "
        "config slo.tenants",
    )
    p.add_argument(
        "--remediate",
        action="store_true",
        help="run the auto-remediation engine over attributed "
        "incidents (config: remediation: section — presence implies "
        "on; needs the burn engine for its burn-state gate and verify "
        "evidence): high-confidence attributions under active burn "
        "apply reversible actions (probe shed, breaker trip, tenant "
        "demotion), verified against the burn or rolled back",
    )
    return p


def _gate_pipeline(events, chaos_stream, gate, metrics):
    """Dict-level chaos + ingest-gate pass over generated probe events.

    Chaos perturbs what the "wire" carries; the gate re-admits it.
    Events the gate quarantined/deduplicated never come back; a
    payload the gate passed through untouched keeps its original
    typed event (no lossy rebuild on the gate-only hot path — both
    chaos and the gate copy on write, so dict identity is the
    "untouched" proof).  A rebuild failure (corrupt event with no
    gate to stop it) is an accounted drop, never a crash.
    """
    from tpuslo.schema import ProbeEventV1

    pairs = [(event, event.to_dict()) for event in events]
    original_by_payload = {id(payload): event for event, payload in pairs}
    payloads = [payload for _, payload in pairs]
    if chaos_stream is not None:
        payloads = list(chaos_stream.stream(payloads))
    if gate is not None:
        payloads = gate.admit_all(payloads).all_events()
    out = []
    for payload in payloads:
        original = original_by_payload.get(id(payload))
        if original is not None:
            out.append(original)
            continue
        try:
            out.append(ProbeEventV1.from_dict(payload))
        except (TypeError, ValueError, KeyError):
            metrics.dropped.labels(reason="malformed").inc()
    return out


def _print_stats(
    gate, metrics: AgentMetrics | None = None, burn_engine=None
) -> None:
    """Periodic stats line: every silent drop, made loud — and, with
    the self-tracer's histograms populated, per-stage p50/p99 so "why
    is the loop slow" is answerable from the log alone."""
    from tpuslo.metrics import REJECTION_COUNTERS, VALIDATION_COUNTERS

    parts = [f"validation={VALIDATION_COUNTERS.snapshot()}"]
    rejections = REJECTION_COUNTERS.snapshot()
    if rejections:
        parts.append(f"rejections={rejections}")
    if gate is not None:
        parts.append(f"gate={gate.snapshot()}")
    if burn_engine is not None:
        parts.append(f"burn={burn_engine.snapshot()}")
    if metrics is not None:
        stages = metrics.stage_quantiles()
        if stages:
            parts.append(
                "stage_ms="
                + ",".join(
                    f"{name}:{est.get('p50', 0.0):.2f}/{est.get('p99', 0.0):.2f}"
                    for name, est in sorted(stages.items())
                )
                + " (p50/p99)"
            )
    print("agent: stats: " + " ".join(parts), file=sys.stderr)


def _signal_ref(event, ts_cache: dict | None = None):
    """ProbeEventV1 → correlation SignalRef without a dict round-trip.

    ``ts_cache`` memoizes the ns→datetime conversion: every probe
    event in one synthetic cycle carries the same sample timestamp,
    and this runs once per emitted event inside the correlate stage
    whose latency the tracer is measuring.
    """
    ts = None
    if event.ts_unix_nano > 0:
        if ts_cache is not None:
            ts = ts_cache.get(event.ts_unix_nano)
        if ts is None:
            ts = datetime.fromtimestamp(
                event.ts_unix_nano / 1e9, tz=timezone.utc
            )
            if ts_cache is not None:
                ts_cache[event.ts_unix_nano] = ts
    tpu = event.tpu
    return SignalRef(
        signal=event.signal,
        timestamp=ts,
        trace_id=event.trace_id,
        node=event.node,
        pod=event.pod,
        pid=event.pid,
        conn_tuple=event.conn_tuple.key() if event.conn_tuple else "",
        value=event.value,
        slice_id=tpu.slice_id if tpu else "",
        host_index=tpu.host_index if tpu else -1,
        program_id=tpu.program_id if tpu else "",
        launch_id=tpu.launch_id if tpu else -1,
    )


def main(
    argv: list[str] | None = None, metrics: AgentMetrics | None = None
) -> int:
    args = build_parser().parse_args(argv)

    if args.probe_smoke:
        result = probe_smoke_check()
        print(f"probe-smoke: {'PASS' if result.ok else 'FAIL'}: {result.detail}")
        return 0 if result.ok else 1

    cfg = resolve_config(args.config)
    mode = parse_capability_mode(args.capability_mode)
    signal_set = (
        [s.strip() for s in args.signal_set.split(",") if s.strip()]
        if args.signal_set
        else cfg.signal_set
    )
    max_overhead = args.max_overhead_pct or cfg.safety.max_overhead_pct
    eps = args.events_per_second or cfg.sampling.events_per_second_limit

    chaos_server = None
    otlp_endpoint = args.otlp_endpoint or cfg.otlp.endpoint
    if args.chaos_sink:
        from tpuslo.delivery.faultsink import FaultInjectingHTTPServer

        chaos_server = FaultInjectingHTTPServer(args.chaos_sink).start()
        otlp_endpoint = chaos_server.endpoint
        if args.output != "otlp":
            print(
                "agent: --chaos-sink implies --output otlp", file=sys.stderr
            )
            args.output = "otlp"
        print(f"agent: chaos sink on {otlp_endpoint}", file=sys.stderr)

    spool_dir = args.spool_dir or cfg.delivery.spool_dir
    delivery_opts = (
        DeliveryOptions.from_config(cfg.delivery, spool_dir=spool_dir)
        if spool_dir
        else None
    )

    metrics = metrics or AgentMetrics()

    chaos_stream = None
    if args.chaos_telemetry > 0 and args.columnar:
        # The chaos stream perturbs payload dicts on the row loop's
        # wire; the columnar loop never materializes per-event dicts,
        # so a drill flag here would silently do nothing.  Refusing
        # loudly beats an all-zero chaos snapshot that looks clean.
        print(
            "agent: --chaos-telemetry needs the row synthetic loop; "
            "drop --columnar to rehearse telemetry chaos",
            file=sys.stderr,
        )
        return 2
    if args.fleet_upstream and not args.columnar:
        # Shipments are columnar batches by contract; the row loop has
        # nothing to put on the fleet wire.  Refusing loudly beats an
        # upstream log that never grows.
        print(
            "agent: --fleet-upstream ships gated columnar batches; "
            "add --columnar",
            file=sys.stderr,
        )
        return 2
    if args.profile_device and not args.columnar:
        # Profiler windows are emitted as probe events on the columnar
        # spine — the row loop has no batch to fold them into.
        # Refusing loudly beats a profiler that silently never ticks.
        print(
            "agent: --profile-device emits capture windows into the "
            "columnar spine; add --columnar",
            file=sys.stderr,
        )
        return 2
    if args.chaos_telemetry > 0 and args.probe_source == "ring":
        # Ring events arrive one at a time from the kernel; the chaos
        # stream's reorder/dup buffering only makes sense on the
        # synthetic batch loop.  Refusing loudly beats a banner that
        # implies a drill which never runs.
        print(
            "agent: --chaos-telemetry applies to the synthetic loop "
            "only; ignored with --probe-source ring",
            file=sys.stderr,
        )
    elif args.chaos_telemetry > 0:
        from tpuslo.chaos.telemetry import ChaosScenario, ChaosStream

        chaos_stream = ChaosStream(
            ChaosScenario.at_intensity(
                args.chaos_telemetry, seed=args.chaos_telemetry_seed
            )
        )
        print(
            f"agent: telemetry chaos at intensity "
            f"{args.chaos_telemetry:g} (seed {args.chaos_telemetry_seed})",
            file=sys.stderr,
        )

    gate = None
    if cfg.ingest.enabled and not args.columnar:
        # Always-on once configured: the gate is the admission point
        # for everything the agent emits downstream.  (The columnar
        # loop builds its own vectorized gate from the same config.)
        from tpuslo.ingest import GateConfig, TelemetryGate

        gate = TelemetryGate(
            GateConfig(
                dedup_window=cfg.ingest.dedup_window,
                watermark_lateness_ms=cfg.ingest.watermark_lateness_ms,
                coordinator_host=cfg.ingest.coordinator_host,
                min_skew_samples=cfg.ingest.min_skew_samples,
                skew_correction=cfg.ingest.skew_correction,
                quarantine_dir=cfg.ingest.quarantine_dir,
                quarantine_max_bytes=cfg.ingest.quarantine_max_bytes,
                quarantine_max_age_s=cfg.ingest.quarantine_max_age_s,
            ),
            observer=metrics.ingest_observer(),
        )
        print(
            "agent: ingest gate on"
            + (
                f" (quarantine: {cfg.ingest.quarantine_dir})"
                if cfg.ingest.quarantine_dir
                else ""
            ),
            file=sys.stderr,
        )

    # ---- SLO error-budget / burn-rate engine -------------------------
    burn_engine = None
    tenant = args.tenant or args.namespace
    if (args.burn_engine or cfg.slo.enabled) and args.probe_source == "ring":
        # The SLI stream comes from the synthetic loop's per-request
        # samples; ring mode emits probe events only (SLO events come
        # from the observed workload).  Refusing loudly beats a "burn
        # engine on" banner over an engine that can never record.
        print(
            "agent: the burn engine needs the synthetic SLO loop; "
            "ignored with --probe-source ring",
            file=sys.stderr,
        )
    elif args.burn_engine or cfg.slo.enabled:
        from tpuslo.collector.pipeline import ERROR_RATE_THRESHOLDS
        from tpuslo.sloengine import (
            BurnEngine,
            EngineConfig,
            RequestOutcome,
        )

        burn_engine = BurnEngine(
            EngineConfig.from_toolkit(cfg.slo),
            observer=metrics.slo_observer(),
        )
        print(
            "agent: burn engine on (tenant="
            f"{tenant}, availability>="
            f"{burn_engine.config.availability_target:g}, "
            f"ttft<={burn_engine.config.ttft_objective_ms:g}ms@"
            f"{burn_engine.config.ttft_target:g}, fast "
            f"{burn_engine.config.fast_burn_threshold:g}x/1h+5m, slow "
            f"{burn_engine.config.slow_burn_threshold:g}x/6h+30m)",
            file=sys.stderr,
        )

    # ---- crash-safe runtime: durable snapshots + warm restore --------
    from tpuslo.runtime import AgentRuntime, StateStore

    runtime_observer = metrics.runtime_observer()
    state_dir = args.state_dir or cfg.runtime.state_dir
    store = None
    if state_dir:
        snapshot_interval = (
            args.snapshot_interval_s
            if args.snapshot_interval_s >= 0
            else cfg.runtime.snapshot_interval_s
        )
        import os as os_mod

        store = StateStore(
            os_mod.path.join(state_dir, "agent-state.json"),
            interval_s=snapshot_interval,
            max_age_s=cfg.runtime.snapshot_max_age_s,
            observer=runtime_observer,
        )
    runtime = AgentRuntime(
        store,
        observer=runtime_observer,
        log=lambda msg: print(f"agent: {msg}", file=sys.stderr),
    )
    # Loop progress: the synthetic loop resumes at next_cycle instead
    # of re-emitting from zero; alert_cycle is the webhook high-water
    # mark (alerts are at-most-once across restarts).
    progress = {"next_cycle": 0, "alert_cycle": -1}
    runtime.register(
        "progress",
        lambda: dict(progress),
        lambda s: progress.update(
            next_cycle=int(s.get("next_cycle", 0)),
            alert_cycle=int(s.get("alert_cycle", -1)),
        ),
    )
    if gate is not None:
        runtime.register("gate", gate.export_state, gate.restore_state)
    if burn_engine is not None:
        # Budgets survive crash-restart: the rings, alert states and
        # counters ride the same snapshot as everything else.
        runtime.register(
            "sloengine",
            burn_engine.export_state,
            burn_engine.restore_state,
        )

    meta_template = Metadata(
        node=args.node,
        namespace=args.namespace,
        pod=f"{args.workload}-agent",
        container=args.workload,
        pid=1,
        tid=1,
        slice_id=cfg.tpu.slice_id,
        host_index=cfg.tpu.host_index,
    )
    enricher = StaticMetadataEnricher(
        TPUMetadataEnricher(dev_glob=cfg.tpu.accel_device_glob).enrich(meta_template)
    )
    generator = Generator(mode, signal_set, enricher=enricher)

    writers = EventWriters(
        output=args.output,
        jsonl_path=args.jsonl_path,
        otlp_endpoint=otlp_endpoint,
        delivery=delivery_opts,
        observer_factory=metrics.delivery_observer,
    )

    # ---- self-observability: cycle spans + incident provenance -------
    from tpuslo.obs import (
        ProvenanceLog,
        SelfTracer,
        SpanExporter,
        TracerConfig,
        trace_endpoint_from_logs,
    )

    obs_cfg = cfg.observability
    obs_enabled = args.trace or obs_cfg.enabled
    span_exporter = None
    trace_channel = None
    trace_poster = None
    if obs_enabled:
        trace_endpoint = (
            args.trace_endpoint
            or obs_cfg.trace_endpoint
            or (
                trace_endpoint_from_logs(otlp_endpoint)
                if args.output == "otlp"
                else ""
            )
        )
        if trace_endpoint:
            span_exporter = SpanExporter(trace_endpoint)
            if delivery_opts is not None:
                # The agent's own telemetry rides the same resilience
                # rails as everyone else's: spool, breaker, retry.
                from tpuslo.delivery.sinks import OTLPRecordSink

                trace_channel = delivery_opts.build_channel(
                    "otlp-traces",
                    OTLPRecordSink(span_exporter),
                    observer=metrics.delivery_observer("otlp-traces"),
                )
            else:
                # No delivery layer: a synchronous POST in the cycle's
                # finish path would stall the loop for the exporter
                # timeout whenever the endpoint is down — hand batches
                # to a bounded background worker instead (best-effort,
                # drop-oldest, accounted).
                from tpuslo.obs import BackgroundSpanPoster

                trace_poster = BackgroundSpanPoster(span_exporter)

    def _export_spans(spans) -> None:
        records = span_exporter.to_records(spans)
        if trace_channel is not None:
            trace_channel.submit("trace", records)
        else:
            trace_poster.submit(records)

    tracer = SelfTracer(
        TracerConfig(
            enabled=obs_enabled,
            sample_rate=(
                args.trace_sample_rate
                if args.trace_sample_rate >= 0
                else obs_cfg.sample_rate
            ),
            slow_cycle_ms=args.trace_slow_ms or obs_cfg.slow_cycle_ms,
            max_overhead_pct=obs_cfg.max_overhead_pct,
        ),
        observer=metrics.trace_observer(),
        # No endpoint = metrics-only tracing: pass no export callback
        # at all, so neither stats nor the spans-exported counter can
        # report spans that never leave the process.
        on_export=_export_spans if span_exporter is not None else None,
        log=lambda msg: print(f"agent: {msg}", file=sys.stderr),
    )
    provenance_path = args.provenance_path or obs_cfg.provenance_path
    if not provenance_path and obs_enabled and (
        args.state_dir or cfg.runtime.state_dir
    ):
        import os as _os

        provenance_path = _os.path.join(
            args.state_dir or cfg.runtime.state_dir, "provenance.jsonl"
        )
    provenance_log = (
        ProvenanceLog(provenance_path)
        if obs_enabled and provenance_path
        else None
    )
    if obs_enabled:
        print(
            "agent: self-tracing on (sample_rate="
            f"{tracer.config.sample_rate:g}, slow>="
            f"{tracer.config.slow_cycle_ms:g}ms, "
            + (
                f"endpoint={span_exporter.endpoint}"
                if span_exporter
                else "metrics-only"
            )
            + (
                f", provenance={provenance_path}" if provenance_log else ""
            )
            + ")",
            file=sys.stderr,
        )

    metrics.up.set(1)
    metrics.capability_mode.labels(mode=mode).set(1)
    metrics.event_kind.labels(kind=args.event_kind).set(1)
    metrics.set_enabled_signals(generator.enabled_signals())

    limiter = RateLimiter(eps, cfg.sampling.burst_limit)
    guard = OverheadGuard(max_overhead)
    recovery = ShedRecoveryPolicy(
        cycles=args.restore_after_cycles or cfg.delivery.restore_after_cycles
    )
    runtime.register(
        "limiter", limiter.export_state, limiter.restore_state
    )
    runtime.register(
        "generator_shed",
        lambda: {"signals": generator.shed_signals()},
        lambda s: generator.import_shed(list(s.get("signals") or [])),
    )

    webhook_url = args.webhook_url or (cfg.webhook.url if cfg.webhook.enabled else "")
    hook = None
    attributor = None
    webhook_channel = None
    if webhook_url:
        hook = webhook.Exporter(
            webhook_url,
            secret=args.webhook_secret or cfg.webhook.secret,
            format=args.webhook_format or cfg.webhook.format,
            timeout_ms=cfg.webhook.timeout_ms,
        )
        attributor = attribution.BayesianAttributor()
        if delivery_opts is not None:
            # Incident delivery rides its own channel: the agent loop
            # never blocks on webhook retries/backoff again.
            from tpuslo.delivery.sinks import WebhookSink

            webhook_channel = delivery_opts.build_channel(
                "webhook",
                WebhookSink(hook),
                observer=metrics.delivery_observer("webhook"),
            )

    # ---- continuous device profiler (tpuslo.deviceplane.profiler) ----
    profiler = None
    profiler_attributor = None
    if args.profile_device or (cfg.profiler.enabled and args.columnar):
        from tpuslo.deviceplane.profiler import (
            ContinuousProfiler,
            seeded_cost_model,
        )

        prof_cfg = cfg.profiler
        prof_source = args.profiler_source or prof_cfg.source
        step_bytes, step_flops, step_dur = seeded_cost_model()
        prof_kwargs = dict(
            stride_cycles=args.profiler_stride or prof_cfg.stride_cycles,
            max_stride_cycles=prof_cfg.max_stride_cycles,
            window_steps=prof_cfg.window_steps,
            overhead_budget_pct=prof_cfg.overhead_budget_pct,
            cycle_budget_ms=prof_cfg.cycle_budget_ms,
            ema_alpha=prof_cfg.ema_alpha,
            grace_cycles=prof_cfg.grace_cycles,
            history=prof_cfg.history,
            bytes_per_step=step_bytes,
            flops_per_step=step_flops,
            step_dur_us=step_dur,
            node=args.node,
            namespace=args.namespace,
            pod=f"{args.workload}-agent",
            chip=args.tpu_chip,
            slice_id=args.slice_id or cfg.tpu.slice_id,
            host_index=(
                args.host_index if args.slice_id else cfg.tpu.host_index
            ),
            log_dir=prof_cfg.log_dir,
            synthetic_preempt_window=args.profiler_preempt_window,
            observer=metrics.profiler_observer(),
        )
        try:
            profiler = ContinuousProfiler(source=prof_source, **prof_kwargs)
        except (RuntimeError, ValueError) as exc:
            if prof_source == "xprof":
                # No live jax workload to bracket (or jax missing):
                # drop to the seeded lane so the loop still carries
                # device windows — loudly, so nobody mistakes the
                # synthetic stream for on-chip truth.
                print(
                    f"agent: profiler xprof lane unavailable ({exc}); "
                    "falling back to the seeded synthetic lane",
                    file=sys.stderr,
                )
                prof_source = "synthetic"
                profiler = ContinuousProfiler(
                    source=prof_source, **prof_kwargs
                )
            else:
                raise
        profiler_attributor = attribution.BayesianAttributor()
        runtime.register(
            "profiler", profiler.export_state, profiler.restore_state
        )
        print(
            "agent: continuous profiler on "
            f"(source={prof_source}, "
            f"stride={profiler.stride_cycles} cycle(s), "
            f"budget {profiler.overhead_budget_pct:g}% of "
            f"{profiler.cycle_budget_ms:g}ms)",
            file=sys.stderr,
        )

    def _all_channels():
        return writers.delivery_channels + [
            ch
            for ch in (webhook_channel, trace_channel)
            if ch is not None
        ]

    def _export_breakers():
        return {
            ch.name: ch.breaker.export_state() for ch in _all_channels()
        }

    def _restore_breakers(state):
        for ch in _all_channels():
            if isinstance(state.get(ch.name), dict):
                ch.breaker.restore_state(state[ch.name])

    runtime.register("breakers", _export_breakers, _restore_breakers)

    # ---- real readiness: /readyz tells the truth ---------------------
    from tpuslo.metrics import Readiness

    readiness = Readiness()
    readiness_state = {"draining": False}
    readiness.add_check(
        "drain",
        lambda: (not readiness_state["draining"], "drain in progress"),
    )

    def _breakers_ready():
        channels = _all_channels()
        if channels and all(
            ch.breaker.state == "open" for ch in channels
        ):
            return False, (
                f"all {len(channels)} delivery breakers open "
                "(every sink unreachable)"
            )
        return True, "ok"

    readiness.add_check("breakers", _breakers_ready)
    if store is not None:

        def _snapshot_fresh():
            age = store.age_s()
            max_age = cfg.runtime.snapshot_max_age_s
            if age != float("inf") and max_age > 0 and age > max_age:
                return False, (
                    f"state snapshot stale ({age:.0f}s > {max_age:.0f}s)"
                )
            return True, "ok"

        readiness.add_check("snapshot", _snapshot_fresh)

    server = None
    if args.metrics_port:
        server = start_metrics_server(
            metrics, args.metrics_port, readiness=readiness
        )
        print(
            f"agent: metrics on :{args.metrics_port}/metrics",
            file=sys.stderr,
        )

    # ---- auto-remediation engine: close the observe → act loop -------
    remediation_engine = None
    shed_ownership = None
    if args.remediate or cfg.remediation.enabled:
        if burn_engine is None:
            # The policy gates on burn state and the verifier watches
            # burn evidence; without the burn engine the loop would
            # either act blind or never act.  Refusing loudly beats a
            # "remediation on" banner over an engine that cannot
            # verify.
            print(
                "agent: auto-remediation needs the burn engine "
                "(--burn-engine / config slo:); disabled",
                file=sys.stderr,
            )
        else:
            from tpuslo.remediation import (
                ActionBindings,
                RemediationEngine,
                RemediationPolicy,
                VerifyPolicy,
                default_rules,
            )
            from tpuslo.safety import ShedOwnership

            shed_ownership = ShedOwnership()
            remediation_engine = RemediationEngine(
                policy=RemediationPolicy(
                    rules=default_rules(
                        min_confidence=cfg.remediation.min_confidence,
                        cooldown_s=cfg.remediation.cooldown_s,
                        rate_limit=cfg.remediation.rate_limit,
                        rate_window_s=cfg.remediation.rate_window_s,
                    ),
                    max_concurrent_actions=(
                        cfg.remediation.max_concurrent_actions
                    ),
                    disabled_actions=tuple(
                        cfg.remediation.disabled_actions
                    ),
                ),
                bindings=ActionBindings(
                    probe_manager=generator,
                    ownership=shed_ownership,
                    breakers={
                        ch.name: ch.breaker for ch in _all_channels()
                    },
                    runtime=runtime,
                    burn_engine=burn_engine,
                ),
                verify=VerifyPolicy(
                    windows=cfg.remediation.verify_windows,
                    subside_streak=cfg.remediation.verify_streak,
                    subside_below=cfg.remediation.verify_subside_below,
                ),
                observer=metrics.remediation_observer(),
                provenance_log=provenance_log,
                log=lambda msg: print(f"agent: {msg}", file=sys.stderr),
            )
            runtime.register(
                "remediation",
                remediation_engine.export_state,
                remediation_engine.restore_state,
            )
            runtime.register(
                "shed_ownership",
                shed_ownership.export_state,
                shed_ownership.restore_state,
            )
            print(
                "agent: auto-remediation on (min confidence "
                f"{cfg.remediation.min_confidence:g}, budget "
                f"{cfg.remediation.max_concurrent_actions} concurrent, "
                f"verify {cfg.remediation.verify_windows} windows)",
                file=sys.stderr,
            )

    sample_meta = SampleMeta(
        cluster=args.cluster,
        namespace=args.namespace,
        workload=args.workload,
        service=args.service,
        node=args.node,
        slice_id=cfg.tpu.slice_id,
        host_index=cfg.tpu.host_index,
    )

    ici_prober = None
    if (
        args.ici_probe_interval_s > 0
        and args.event_kind == "slo"
        and args.probe_source != "ring"
    ):
        # Ring mode emits probe events regardless of event_kind, so the
        # guard only applies to the synthetic loop.
        print(
            "agent: --ici-probe-interval-s needs --event-kind probe|both "
            "(probe events are the prober's output); disabled",
            file=sys.stderr,
        )
    elif args.ici_probe_interval_s > 0:
        from tpuslo.parallel.collectives import ActiveICIProber

        ici_prober = ActiveICIProber(
            interval_s=args.ici_probe_interval_s,
            node=args.node,
            namespace=args.namespace,
            slice_id=cfg.tpu.slice_id,
            host_index=cfg.tpu.host_index,
            payload_kb=args.ici_probe_payload_kb,
            log=lambda msg: print(f"agent: {msg}", file=sys.stderr),
        )

    from tpuslo.correlation.matcher import SpanRef
    from tpuslo.correlation.matcher import match as corr_match
    from tpuslo.obs import (
        EvidenceEvent,
        ProvenanceRecord,
        probe_event_id,
    )
    from tpuslo.schema import rfc3339

    def _correlation_summary(decisions) -> dict:
        matched = [d for _, d in decisions if d.matched]
        best = max(matched, key=lambda d: d.confidence, default=None)
        return {
            "window_ms": cfg.correlation.window_ms,
            "total": len(decisions),
            "matched": len(matched),
            "best_tier": best.tier if best else "none",
        }

    def emit_one(idx: int) -> None:
        now = datetime.now(timezone.utc)
        with tracer.cycle(
            "agent.cycle", cycle=idx, scenario=args.scenario
        ) as tr:
            # ---- generate: synthetic sample → SLO + probe events -----
            with tr.stage("generate") as sp:
                sample = build_synthetic_sample(
                    args.scenario, idx, now, sample_meta
                )
                slo_events = (
                    normalize_sample(sample)
                    if args.event_kind in ("slo", "both")
                    else []
                )
                generated: list = []
                if args.event_kind in ("probe", "both"):
                    probe_meta = Metadata(trace_id=sample.trace_id)
                    generated = list(generator.generate(sample, probe_meta))
                    if ici_prober is not None:
                        # Measured collectives ride the same validation /
                        # rate-limit / emit path as every other signal.
                        generated.extend(
                            ici_prober.maybe_probe(time.monotonic())
                        )
                sp.set(
                    slo_events=len(slo_events),
                    probe_events=len(generated),
                    fault_label=sample.fault_label or "",
                )

            # ---- ingest gate: chaos + admission --------------------
            with tr.stage("ingest_gate") as sp:
                gated = generated
                if generated and (
                    chaos_stream is not None or gate is not None
                ):
                    gated = _gate_pipeline(
                        generated, chaos_stream, gate, metrics
                    )
                sp.set(
                    events_in=len(generated),
                    events_out=len(gated),
                    gate_enabled=gate is not None,
                )

            # ---- validate: schema + rate limit ---------------------
            with tr.stage("validate") as sp:
                valid_slo = []
                slo_rejects = 0
                for event in slo_events:
                    if validate_slo(event):
                        valid_slo.append(event)
                    else:
                        slo_rejects += 1
                        metrics.dropped.labels(reason="schema").inc()
                emitted = []
                rate_dropped = schema_dropped = 0
                for event in gated:
                    if not limiter.allow():
                        rate_dropped += 1
                        metrics.dropped.labels(reason="rate_limit").inc()
                        continue
                    if not validate_probe(event):
                        schema_dropped += 1
                        metrics.dropped.labels(reason="schema").inc()
                        continue
                    emitted.append(event)
                sp.set(
                    slo_valid=len(valid_slo),
                    slo_rejected=slo_rejects,
                    probe_valid=len(emitted),
                    rate_limited=rate_dropped,
                    schema_rejected=schema_dropped,
                )

            # ---- burn: fold the request outcome into the SLI stream
            # and run the multi-window burn rules.  A transition here
            # is the alert; sustained burns only move the gauges.
            burn_transitions: list = []
            if burn_engine is not None:
                with tr.stage("burn") as sp:
                    tps = sample.token_throughput_tps
                    burn_engine.record(
                        RequestOutcome(
                            tenant=tenant,
                            ts_unix_nano=int(now.timestamp() * 1e9),
                            ttft_ms=sample.ttft_ms,
                            tpot_ms=(1000.0 / tps if tps > 0 else 0.0),
                            tokens=max(
                                1,
                                int(
                                    tps
                                    * sample.request_latency_ms
                                    / 1000.0
                                ),
                            ),
                            status=(
                                "error"
                                if sample.error_rate
                                >= ERROR_RATE_THRESHOLDS[1]
                                else "ok"
                            ),
                            request_id=sample.request_id,
                        )
                    )
                    burn_transitions = burn_engine.evaluate(
                        now.timestamp()
                    )
                    for transition in burn_transitions:
                        print(
                            "agent: burn alert: "
                            f"{transition.severity} "
                            f"{transition.tenant}/"
                            f"{transition.objective} "
                            f"{transition.from_state}->"
                            f"{transition.to_state} "
                            f"(burn {transition.burn_long:.1f}x long / "
                            f"{transition.burn_short:.1f}x short)",
                            file=sys.stderr,
                        )
                    sp.set(
                        transitions=len(burn_transitions),
                        alerting=burn_engine.policy.alerting_count(),
                    )

            # ---- correlate: probe events vs this cycle's trace -----
            # Per-event tier/confidence decisions feed the incident
            # provenance chain — their only consumer — so the matcher
            # runs exactly on the cycles that will attribute (fault
            # label + webhook) with observability on; every other
            # cycle records the stage as skipped and pays nothing.
            incident_fault = (
                hook is not None
                and attributor is not None
                and sample.fault_label
            )
            decisions: list = []
            with tr.stage("correlate") as sp:
                if (
                    incident_fault
                    and emitted
                    and (tracer.enabled or provenance_log is not None)
                ):
                    span_ref = SpanRef(
                        timestamp=now,
                        trace_id=sample.trace_id,
                        service=args.service,
                        node=args.node,
                    )
                    ts_cache: dict = {}
                    decisions = [
                        (
                            event,
                            corr_match(
                                span_ref,
                                _signal_ref(event, ts_cache),
                                cfg.correlation.window_ms,
                            ),
                        )
                        for event in emitted
                    ]
                    matched = [d for _, d in decisions if d.matched]
                    best = max(
                        matched, key=lambda d: d.confidence, default=None
                    )
                    sp.set(
                        total=len(emitted),
                        matched=len(matched),
                        best_tier=best.tier if best else "",
                        window_ms=cfg.correlation.window_ms,
                    )
                else:
                    sp.set(total=len(emitted), skipped=True)

            # ---- attribute: fault cycles → incident posterior ------
            attr = None
            prov_rec = None
            webhook_outcome = ""
            with tr.stage("attribute") as sp:
                if incident_fault and idx <= progress["alert_cycle"]:
                    # Already alerted by a previous incarnation
                    # (restored high-water mark): re-emitting would
                    # page twice for one incident.
                    webhook_outcome = "deduped"
                    sp.set(deduped=True)
                elif incident_fault:
                    # The burn engine supplies the customer-impact
                    # denominator: slo_impact carries the real max
                    # active burn instead of a placeholder, so webhook
                    # severity escalates on fast burns.
                    active_burns = (
                        burn_engine.active_burns()
                        if burn_engine is not None
                        else []
                    )
                    incident_burn = max(
                        2.0,
                        (
                            burn_engine.max_active_burn(active_burns)
                            if burn_engine is not None
                            else 0.0
                        ),
                    )
                    fault = attribution.FaultSample(
                        incident_id=f"agent-inc-{idx + 1:04d}",
                        timestamp=now,
                        cluster=args.cluster,
                        namespace=args.namespace,
                        service=args.service,
                        fault_label=sample.fault_label,
                        confidence=0.9,
                        burn_rate=incident_burn,
                        window_minutes=5,
                        request_id=sample.request_id,
                        trace_id=sample.trace_id,
                        # Full fault profile, independent of the
                        # currently-enabled probe set: shedding
                        # shouldn't starve attribution.
                        signals=profile_for_fault(sample.fault_label),
                    )
                    attr = attributor.attribute_sample(fault)
                    if active_burns:
                        # The incident records which budgets were
                        # burning when it fired — the page's "how bad
                        # is this" context.
                        attr.slo_burn = {
                            "evaluated_at": rfc3339(now),
                            "max_burn_rate": round(incident_burn, 4),
                            "alerting": active_burns,
                        }
                    if tracer.enabled or provenance_log is not None:
                        supporting = {
                            s
                            for h in attr.fault_hypotheses
                            for s in h.evidence
                        }
                        prov_rec = ProvenanceRecord(
                            incident_id=attr.incident_id,
                            recorded_at=rfc3339(now),
                            cycle=idx,
                            trace_id=tr.trace_id,
                            root_span_id=(
                                tr.root.span_id if tr.root else ""
                            ),
                            fault_label=sample.fault_label,
                            predicted_fault_domain=(
                                attr.predicted_fault_domain
                            ),
                            confidence=attr.confidence,
                            posterior={
                                h.domain: round(h.posterior, 6)
                                for h in attr.fault_hypotheses[:5]
                            },
                            events=[
                                EvidenceEvent(
                                    event_id=probe_event_id(
                                        ev.signal, ev.ts_unix_nano
                                    ),
                                    signal=ev.signal,
                                    value=ev.value,
                                    tier=dec.tier,
                                    confidence=dec.confidence,
                                )
                                for ev, dec in decisions
                                if ev.signal in supporting or dec.matched
                            ],
                            correlation=_correlation_summary(decisions),
                            burning=active_burns,
                        )
                        attr.provenance = prov_rec.attribution_block()
                        # The provenance record points at this cycle's
                        # trace — force tail sampling to keep it, or
                        # the pointer would dangle for ~95% of
                        # incidents at the default sample rate.
                        tr.mark_keep()
                    sp.set(
                        incident_id=attr.incident_id,
                        domain=attr.predicted_fault_domain,
                        confidence=round(attr.confidence, 4),
                    )
                else:
                    sp.set(skipped=True)

            # ---- deliver: writers + webhook ------------------------
            with tr.stage("deliver") as sp:
                if args.event_kind in ("slo", "both"):
                    try:
                        writers.emit_slo(valid_slo)
                        metrics.slo_events.inc(len(valid_slo))
                    except Exception as exc:  # noqa: BLE001 — drops
                        metrics.dropped.labels(reason="emit").inc(
                            len(valid_slo)
                        )
                        print(
                            f"agent: slo emit failed: {exc}",
                            file=sys.stderr,
                        )
                if args.event_kind in ("probe", "both"):
                    try:
                        writers.emit_probe(emitted)
                        for event in emitted:
                            metrics.observe_probe(event.signal, event.value)
                    except Exception as exc:  # noqa: BLE001
                        metrics.dropped.labels(reason="emit").inc(
                            len(emitted)
                        )
                        print(
                            f"agent: probe emit failed: {exc}",
                            file=sys.stderr,
                        )
                if webhook_outcome == "deduped":
                    metrics.webhook_sent.labels(outcome="deduped").inc()
                elif attr is not None:
                    # At-most-once across restarts: persist the high-
                    # water mark *before* the send, so a crash in
                    # between loses (at worst) one alert instead of
                    # duplicating it — downstream pagers treat
                    # duplicate incidents as new pages, lost ones
                    # re-fire on the next burn window.
                    progress["alert_cycle"] = idx
                    if runtime.enabled:
                        runtime.snapshot_now()
                    if webhook_channel is not None:
                        import json as json_mod

                        webhook_channel.submit(
                            "incident",
                            [json_mod.loads(hook.build_payload(attr))],
                        )
                        metrics.webhook_sent.labels(outcome="queued").inc()
                        webhook_outcome = "queued"
                    else:
                        try:
                            hook.send(attr)
                            metrics.webhook_sent.labels(outcome="ok").inc()
                            webhook_outcome = "ok"
                        except webhook.WebhookError as exc:
                            metrics.webhook_sent.labels(
                                outcome="error"
                            ).inc()
                            webhook_outcome = "error"
                            print(
                                f"agent: webhook failed: {exc}",
                                file=sys.stderr,
                            )
                sp.set(
                    slo=len(valid_slo),
                    probe=len(emitted),
                    webhook=webhook_outcome or "none",
                )
                if prov_rec is not None:
                    prov_rec.delivery = {
                        "outcome": webhook_outcome or "none",
                        "channel": (
                            "delivery_channel"
                            if webhook_channel is not None
                            else "direct"
                        ),
                    }

            # ---- remediate: high-confidence attribution × burn ------
            # → ranked reversible action, then verify-or-rollback.
            if remediation_engine is not None:
                with tr.stage("remediate") as sp:
                    now_s = now.timestamp()
                    if attr is not None:
                        from tpuslo.remediation import (
                            AttributionContext,
                        )

                        ctx = AttributionContext(
                            incident_id=attr.incident_id,
                            domain=attr.predicted_fault_domain,
                            confidence=attr.confidence,
                            burn_state=burn_engine.policy.state_of(
                                tenant, "availability"
                            ),
                            burn_rate=burn_engine.max_active_burn(),
                            tenant=tenant,
                            node=args.node,
                            slice_id=cfg.tpu.slice_id,
                            at_s=now_s,
                        )
                        acted = remediation_engine.consider(
                            ctx, now_s, provenance=prov_rec
                        )
                        if acted is not None:
                            print(
                                "agent: remediation: "
                                f"{acted.kind} on {acted.target} "
                                f"[{acted.phase}] for "
                                f"{attr.incident_id} — {acted.detail}",
                                file=sys.stderr,
                            )

                    def _verify_burn(rec) -> float:
                        # Verify evidence: the fast-reacting 5m
                        # availability burn of the acted tenant.
                        watch = (
                            rec.target
                            if rec.kind == "demote_tenant"
                            else tenant
                        )
                        for stat in burn_engine.status():
                            if (
                                stat.tenant == watch
                                and stat.objective == "availability"
                            ):
                                return stat.burn_rates.get("5m", 0.0)
                        return 0.0

                    for settled in remediation_engine.tick(
                        now_s, _verify_burn
                    ):
                        print(
                            "agent: remediation: "
                            f"{settled.kind} on {settled.target} "
                            f"settled {settled.phase} after "
                            f"{settled.windows_seen} window(s)"
                            + (
                                " — ESCALATED"
                                if settled.escalated
                                else ""
                            ),
                            file=sys.stderr,
                        )
                    snap = remediation_engine.snapshot()
                    sp.set(
                        in_flight=snap["in_flight"],
                        applied=snap["applied"],
                        confirmed=snap["confirmed"],
                        rolled_back=snap["rolled_back"],
                    )

            # ---- snapshot: stats, overhead guard, durable state ----
            with tr.stage("snapshot") as sp:
                if (
                    args.stats_interval_cycles
                    and (idx + 1) % args.stats_interval_cycles == 0
                ):
                    _print_stats(gate, metrics, burn_engine)
                result = guard.evaluate()
                if result.valid:
                    metrics.cpu_overhead_pct.set(result.cpu_pct)
                    if result.over_budget:
                        recovery.note(result)  # breaks the streak
                        shed = generator.disable_highest_cost()
                        if shed:
                            print(
                                f"agent: overhead {result.cpu_pct:.2f}% > "
                                f"{max_overhead:.2f}%, disabled {shed}",
                                file=sys.stderr,
                            )
                            metrics.set_enabled_signals(
                                generator.enabled_signals()
                            )
                    elif recovery.note(result):
                        shed_list = generator.shed_signals()
                        candidate = shed_list[-1] if shed_list else None
                        if (
                            candidate is not None
                            and shed_ownership is not None
                            and not shed_ownership.may_restore(
                                candidate, "guard"
                            )
                        ):
                            # Ownership precedence: the recovery streak
                            # must not restore a probe the remediation
                            # engine shed — its verifier owns that
                            # lever until it confirms or rolls back.
                            print(
                                f"agent: restore of {candidate} held "
                                "(remediation-owned shed)",
                                file=sys.stderr,
                            )
                            restored = None
                        else:
                            restored = generator.restore_one()
                        if restored:
                            print(
                                f"agent: overhead {result.cpu_pct:.2f}% "
                                f"under budget for {recovery.cycles} "
                                f"cycles, re-enabled {restored}",
                                file=sys.stderr,
                            )
                            metrics.signals_restored.labels(
                                signal=restored
                            ).inc()
                            metrics.set_enabled_signals(
                                generator.enabled_signals()
                            )
                metrics.mark_cycle()
                # Progress advances only after the cycle's events hit
                # the writers: a crash replays from the last durable
                # cycle (at-least-once; the restored dedup digest
                # absorbs the overlap).
                progress["next_cycle"] = idx + 1
                snapshot_age = -1.0
                if runtime.enabled:
                    runtime.maybe_snapshot()
                    age = runtime.store.age_s()
                    if age != float("inf"):
                        metrics.runtime_snapshot_age_seconds.set(age)
                        snapshot_age = age
                sp.set(
                    snapshot_age_s=round(snapshot_age, 3),
                    breakers_open=sum(
                        1
                        for ch in _all_channels()
                        if ch.breaker.state == "open"
                    ),
                )

            # Provenance is finalized after the last stage CM closed,
            # so stages_ms covers the full cycle — deliver and snapshot
            # included (the two stages most likely to explain a slow
            # incident cycle).
            if prov_rec is not None:
                prov_rec.stages_ms = {
                    s.name: round(s.duration_ms, 4)
                    for s in getattr(tr, "spans", [])
                }
                if provenance_log is not None:
                    provenance_log.record(prov_rec)

    # Warm restore happens after every component registered its hooks;
    # ring-loop components (ProbeManager shed list, supervisor) apply
    # their restored sections at late registration inside the loop.
    restore_outcome = runtime.restore(cold_start=args.cold_start)
    if runtime.enabled:
        detail = ""
        if restore_outcome == "restored":
            detail = (
                f" (age {runtime.restored_age_s:.1f}s, components: "
                f"{','.join(runtime.restored_components) or 'none'})"
            )
        print(
            f"agent: runtime: snapshot {restore_outcome}{detail}; "
            f"resuming at cycle {progress['next_cycle']}",
            file=sys.stderr,
        )

    # Which gate the drain-path stats line reports: the row gate by
    # default, the columnar loop's vectorized gate when it builds one.
    stats_gate = gate

    def _run_columnar_loop() -> None:
        """Fleet-scale batch loop on the columnar spine.

        Each cycle expands ``--columnar-batch`` synthetic samples
        straight into a :class:`~tpuslo.columnar.ColumnarBatch`
        (per-sample trace identity preserved), pushes the batch through
        the vectorized gate (same admission semantics as the row gate,
        parity-tested), and serializes one JSONL block without
        per-event dicts.  Probe events only — the SLO/burn/webhook
        plumbing stays on the row loop, which this mode does not
        replace.
        """
        import numpy as np

        from tpuslo.columnar.gate import ColumnarGate
        from tpuslo.columnar.schema import (
            concat_batches,
            from_payloads,
            to_rows,
        )
        from tpuslo.columnar.serialize import serialize_jsonl
        from tpuslo.ingest import GateConfig as _GateConfig

        nonlocal stats_gate
        col_gate = None
        if cfg.ingest.enabled:
            col_gate = ColumnarGate(
                _GateConfig(
                    dedup_window=cfg.ingest.dedup_window,
                    watermark_lateness_ms=cfg.ingest.watermark_lateness_ms,
                    coordinator_host=cfg.ingest.coordinator_host,
                    min_skew_samples=cfg.ingest.min_skew_samples,
                    skew_correction=cfg.ingest.skew_correction,
                    quarantine_dir=cfg.ingest.quarantine_dir,
                    quarantine_max_bytes=cfg.ingest.quarantine_max_bytes,
                    quarantine_max_age_s=cfg.ingest.quarantine_max_age_s,
                )
            )
            stats_gate = col_gate
            print("agent: columnar ingest gate on", file=sys.stderr)
        batch_size = max(1, args.columnar_batch)
        probe_counter = metrics.probe_events
        stats_every = max(0, args.stats_interval_cycles)
        shipper = None
        live_client = None
        seq_journal = None
        pressure_path = None
        cadence = None
        shipment_seq = -1
        ship_errors = 0
        if args.fleet_upstream:
            import os as os_mod

            from tpuslo.fleet.wire import ShipmentWriter, encode_shipment
            from tpuslo.livenet import (
                ReconnectingClient,
                SeqJournal,
                ShipmentCadence,
                parse_socket_url,
                pressure_sidecar_path,
                read_pressure_file,
                resolve_resume_seq,
            )

            cadence = ShipmentCadence()
            # The seq journal + socket spool live wherever the agent
            # already keeps durable state; either dir works.
            journal_dir = spool_dir or state_dir
            try:
                live_address = parse_socket_url(args.fleet_upstream)
            except ValueError as exc:
                print(f"agent: {exc}", file=sys.stderr)
                return 2
            if live_address is not None:
                if not journal_dir:
                    # The socket hop has no local log to scan for seq
                    # resume and no file to spool into: without a
                    # durable dir a restart would reuse seqs, which
                    # the aggregator's dedup eats as silent loss.
                    print(
                        "agent: tcp:// fleet upstream needs "
                        "--spool-dir or --state-dir for the shipment "
                        "spool and seq journal",
                        file=sys.stderr,
                    )
                    return 2
                seq_journal = SeqJournal(
                    os_mod.path.join(journal_dir, "fleet-seq.json")
                )
                try:
                    live_client = ReconnectingClient(
                        live_address,
                        os_mod.path.join(journal_dir, "fleet-spool"),
                        peer="fleet",
                        observer=metrics.livenet_observer(),
                        log=lambda msg: print(
                            f"agent: {msg}", file=sys.stderr
                        ),
                    )
                except OSError as exc:
                    print(
                        f"agent: cannot open fleet spool under "
                        f"{journal_dir}: {exc}",
                        file=sys.stderr,
                    )
                    return 2
                shipment_seq = resolve_resume_seq(
                    args.node, journal=seq_journal
                )
            else:
                # Probe writability up front: a missing directory or
                # unwritable path should refuse at startup, not crash
                # the loop at the first gated batch.
                try:
                    with open(
                        args.fleet_upstream, "a", encoding="utf-8"
                    ):
                        pass
                except OSError as exc:
                    print(
                        "agent: cannot write fleet upstream "
                        f"{args.fleet_upstream}: {exc}",
                        file=sys.stderr,
                    )
                    return 2
                shipper = ShipmentWriter(args.fleet_upstream)
                if journal_dir:
                    # Maintained alongside the log so a later switch
                    # to the socket transport resumes from the same
                    # cursor (resolve_resume_seq takes the max).
                    seq_journal = SeqJournal(
                        os_mod.path.join(journal_dir, "fleet-seq.json")
                    )
                # The log appends across restarts and the aggregator
                # dedups on seq: resume the node's sequence, never
                # restart at 0.
                shipment_seq = resolve_resume_seq(
                    args.node,
                    upstream_log=args.fleet_upstream,
                    journal=seq_journal,
                )
                # The file hop's backpressure channel: fleetagg
                # --pressure-out mirrors its level into this sidecar.
                pressure_path = pressure_sidecar_path(
                    args.fleet_upstream
                )
            print(
                f"agent: fleet upstream -> {args.fleet_upstream} "
                f"(node {args.node}"
                + (f", slice {args.slice_id}" if args.slice_id else "")
                + (", live socket" if live_client is not None else "")
                + ")",
                file=sys.stderr,
            )
        def _ship_upstream(out) -> None:
            """One merged shipment over whichever transport is wired.

            Socket hop journals the seq BEFORE the send: a crash in
            between burns the seq (a harmless gap), never reuses one —
            reuse would be eaten by the aggregator's dedup as silent
            loss.  File hop journals AFTER the append (the log itself
            is the durable record there).
            """
            nonlocal shipment_seq, ship_errors
            shipment_seq += 1
            envelope = encode_shipment(
                out,
                args.node,
                shipment_seq,
                transport="base64",
                slice_id=args.slice_id,
            )
            try:
                if live_client is not None:
                    seq_journal.record(args.node, shipment_seq)
                    live_client.send(envelope)
                else:
                    shipper.send("fleet", [envelope])
                    if seq_journal is not None:
                        seq_journal.record(args.node, shipment_seq)
            except OSError as exc:
                # Disk-full / rotated-away mid-run: the local sinks
                # must still get this batch; the aggregator's seq gap
                # shows the loss.
                ship_errors += 1
                if ship_errors == 1:
                    print(
                        "agent: fleet upstream write failed "
                        f"({exc}); local sinks continue",
                        file=sys.stderr,
                    )

        # Sink capability is fixed for the process: local sinks take
        # pre-serialized blocks, OTLP exporters need typed records —
        # probe once instead of serializing a block per batch only to
        # learn it cannot be used.
        blocks_ok = writers.write_probe_block("")
        idx = 0
        emitted_total = 0
        pending_ship: list = []
        profiler_incidents = 0

        def _profiler_incident(window) -> None:
            """Eviction-carrying windows page like any kernel signal:
            attribute the window's device signals, attach the window's
            roofline verdict, and chain the whole capture into the
            incident's provenance."""
            nonlocal profiler_incidents
            if window.eviction_events <= 0 or profiler_attributor is None:
                return
            values = profiler.window_signal_values(window)
            posteriors = profiler_attributor.attribute(values)
            if not posteriors:
                return
            top = posteriors[0]
            profiler_incidents += 1
            incident_id = f"profiler-w{window.index}-{window.ts_unix_nano}"
            print(
                f"agent: profiler incident {incident_id}: "
                f"{top.domain} (confidence {top.posterior:.3f}), "
                f"idle gap {window.idle_gap_ms:.3f} ms, "
                f"{window.eviction_events} eviction(s)"
                + (
                    f", window verdict {window.verdict}"
                    if window.verdict
                    else ""
                ),
                file=sys.stderr,
            )
            if provenance_log is None:
                return
            from tpuslo.obs import EvidenceEvent, ProvenanceRecord
            from tpuslo.obs.provenance import probe_event_id

            rec = ProvenanceRecord(
                incident_id=incident_id,
                recorded_at=datetime.now(timezone.utc).isoformat(),
                cycle=idx,
                predicted_fault_domain=top.domain,
                confidence=top.posterior,
                posterior={
                    post.domain: post.posterior
                    for post in posteriors[:5]
                },
                events=[
                    EvidenceEvent(
                        event_id=probe_event_id(
                            name, window.ts_unix_nano
                        ),
                        signal=name,
                        value=value,
                        # The profiler's signals are born joined: the
                        # window's ledger fold IS the correlation, so
                        # the per-event confidence is the window's
                        # substantive join rate.
                        tier="profiler_window",
                        confidence=window.substantive_join_rate,
                    )
                    for name, value in values.items()
                ],
                correlation={
                    "matched": window.launches,
                    "total": window.launches,
                    "window_ms": round(window.window_ms, 3),
                    "best_tier": "identity",
                },
                profiler=window.to_dict(),
            )
            if window.verdict:
                rec.roofline = profiler.window_roofline(
                    window.index
                ) or {
                    "verdict": window.verdict,
                    "mfu_pct": window.mfu_pct,
                    "detail": window.verdict_detail,
                }
            provenance_log.record(rec)
        try:
            while not args.count or idx < args.count:
                now = datetime.now(timezone.utc)
                samples = [
                    build_synthetic_sample(
                        args.scenario,
                        idx * batch_size + j,
                        now + timedelta(microseconds=j),
                        sample_meta,
                    )
                    for j in range(batch_size)
                ]
                batch = generator.generate_batch_columnar(
                    samples,
                    Metadata(),
                    trace_ids=[s.trace_id for s in samples],
                )
                if col_gate is not None:
                    result = col_gate.admit_batch(batch)
                    outgoing = [result.admitted, result.late]
                else:
                    outgoing = [batch]
                if profiler is not None:
                    # On-chip truth rides the same spine as every
                    # kernel signal: the window's probe payloads go
                    # through the identical gate admission and writer
                    # path as the synthetic batch above.
                    window = profiler.tick()
                    if window is not None:
                        pbatch, rejects = from_payloads(
                            profiler.probe_payloads(window)
                        )
                        if rejects:
                            # A contract-invalid payload here is a
                            # profiler bug, not bad data — surface it.
                            print(
                                "agent: profiler window "
                                f"#{window.index} produced "
                                f"{len(rejects)} contract-invalid "
                                "probe payload(s); dropped",
                                file=sys.stderr,
                            )
                        if len(pbatch):
                            if col_gate is not None:
                                presult = col_gate.admit_batch(pbatch)
                                outgoing.extend(
                                    [presult.admitted, presult.late]
                                )
                            else:
                                outgoing.append(pbatch)
                        _profiler_incident(window)
                for out in outgoing:
                    if not len(out):
                        continue
                    emitted_total += len(out)
                    if shipper is not None or live_client is not None:
                        # Local sinks get every batch immediately;
                        # the upstream flush is cadence-gated below.
                        pending_ship.append(out)
                    if blocks_ok:
                        writers.write_probe_block(
                            serialize_jsonl(out, kind="probe")
                        )
                    else:
                        # OTLP sinks need typed records: adapter
                        # boundary, row objects only here.
                        writers.emit_probe(to_rows(out))
                    codes, counts = np.unique(
                        out.column("signal"), return_counts=True
                    )
                    strings = out.pool.strings
                    for code, count in zip(
                        codes.tolist(), counts.tolist()
                    ):
                        probe_counter.labels(
                            signal=strings[code]
                        ).inc(count)
                if shipper is not None or live_client is not None:
                    # Fold the freshest upstream pressure level, then
                    # ask the cadence whether this cycle flushes.  At
                    # level 0 this is today's behavior bit-for-bit
                    # (every cycle ships); at level >= 1 consecutive
                    # cycles merge into one coarser shipment.
                    if live_client is not None:
                        cadence.observe(
                            live_client.pressure_level
                            if live_client.pressure_level >= 0
                            else None
                        )
                    else:
                        sig = read_pressure_file(pressure_path)
                        cadence.observe(
                            sig.level if sig is not None else None
                        )
                    if cadence.should_flush() and pending_ship:
                        merged = (
                            pending_ship[0]
                            if len(pending_ship) == 1
                            else concat_batches(pending_ship)
                        )
                        pending_ship = []
                        _ship_upstream(merged)
                idx += 1
                if stats_every and idx % stats_every == 0:
                    _print_stats(col_gate, metrics)
                if args.count and idx >= args.count:
                    break
                if args.interval_s > 0:
                    time.sleep(args.interval_s)
        finally:
            print(
                f"agent: columnar loop: {idx} cycles, "
                f"{emitted_total} probe events emitted",
                file=sys.stderr,
            )
            if profiler is not None:
                pstats = profiler.stats()
                print(
                    "agent: profiler: "
                    f"{pstats['windows_captured']} window(s) "
                    f"({pstats['windows_forced']} forced, "
                    f"{pstats['eviction_windows']} with evictions), "
                    f"{pstats['degradations']} degradation(s), "
                    f"{pstats['reengagements']} reengagement(s), "
                    f"overhead EMA {pstats['overhead_ema_pct']:.4f}% "
                    f"of {pstats['overhead_budget_pct']:g}% budget, "
                    f"{profiler_incidents} incident(s)",
                    file=sys.stderr,
                )
            if pending_ship:
                # Held batches must not die with the loop: the final
                # flush ignores the cadence stride.
                merged = (
                    pending_ship[0]
                    if len(pending_ship) == 1
                    else concat_batches(pending_ship)
                )
                pending_ship = []
                _ship_upstream(merged)
            if cadence is not None and (
                shipper is not None or live_client is not None
            ):
                cstats = cadence.stats()
                print(
                    "agent: fleet cadence: "
                    f"cycles={cstats['cycles']} "
                    f"flushes={cstats['flushes']} "
                    f"coarsened={cstats['coarsened_cycles']} "
                    f"max_level={cstats['max_level_seen']}",
                    file=sys.stderr,
                )
            if shipper is not None:
                print(
                    f"agent: fleet upstream: {shipper.shipments} "
                    f"shipments, {shipper.events} events"
                    + (
                        f", {ship_errors} failed writes"
                        if ship_errors
                        else ""
                    ),
                    file=sys.stderr,
                )
                shipper.close()
            if live_client is not None:
                print(
                    "agent: fleet upstream: "
                    f"{live_client.sent_frames} sent, "
                    f"{live_client.spooled_frames} spooled, "
                    f"{live_client.replayed_frames} replayed, "
                    f"{live_client.reconnects} reconnects, "
                    f"{live_client.pending_spooled()} pending"
                    + (
                        f", {ship_errors} failed writes"
                        if ship_errors
                        else ""
                    ),
                    file=sys.stderr,
                )
                live_client.close()
            if col_gate is not None:
                col_gate.close()

    from tpuslo.runtime import (
        DrainController,
        DrainSignal,
        install_drain_handler,
    )

    # SIGTERM takes exactly the KeyboardInterrupt path: one drain
    # sequence for Ctrl-C and for a Kubernetes pod termination.
    restore_handlers = install_drain_handler()
    drain_timeout = args.drain_timeout_s or cfg.runtime.drain_timeout_s
    drain_reason = "completed"
    try:
        if args.probe_source == "ring":
            _run_ring_loop(
                args, cfg, mode, signal_set, enricher, writers, metrics,
                limiter, guard, recovery, ici_prober=ici_prober, gate=gate,
                runtime=runtime, runtime_observer=runtime_observer,
                self_tracer=tracer,
            )
        elif args.columnar:
            _run_columnar_loop()
        else:
            idx = progress["next_cycle"]
            while not args.count or idx < args.count:
                emit_one(idx)
                idx += 1
                if args.count and idx >= args.count:
                    break
                time.sleep(args.interval_s)
    except KeyboardInterrupt:
        drain_reason = "sigint"
    except DrainSignal as sig:
        drain_reason = f"signal_{sig.signum}"
    finally:
        restore_handlers()
        readiness_state["draining"] = True  # /readyz flips to 503 first
        drain = DrainController(
            drain_reason,
            deadline_s=drain_timeout,
            log=lambda msg: print(f"agent: {msg}", file=sys.stderr),
        )
        metrics.up.set(0)
        _print_stats(stats_gate, metrics, burn_engine)
        if chaos_stream is not None:
            print(
                f"agent: chaos-telemetry: {chaos_stream.snapshot()}",
                file=sys.stderr,
            )
        if obs_enabled:
            snap = dict(tracer.snapshot())
            if trace_poster is not None:
                snap["direct_poster"] = dict(trace_poster.stats)
            print(f"agent: self-trace: {snap}", file=sys.stderr)
        # Generation stopped above; now push queued batches out (or to
        # the spool), snapshot, and release sinks — all on one deadline.
        if webhook_channel is not None:
            drain.step(
                "flush_webhook",
                lambda budget: webhook_channel.close(
                    flush_timeout_s=budget
                ),
            )
        drain.step(
            "flush_writers",
            lambda budget: writers.close(flush_timeout_s=budget),
        )
        if trace_channel is not None:
            drain.step(
                "flush_traces",
                lambda budget: trace_channel.close(flush_timeout_s=budget),
            )
        if trace_poster is not None:
            drain.step(
                "flush_traces",
                lambda budget: trace_poster.close(timeout_s=budget),
            )
        if provenance_log is not None:
            drain.step(
                "close_provenance", lambda budget: provenance_log.close()
            )
        if runtime.enabled:
            drain.step(
                "final_snapshot", lambda budget: runtime.snapshot_now()
            )
        if gate is not None:
            drain.step("close_gate", lambda budget: gate.close())
        report = drain.finish()
        runtime_observer.drain(report.outcome, report.duration_s)
        print(f"agent: drain: {report.summary()}", file=sys.stderr)
        for channel in (
            writers.delivery_channels
            + ([webhook_channel] if webhook_channel else [])
            + ([trace_channel] if trace_channel else [])
        ):
            snap = channel.snapshot()
            print(
                "agent: delivery[{sink}]: delivered={delivered_events} "
                "spooled={spooled_events} replayed={replayed_events} "
                "dead_lettered={dead_lettered_events} retries={retries} "
                "breaker={breaker} spool_bytes={spool_bytes}".format(**snap),
                file=sys.stderr,
            )
        if chaos_server is not None:
            chaos_server.stop()
        if server is not None:
            server.shutdown()
    return 0


def _run_ring_loop(
    args, cfg, mode, signal_set, enricher, writers, metrics, limiter, guard,
    recovery, ici_prober=None, gate=None, runtime=None,
    runtime_observer=None, self_tracer=None,
) -> None:
    """The real-probe path: ringbuf → normalize → schema → emit.

    This is the loop the reference scaffolded but never closed (its
    RingBufConsumer/ProbeManager have no caller outside tests —
    SURVEY.md §0).  Degradation is graceful and reported: no libbpf or
    no privileges → the kernel surface is skipped but userspace rings
    (BCC fallback, injectors, hello tracer, HBM sampler) still flow.
    """
    import os
    import tempfile

    from tpuslo.collector.hbm_sampler import HBMSampler
    from tpuslo.collector.hello_tracer import HelloTracer
    from tpuslo.collector.probe_manager import ProbeManager
    from tpuslo.collector.ringbuf import RingBufConsumer, to_probe_event
    from tpuslo.signals import constants as sigconst

    pm = ProbeManager(guard=guard)
    report = pm.attach_all(signal_set)
    attached = report.attached_signals
    print(
        f"agent: ring mode, {len(attached)}/{len(signal_set)} signals "
        f"attached ({mode})",
        file=sys.stderr,
    )
    for r in report.results:
        if not r.attached:
            print(
                f"agent:   {r.signal}: {r.status} {r.detail}".rstrip(),
                file=sys.stderr,
            )
    metrics.set_enabled_signals(attached)

    consumer = RingBufConsumer(
        steal_window_ms=1000,
        batch=cfg.sampling.burst_limit or 256,
    )
    known_fds: set[int] = set()
    for fd in pm.ringbuf_fds():
        consumer.add_kernel_ringbuf(fd)
        known_fds.add(fd)

    def _sync_ring_fds() -> None:
        """Re-register new ring fds; forget fds closed by a detach."""
        nonlocal known_fds
        current = set(pm.ringbuf_fds())
        for fd in current - known_fds:
            try:
                consumer.add_kernel_ringbuf(fd)
                known_fds.add(fd)
            except Exception as exc:  # noqa: BLE001
                print(f"agent: ring re-add failed: {exc}", file=sys.stderr)
        known_fds &= current

    # ---- probe supervision (tpuslo.runtime.ProbeSupervisor) ----------
    from tpuslo.runtime import (
        ProbeSupervisor,
        RuntimeObserver,
        SupervisorConfig,
    )

    if runtime_observer is None:
        runtime_observer = RuntimeObserver()

    def _restart_probe(signal: str) -> bool:
        pm.detach_signal(signal)
        restarted = signal in pm.attach_all([signal]).attached_signals
        _sync_ring_fds()
        return restarted

    def _flap_shed(signal: str, reason: str) -> None:
        # Route through the shed list so restore_one can bring the
        # signal back (reverse cost order) once the hold-down expires.
        pm.import_shed([signal])
        _sync_ring_fds()
        metrics.set_enabled_signals(pm.attached_signals)
        runtime_observer.flap_shed(signal)

    supervisor = ProbeSupervisor(
        config=SupervisorConfig(
            heartbeat_timeout_s=cfg.runtime.supervisor_heartbeat_timeout_s,
            flap_restarts=cfg.runtime.supervisor_flap_restarts,
            flap_window_s=cfg.runtime.supervisor_flap_window_s,
            flap_holddown_s=cfg.runtime.supervisor_flap_holddown_s,
        ),
        restart=_restart_probe,
        shed=_flap_shed,
        log=lambda msg: print(f"agent: {msg}", file=sys.stderr),
    )
    supervisor.watch(attached)
    if runtime is not None:
        # Late registration: a restored "supervisor"/"pm_shed" section
        # pending from main's restore pass applies here.
        runtime.register(
            "supervisor", supervisor.export_state, supervisor.restore_state
        )
        runtime.register(
            "pm_shed",
            lambda: {"signals": pm.shed_signals},
            lambda s: pm.import_shed(list(s.get("signals") or [])),
        )
        metrics.set_enabled_signals(pm.attached_signals)

    # Userspace side-channel ring: hello tracer + HBM sampler share it,
    # plus whatever external producer --ring-path points at.
    tracer = None
    sampler = None
    side_ring = args.ring_path
    side_ring_owned = False
    if not side_ring and (args.hello or sigconst.SIGNAL_HBM_UTILIZATION_PCT
                          in signal_set):
        # mkstemp (not the race-prone, deprecated mktemp): the path is
        # created 0600 and owned by us; the ring producer re-initializes
        # it in place (O_TRUNC) before the consumer maps it.
        fd, side_ring = tempfile.mkstemp(
            prefix="tpuslo-ring-", suffix=".buf"
        )
        os.close(fd)
        side_ring_owned = True
    if args.hello and side_ring:
        tracer = HelloTracer(side_ring, interval_s=5.0)
        tracer.start()
    if side_ring and sigconst.SIGNAL_HBM_UTILIZATION_PCT in signal_set:
        try:
            sampler = HBMSampler(side_ring)
        except Exception:  # noqa: BLE001 — sampler is best-effort
            sampler = None
    if side_ring:
        try:
            consumer.add_userspace_ring(side_ring)
        except Exception as exc:  # noqa: BLE001
            print(f"agent: side ring attach failed: {exc}", file=sys.stderr)

    meta_template = Metadata(
        node=args.node,
        namespace=args.namespace,
        pod=f"{args.workload}-agent",
        container=args.workload,
        pid=1,
        tid=1,
        tpu_chip=args.tpu_chip,
        slice_id=args.slice_id,
        host_index=args.host_index,
        xla_program_id=args.xla_program_id,
    )

    if args.event_kind == "slo":
        print(
            "agent: ring mode emits probe events only "
            "(SLO events come from the observed workload)",
            file=sys.stderr,
        )

    def emit_probe_event(event) -> None:
        if not limiter.allow():
            metrics.dropped.labels(reason="rate_limit").inc()
            return
        if gate is not None:
            # Real-probe events are exactly the skewed/duplicated/
            # corrupt surface the gate exists for; late events are
            # still emitted (downstream consumers run the re-match).
            from tpuslo.ingest import ADMITTED, LATE
            from tpuslo.schema import ProbeEventV1

            payload = event.to_dict()
            outcome, gated = gate.admit(payload)
            if outcome not in (ADMITTED, LATE):
                return
            if gated is not payload:
                # The gate copies only when it skew-corrected the
                # timestamp; everything else keeps the typed event.
                try:
                    event = ProbeEventV1.from_dict(gated)
                except (TypeError, ValueError, KeyError):
                    metrics.dropped.labels(reason="malformed").inc()
                    return
        if not validate_probe(event):
            metrics.dropped.labels(reason="schema").inc()
            return
        try:
            writers.emit_probe([event])
            metrics.observe_probe(event.signal, event.value)
        except Exception as exc:  # noqa: BLE001
            metrics.dropped.labels(reason="emit").inc()
            print(f"agent: probe emit failed: {exc}", file=sys.stderr)

    if self_tracer is None:
        from tpuslo.obs import SelfTracer, TracerConfig

        self_tracer = SelfTracer(TracerConfig(enabled=False))

    cycles = 0
    try:
        while True:
            # Ring cycles get a shallower span tree than the synthetic
            # loop (gate/validate/deliver happen per-event inside the
            # consumer drain), but the same root span + tail sampling.
            with self_tracer.cycle(
                "agent.cycle", cycle=cycles, loop="ring"
            ) as tr:
                with tr.stage("generate") as sp:
                    if sampler is not None:
                        sampler.sample_once()
                    polled = list(
                        consumer.poll(
                            timeout_ms=int(args.interval_s * 500)
                        )
                    )
                    sp.set(samples=len(polled))
                with tr.stage("deliver") as sp:
                    emitted_n = 0
                    for sample in polled:
                        supervisor.beat(sample.signal)
                        event = to_probe_event(
                            sample, meta_template, enricher
                        )
                        if event is None:
                            if sample.signal == "hello_heartbeat_total":
                                metrics.mark_cycle()
                            continue
                        emit_probe_event(event)
                        emitted_n += 1
                    if ici_prober is not None:
                        # Active interconnect probe rides the same emit
                        # path as kernel-ring events.
                        for event in ici_prober.maybe_probe(
                            time.monotonic()
                        ):
                            emit_probe_event(event)
                            emitted_n += 1
                    sp.set(events=emitted_n)

                with tr.stage("supervise") as sp:
                    restarts = 0
                    for action in supervisor.evaluate():
                        if action.action == "restarted":
                            restarts += 1
                            runtime_observer.probe_restarted(action.signal)
                        print(
                            f"agent: supervisor: {action.signal} "
                            f"{action.action} {action.detail}".rstrip(),
                            file=sys.stderr,
                        )
                    sp.set(restarts=restarts)

                with tr.stage("guard") as sp:
                    result = guard.evaluate()
                    if result.valid:
                        metrics.cpu_overhead_pct.set(result.cpu_pct)
                        sp.set(cpu_pct=round(result.cpu_pct, 3))
                        if result.over_budget:
                            recovery.note(result)  # breaks the streak
                            shed = pm.shed_highest_cost()
                            if shed:
                                print(
                                    f"agent: overhead "
                                    f"{result.cpu_pct:.2f}%, "
                                    f"detached {shed}",
                                    file=sys.stderr,
                                )
                                supervisor.forget(shed)
                                metrics.set_enabled_signals(
                                    pm.attached_signals
                                )
                                # Detach closed that object's ring fd;
                                # forget it so a restored probe reusing
                                # the fd number re-registers.
                                known_fds &= set(pm.ringbuf_fds())
                        elif recovery.note(result):
                            shed_list = pm.shed_signals
                            candidate = (
                                shed_list[-1] if shed_list else None
                            )
                            if (
                                candidate is not None
                                and not supervisor.may_restore(candidate)
                            ):
                                # Flap hold-down outranks the overhead-
                                # guard recovery streak: quiet CPU
                                # cycles say nothing about why the
                                # supervisor shed a flapping probe.
                                print(
                                    f"agent: restore of {candidate} "
                                    "held down (flapping)",
                                    file=sys.stderr,
                                )
                                restored = None
                            else:
                                restored = pm.restore_one()
                            if restored:
                                print(
                                    f"agent: overhead "
                                    f"{result.cpu_pct:.2f}% under "
                                    f"budget for {recovery.cycles} "
                                    f"cycles, re-attached {restored}",
                                    file=sys.stderr,
                                )
                                supervisor.note_restored(restored)
                                metrics.signals_restored.labels(
                                    signal=restored
                                ).inc()
                                metrics.set_enabled_signals(
                                    pm.attached_signals
                                )
                                _sync_ring_fds()

                with tr.stage("snapshot") as sp:
                    metrics.mark_cycle()
                    if runtime is not None and runtime.enabled:
                        runtime.maybe_snapshot()
                        age = runtime.store.age_s()
                        if age != float("inf"):
                            # Kept current even across failed saves:
                            # the staleness alert must fire then.
                            metrics.runtime_snapshot_age_seconds.set(age)
            cycles += 1
            if (
                args.stats_interval_cycles
                and cycles % args.stats_interval_cycles == 0
            ):
                _print_stats(gate, metrics)
            if args.count and cycles >= args.count:
                break
            time.sleep(args.interval_s)
    finally:
        if tracer is not None:
            tracer.stop()
        if sampler is not None:
            sampler.close()
        consumer.close()
        pm.detach_all()
        if side_ring_owned:
            try:
                os.unlink(side_ring)
            except OSError:
                pass


if __name__ == "__main__":
    raise SystemExit(main())
