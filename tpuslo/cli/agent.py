"""Node agent: the toolkit's ``serve()`` loop.

Reference: ``cmd/agent/main.go`` — synthetic scenario → SLO + probe
events → stdout/jsonl/OTLP, Prometheus metrics server on :2112,
overhead-guard probe shedding, rate limiting with drop accounting,
optional webhook attribution, ``--probe-smoke`` privilege check.

The real-probe path swaps in behind ``--probe-source ring`` once the
native loader is present (closing the reference's biggest gap: its
ring-buffer consumer is never wired into the agent loop — SURVEY.md §0).
"""

from __future__ import annotations

import argparse
import sys
import time

from tpuslo import attribution, webhook
from tpuslo.cli.common import EventWriters, resolve_config, validate_probe, validate_slo
from tpuslo.collector import (
    SampleMeta,
    build_synthetic_sample,
    normalize_sample,
    supported_synthetic_scenarios,
)
from tpuslo.collector.kernel import probe_smoke_check
from tpuslo.delivery import DeliveryOptions
from tpuslo.metrics import AgentMetrics, start_metrics_server
from tpuslo.safety import OverheadGuard, RateLimiter, ShedRecoveryPolicy
from tpuslo.signals import (
    Generator,
    Metadata,
    StaticMetadataEnricher,
    TPUMetadataEnricher,
    parse_capability_mode,
    profile_for_fault,
)
from datetime import datetime, timezone


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpuslo agent", description=__doc__)
    p.add_argument("--config", default="", help="toolkit.yaml path")
    p.add_argument(
        "--scenario",
        default="baseline",
        choices=supported_synthetic_scenarios(),
    )
    p.add_argument("--interval-s", type=float, default=1.0)
    p.add_argument("--count", type=int, default=0, help="0 = run forever")
    p.add_argument("--event-kind", default="both", choices=["slo", "probe", "both"])
    p.add_argument("--output", default="stdout", choices=["stdout", "jsonl", "otlp"])
    p.add_argument("--jsonl-path", default="")
    p.add_argument("--otlp-endpoint", default="")
    p.add_argument("--capability-mode", default="auto")
    p.add_argument("--signal-set", default="", help="comma-separated override")
    p.add_argument("--metrics-port", type=int, default=2112, help="0 disables")
    p.add_argument("--max-overhead-pct", type=float, default=0.0)
    p.add_argument("--events-per-second", type=int, default=0)
    p.add_argument("--webhook-url", default="")
    p.add_argument("--webhook-secret", default="")
    p.add_argument("--webhook-format", default="")
    p.add_argument("--cluster", default="tpu-cluster")
    p.add_argument("--namespace", default="llm")
    p.add_argument("--workload", default="rag-service")
    p.add_argument("--service", default="rag-service")
    p.add_argument("--node", default="tpu-vm-0")
    p.add_argument("--probe-smoke", action="store_true")
    # Multi-host identity for the ring loop's TPU events: a DaemonSet
    # agent knows which slice/host it runs on; SliceJoiner joins
    # per-host streams on exactly this identity.
    p.add_argument("--slice-id", default="", help="TPU slice identity")
    p.add_argument(
        "--host-index", type=int, default=0,
        help="this host's index within the slice",
    )
    p.add_argument(
        "--xla-program-id", default="",
        help="program identity stamped on collective probe events",
    )
    p.add_argument("--tpu-chip", default="accel0")
    p.add_argument(
        "--probe-source",
        default="synthetic",
        choices=["synthetic", "ring"],
        help="ring = consume the native eBPF ring buffer",
    )
    p.add_argument(
        "--ring-path",
        default="",
        help="extra userspace ring to consume (injectors/fallback); "
        "ring mode only",
    )
    p.add_argument(
        "--hello",
        action="store_true",
        help="emit hello heartbeat events through the ring (e2e evidence)",
    )
    p.add_argument(
        "--spool-dir",
        default="",
        help="enable resilient delivery: batches that cannot reach a "
        "network sink are spooled here and replayed on recovery "
        "(config: delivery.spool_dir)",
    )
    p.add_argument(
        "--restore-after-cycles",
        type=int,
        default=0,
        help="re-enable one shed probe signal after this many "
        "consecutive under-budget guard cycles "
        "(0 = config delivery.restore_after_cycles)",
    )
    p.add_argument(
        "--chaos-sink",
        default="",
        metavar="SCHEDULE",
        help="start an in-process fault-injection OTLP sink and point "
        "the exporters at it; SCHEDULE is behavior[:count],... with "
        "behaviors ok|refuse|5xx|4xx|hang|flap (e.g. 'ok:3,refuse:6,ok') "
        "— demo/chaos harness, implies --output otlp",
    )
    p.add_argument(
        "--ici-probe-interval-s",
        type=float,
        default=0.0,
        help="run the active ICI collective prober every N seconds "
        "(0 disables; needs exclusive device access — the chip must "
        "not be held by a serving workload)",
    )
    p.add_argument("--ici-probe-payload-kb", type=int, default=256)
    p.add_argument(
        "--chaos-telemetry",
        type=float,
        default=0.0,
        metavar="INTENSITY",
        help="perturb the probe stream at the source with seeded skew/"
        "reorder/dup/corrupt/drop chaos (1.0 = moderate: skew<=250ms, "
        "5%% dup, 5%% reorder, 1%% corrupt); pairs with the ingest "
        "gate (config ingest:) to rehearse telemetry-quality incidents",
    )
    p.add_argument("--chaos-telemetry-seed", type=int, default=1337)
    p.add_argument(
        "--stats-interval-cycles",
        type=int,
        default=30,
        help="emit a periodic stats line (drops, rejections by reason, "
        "gate counters) every N cycles; 0 disables",
    )
    p.add_argument(
        "--state-dir",
        default="",
        help="enable the crash-safe runtime: periodic atomic snapshots "
        "of agent state (watermark, skew, dedup digest, breaker/shed "
        "state, limiter budget) land here and are restored on restart "
        "(config: runtime.state_dir)",
    )
    p.add_argument(
        "--snapshot-interval-s",
        type=float,
        default=-1.0,
        help="seconds between periodic snapshots; 0 = every cycle, "
        "-1 = config runtime.snapshot_interval_s",
    )
    p.add_argument(
        "--cold-start",
        action="store_true",
        help="ignore any on-disk snapshot and start cold (operator "
        "escape hatch for a poisoned snapshot)",
    )
    p.add_argument(
        "--drain-timeout-s",
        type=float,
        default=0.0,
        help="deadline for the graceful SIGTERM/SIGINT drain sequence "
        "(0 = config runtime.drain_timeout_s)",
    )
    return p


def _gate_pipeline(events, chaos_stream, gate, metrics):
    """Dict-level chaos + ingest-gate pass over generated probe events.

    Chaos perturbs what the "wire" carries; the gate re-admits it.
    Events the gate quarantined/deduplicated never come back; a
    payload the gate passed through untouched keeps its original
    typed event (no lossy rebuild on the gate-only hot path — both
    chaos and the gate copy on write, so dict identity is the
    "untouched" proof).  A rebuild failure (corrupt event with no
    gate to stop it) is an accounted drop, never a crash.
    """
    from tpuslo.schema import ProbeEventV1

    pairs = [(event, event.to_dict()) for event in events]
    original_by_payload = {id(payload): event for event, payload in pairs}
    payloads = [payload for _, payload in pairs]
    if chaos_stream is not None:
        payloads = list(chaos_stream.stream(payloads))
    if gate is not None:
        payloads = gate.admit_all(payloads).all_events()
    out = []
    for payload in payloads:
        original = original_by_payload.get(id(payload))
        if original is not None:
            out.append(original)
            continue
        try:
            out.append(ProbeEventV1.from_dict(payload))
        except (TypeError, ValueError, KeyError):
            metrics.dropped.labels(reason="malformed").inc()
    return out


def _print_stats(gate) -> None:
    """Periodic stats line: every silent drop, made loud."""
    from tpuslo.metrics import REJECTION_COUNTERS, VALIDATION_COUNTERS

    parts = [f"validation={VALIDATION_COUNTERS.snapshot()}"]
    rejections = REJECTION_COUNTERS.snapshot()
    if rejections:
        parts.append(f"rejections={rejections}")
    if gate is not None:
        parts.append(f"gate={gate.snapshot()}")
    print("agent: stats: " + " ".join(parts), file=sys.stderr)


def main(
    argv: list[str] | None = None, metrics: AgentMetrics | None = None
) -> int:
    args = build_parser().parse_args(argv)

    if args.probe_smoke:
        result = probe_smoke_check()
        print(f"probe-smoke: {'PASS' if result.ok else 'FAIL'}: {result.detail}")
        return 0 if result.ok else 1

    cfg = resolve_config(args.config)
    mode = parse_capability_mode(args.capability_mode)
    signal_set = (
        [s.strip() for s in args.signal_set.split(",") if s.strip()]
        if args.signal_set
        else cfg.signal_set
    )
    max_overhead = args.max_overhead_pct or cfg.safety.max_overhead_pct
    eps = args.events_per_second or cfg.sampling.events_per_second_limit

    chaos_server = None
    otlp_endpoint = args.otlp_endpoint or cfg.otlp.endpoint
    if args.chaos_sink:
        from tpuslo.delivery.faultsink import FaultInjectingHTTPServer

        chaos_server = FaultInjectingHTTPServer(args.chaos_sink).start()
        otlp_endpoint = chaos_server.endpoint
        if args.output != "otlp":
            print(
                "agent: --chaos-sink implies --output otlp", file=sys.stderr
            )
            args.output = "otlp"
        print(f"agent: chaos sink on {otlp_endpoint}", file=sys.stderr)

    spool_dir = args.spool_dir or cfg.delivery.spool_dir
    delivery_opts = (
        DeliveryOptions.from_config(cfg.delivery, spool_dir=spool_dir)
        if spool_dir
        else None
    )

    metrics = metrics or AgentMetrics()

    chaos_stream = None
    if args.chaos_telemetry > 0 and args.probe_source == "ring":
        # Ring events arrive one at a time from the kernel; the chaos
        # stream's reorder/dup buffering only makes sense on the
        # synthetic batch loop.  Refusing loudly beats a banner that
        # implies a drill which never runs.
        print(
            "agent: --chaos-telemetry applies to the synthetic loop "
            "only; ignored with --probe-source ring",
            file=sys.stderr,
        )
    elif args.chaos_telemetry > 0:
        from tpuslo.chaos.telemetry import ChaosScenario, ChaosStream

        chaos_stream = ChaosStream(
            ChaosScenario.at_intensity(
                args.chaos_telemetry, seed=args.chaos_telemetry_seed
            )
        )
        print(
            f"agent: telemetry chaos at intensity "
            f"{args.chaos_telemetry:g} (seed {args.chaos_telemetry_seed})",
            file=sys.stderr,
        )

    gate = None
    if cfg.ingest.enabled:
        # Always-on once configured: the gate is the admission point
        # for everything the agent emits downstream.
        from tpuslo.ingest import GateConfig, TelemetryGate

        gate = TelemetryGate(
            GateConfig(
                dedup_window=cfg.ingest.dedup_window,
                watermark_lateness_ms=cfg.ingest.watermark_lateness_ms,
                coordinator_host=cfg.ingest.coordinator_host,
                min_skew_samples=cfg.ingest.min_skew_samples,
                skew_correction=cfg.ingest.skew_correction,
                quarantine_dir=cfg.ingest.quarantine_dir,
                quarantine_max_bytes=cfg.ingest.quarantine_max_bytes,
                quarantine_max_age_s=cfg.ingest.quarantine_max_age_s,
            ),
            observer=metrics.ingest_observer(),
        )
        print(
            "agent: ingest gate on"
            + (
                f" (quarantine: {cfg.ingest.quarantine_dir})"
                if cfg.ingest.quarantine_dir
                else ""
            ),
            file=sys.stderr,
        )

    # ---- crash-safe runtime: durable snapshots + warm restore --------
    from tpuslo.runtime import AgentRuntime, StateStore

    runtime_observer = metrics.runtime_observer()
    state_dir = args.state_dir or cfg.runtime.state_dir
    store = None
    if state_dir:
        snapshot_interval = (
            args.snapshot_interval_s
            if args.snapshot_interval_s >= 0
            else cfg.runtime.snapshot_interval_s
        )
        import os as os_mod

        store = StateStore(
            os_mod.path.join(state_dir, "agent-state.json"),
            interval_s=snapshot_interval,
            max_age_s=cfg.runtime.snapshot_max_age_s,
            observer=runtime_observer,
        )
    runtime = AgentRuntime(
        store,
        observer=runtime_observer,
        log=lambda msg: print(f"agent: {msg}", file=sys.stderr),
    )
    # Loop progress: the synthetic loop resumes at next_cycle instead
    # of re-emitting from zero; alert_cycle is the webhook high-water
    # mark (alerts are at-most-once across restarts).
    progress = {"next_cycle": 0, "alert_cycle": -1}
    runtime.register(
        "progress",
        lambda: dict(progress),
        lambda s: progress.update(
            next_cycle=int(s.get("next_cycle", 0)),
            alert_cycle=int(s.get("alert_cycle", -1)),
        ),
    )
    if gate is not None:
        runtime.register("gate", gate.export_state, gate.restore_state)

    meta_template = Metadata(
        node=args.node,
        namespace=args.namespace,
        pod=f"{args.workload}-agent",
        container=args.workload,
        pid=1,
        tid=1,
        slice_id=cfg.tpu.slice_id,
        host_index=cfg.tpu.host_index,
    )
    enricher = StaticMetadataEnricher(
        TPUMetadataEnricher(dev_glob=cfg.tpu.accel_device_glob).enrich(meta_template)
    )
    generator = Generator(mode, signal_set, enricher=enricher)

    writers = EventWriters(
        output=args.output,
        jsonl_path=args.jsonl_path,
        otlp_endpoint=otlp_endpoint,
        delivery=delivery_opts,
        observer_factory=metrics.delivery_observer,
    )

    metrics.up.set(1)
    metrics.capability_mode.labels(mode=mode).set(1)
    metrics.event_kind.labels(kind=args.event_kind).set(1)
    metrics.set_enabled_signals(generator.enabled_signals())
    server = None
    if args.metrics_port:
        server = start_metrics_server(metrics, args.metrics_port)
        print(f"agent: metrics on :{args.metrics_port}/metrics", file=sys.stderr)

    limiter = RateLimiter(eps, cfg.sampling.burst_limit)
    guard = OverheadGuard(max_overhead)
    recovery = ShedRecoveryPolicy(
        cycles=args.restore_after_cycles or cfg.delivery.restore_after_cycles
    )
    runtime.register(
        "limiter", limiter.export_state, limiter.restore_state
    )
    runtime.register(
        "generator_shed",
        lambda: {"signals": generator.shed_signals()},
        lambda s: generator.import_shed(list(s.get("signals") or [])),
    )

    webhook_url = args.webhook_url or (cfg.webhook.url if cfg.webhook.enabled else "")
    hook = None
    attributor = None
    webhook_channel = None
    if webhook_url:
        hook = webhook.Exporter(
            webhook_url,
            secret=args.webhook_secret or cfg.webhook.secret,
            format=args.webhook_format or cfg.webhook.format,
            timeout_ms=cfg.webhook.timeout_ms,
        )
        attributor = attribution.BayesianAttributor()
        if delivery_opts is not None:
            # Incident delivery rides its own channel: the agent loop
            # never blocks on webhook retries/backoff again.
            from tpuslo.delivery.sinks import WebhookSink

            webhook_channel = delivery_opts.build_channel(
                "webhook",
                WebhookSink(hook),
                observer=metrics.delivery_observer("webhook"),
            )

    def _all_channels():
        return writers.delivery_channels + (
            [webhook_channel] if webhook_channel is not None else []
        )

    def _export_breakers():
        return {
            ch.name: ch.breaker.export_state() for ch in _all_channels()
        }

    def _restore_breakers(state):
        for ch in _all_channels():
            if isinstance(state.get(ch.name), dict):
                ch.breaker.restore_state(state[ch.name])

    runtime.register("breakers", _export_breakers, _restore_breakers)

    sample_meta = SampleMeta(
        cluster=args.cluster,
        namespace=args.namespace,
        workload=args.workload,
        service=args.service,
        node=args.node,
        slice_id=cfg.tpu.slice_id,
        host_index=cfg.tpu.host_index,
    )

    ici_prober = None
    if (
        args.ici_probe_interval_s > 0
        and args.event_kind == "slo"
        and args.probe_source != "ring"
    ):
        # Ring mode emits probe events regardless of event_kind, so the
        # guard only applies to the synthetic loop.
        print(
            "agent: --ici-probe-interval-s needs --event-kind probe|both "
            "(probe events are the prober's output); disabled",
            file=sys.stderr,
        )
    elif args.ici_probe_interval_s > 0:
        from tpuslo.parallel.collectives import ActiveICIProber

        ici_prober = ActiveICIProber(
            interval_s=args.ici_probe_interval_s,
            node=args.node,
            namespace=args.namespace,
            slice_id=cfg.tpu.slice_id,
            host_index=cfg.tpu.host_index,
            payload_kb=args.ici_probe_payload_kb,
            log=lambda msg: print(f"agent: {msg}", file=sys.stderr),
        )

    def emit_one(idx: int) -> None:
        now = datetime.now(timezone.utc)
        sample = build_synthetic_sample(args.scenario, idx, now, sample_meta)

        if args.event_kind in ("slo", "both"):
            events = normalize_sample(sample)
            valid = []
            for event in events:
                if validate_slo(event):
                    valid.append(event)
                else:
                    metrics.dropped.labels(reason="schema").inc()
            try:
                writers.emit_slo(valid)
                metrics.slo_events.inc(len(valid))
            except Exception as exc:  # noqa: BLE001 — emit failures are drops
                metrics.dropped.labels(reason="emit").inc(len(valid))
                print(f"agent: slo emit failed: {exc}", file=sys.stderr)

        if args.event_kind in ("probe", "both"):
            probe_meta = Metadata(trace_id=sample.trace_id)
            generated = list(generator.generate(sample, probe_meta))
            if ici_prober is not None:
                # Measured collectives ride the same validation /
                # rate-limit / emit path as every other probe signal.
                generated.extend(ici_prober.maybe_probe(time.monotonic()))
            if chaos_stream is not None or gate is not None:
                generated = _gate_pipeline(
                    generated, chaos_stream, gate, metrics
                )
            emitted = []
            for event in generated:
                if not limiter.allow():
                    metrics.dropped.labels(reason="rate_limit").inc()
                    continue
                if not validate_probe(event):
                    metrics.dropped.labels(reason="schema").inc()
                    continue
                emitted.append(event)
            try:
                writers.emit_probe(emitted)
                for event in emitted:
                    metrics.observe_probe(event.signal, event.value)
            except Exception as exc:  # noqa: BLE001
                metrics.dropped.labels(reason="emit").inc(len(emitted))
                print(f"agent: probe emit failed: {exc}", file=sys.stderr)

        if (
            hook is not None
            and attributor is not None
            and sample.fault_label
            and idx <= progress["alert_cycle"]
        ):
            # This cycle's alert was already sent by a previous
            # incarnation (restored high-water mark): re-emitting it
            # would page twice for one incident.
            metrics.webhook_sent.labels(outcome="deduped").inc()
        elif hook is not None and attributor is not None and sample.fault_label:
            # At-most-once across restarts: persist the high-water mark
            # *before* the send, so a crash in between loses (at worst)
            # one alert instead of duplicating it — downstream pagers
            # treat duplicate incidents as new pages, lost ones re-fire
            # on the next burn window.
            progress["alert_cycle"] = idx
            if runtime.enabled:
                runtime.snapshot_now()
            fault = attribution.FaultSample(
                incident_id=f"agent-inc-{idx + 1:04d}",
                timestamp=now,
                cluster=args.cluster,
                namespace=args.namespace,
                service=args.service,
                fault_label=sample.fault_label,
                confidence=0.9,
                burn_rate=2.0,
                window_minutes=5,
                request_id=sample.request_id,
                trace_id=sample.trace_id,
                # Full fault profile, independent of the currently-enabled
                # probe set: shedding shouldn't starve attribution.
                signals=profile_for_fault(sample.fault_label),
            )
            attr = attributor.attribute_sample(fault)
            if webhook_channel is not None:
                import json as json_mod

                webhook_channel.submit(
                    "incident", [json_mod.loads(hook.build_payload(attr))]
                )
                metrics.webhook_sent.labels(outcome="queued").inc()
            else:
                try:
                    hook.send(attr)
                    metrics.webhook_sent.labels(outcome="ok").inc()
                except webhook.WebhookError as exc:
                    metrics.webhook_sent.labels(outcome="error").inc()
                    print(f"agent: webhook failed: {exc}", file=sys.stderr)

        if (
            args.stats_interval_cycles
            and (idx + 1) % args.stats_interval_cycles == 0
        ):
            _print_stats(gate)

        result = guard.evaluate()
        if result.valid:
            metrics.cpu_overhead_pct.set(result.cpu_pct)
            if result.over_budget:
                recovery.note(result)  # breaks any under-budget streak
                shed = generator.disable_highest_cost()
                if shed:
                    print(
                        f"agent: overhead {result.cpu_pct:.2f}% > "
                        f"{max_overhead:.2f}%, disabled {shed}",
                        file=sys.stderr,
                    )
                    metrics.set_enabled_signals(generator.enabled_signals())
            elif recovery.note(result):
                restored = generator.restore_one()
                if restored:
                    print(
                        f"agent: overhead {result.cpu_pct:.2f}% under "
                        f"budget for {recovery.cycles} cycles, "
                        f"re-enabled {restored}",
                        file=sys.stderr,
                    )
                    metrics.signals_restored.labels(signal=restored).inc()
                    metrics.set_enabled_signals(generator.enabled_signals())
        metrics.mark_cycle()
        # Progress advances only after the cycle's events hit the
        # writers: a crash replays from the last durable cycle (at-
        # least-once; the restored dedup digest absorbs the overlap).
        progress["next_cycle"] = idx + 1
        if runtime.enabled:
            runtime.maybe_snapshot()
            age = runtime.store.age_s()
            if age != float("inf"):
                metrics.runtime_snapshot_age_seconds.set(age)

    # Warm restore happens after every component registered its hooks;
    # ring-loop components (ProbeManager shed list, supervisor) apply
    # their restored sections at late registration inside the loop.
    restore_outcome = runtime.restore(cold_start=args.cold_start)
    if runtime.enabled:
        detail = ""
        if restore_outcome == "restored":
            detail = (
                f" (age {runtime.restored_age_s:.1f}s, components: "
                f"{','.join(runtime.restored_components) or 'none'})"
            )
        print(
            f"agent: runtime: snapshot {restore_outcome}{detail}; "
            f"resuming at cycle {progress['next_cycle']}",
            file=sys.stderr,
        )

    from tpuslo.runtime import (
        DrainController,
        DrainSignal,
        install_drain_handler,
    )

    # SIGTERM takes exactly the KeyboardInterrupt path: one drain
    # sequence for Ctrl-C and for a Kubernetes pod termination.
    restore_handlers = install_drain_handler()
    drain_timeout = args.drain_timeout_s or cfg.runtime.drain_timeout_s
    drain_reason = "completed"
    try:
        if args.probe_source == "ring":
            _run_ring_loop(
                args, cfg, mode, signal_set, enricher, writers, metrics,
                limiter, guard, recovery, ici_prober=ici_prober, gate=gate,
                runtime=runtime, runtime_observer=runtime_observer,
            )
        else:
            idx = progress["next_cycle"]
            while not args.count or idx < args.count:
                emit_one(idx)
                idx += 1
                if args.count and idx >= args.count:
                    break
                time.sleep(args.interval_s)
    except KeyboardInterrupt:
        drain_reason = "sigint"
    except DrainSignal as sig:
        drain_reason = f"signal_{sig.signum}"
    finally:
        restore_handlers()
        drain = DrainController(
            drain_reason,
            deadline_s=drain_timeout,
            log=lambda msg: print(f"agent: {msg}", file=sys.stderr),
        )
        metrics.up.set(0)
        _print_stats(gate)
        if chaos_stream is not None:
            print(
                f"agent: chaos-telemetry: {chaos_stream.snapshot()}",
                file=sys.stderr,
            )
        # Generation stopped above; now push queued batches out (or to
        # the spool), snapshot, and release sinks — all on one deadline.
        if webhook_channel is not None:
            drain.step(
                "flush_webhook",
                lambda budget: webhook_channel.close(
                    flush_timeout_s=budget
                ),
            )
        drain.step(
            "flush_writers",
            lambda budget: writers.close(flush_timeout_s=budget),
        )
        if runtime.enabled:
            drain.step(
                "final_snapshot", lambda budget: runtime.snapshot_now()
            )
        if gate is not None:
            drain.step("close_gate", lambda budget: gate.close())
        report = drain.finish()
        runtime_observer.drain(report.outcome, report.duration_s)
        print(f"agent: drain: {report.summary()}", file=sys.stderr)
        for channel in (
            writers.delivery_channels
            + ([webhook_channel] if webhook_channel else [])
        ):
            snap = channel.snapshot()
            print(
                "agent: delivery[{sink}]: delivered={delivered_events} "
                "spooled={spooled_events} replayed={replayed_events} "
                "dead_lettered={dead_lettered_events} retries={retries} "
                "breaker={breaker} spool_bytes={spool_bytes}".format(**snap),
                file=sys.stderr,
            )
        if chaos_server is not None:
            chaos_server.stop()
        if server is not None:
            server.shutdown()
    return 0


def _run_ring_loop(
    args, cfg, mode, signal_set, enricher, writers, metrics, limiter, guard,
    recovery, ici_prober=None, gate=None, runtime=None,
    runtime_observer=None,
) -> None:
    """The real-probe path: ringbuf → normalize → schema → emit.

    This is the loop the reference scaffolded but never closed (its
    RingBufConsumer/ProbeManager have no caller outside tests —
    SURVEY.md §0).  Degradation is graceful and reported: no libbpf or
    no privileges → the kernel surface is skipped but userspace rings
    (BCC fallback, injectors, hello tracer, HBM sampler) still flow.
    """
    import os
    import tempfile

    from tpuslo.collector.hbm_sampler import HBMSampler
    from tpuslo.collector.hello_tracer import HelloTracer
    from tpuslo.collector.probe_manager import ProbeManager
    from tpuslo.collector.ringbuf import RingBufConsumer, to_probe_event
    from tpuslo.signals import constants as sigconst

    pm = ProbeManager(guard=guard)
    report = pm.attach_all(signal_set)
    attached = report.attached_signals
    print(
        f"agent: ring mode, {len(attached)}/{len(signal_set)} signals "
        f"attached ({mode})",
        file=sys.stderr,
    )
    for r in report.results:
        if not r.attached:
            print(
                f"agent:   {r.signal}: {r.status} {r.detail}".rstrip(),
                file=sys.stderr,
            )
    metrics.set_enabled_signals(attached)

    consumer = RingBufConsumer(
        steal_window_ms=1000,
        batch=cfg.sampling.burst_limit or 256,
    )
    known_fds: set[int] = set()
    for fd in pm.ringbuf_fds():
        consumer.add_kernel_ringbuf(fd)
        known_fds.add(fd)

    def _sync_ring_fds() -> None:
        """Re-register new ring fds; forget fds closed by a detach."""
        nonlocal known_fds
        current = set(pm.ringbuf_fds())
        for fd in current - known_fds:
            try:
                consumer.add_kernel_ringbuf(fd)
                known_fds.add(fd)
            except Exception as exc:  # noqa: BLE001
                print(f"agent: ring re-add failed: {exc}", file=sys.stderr)
        known_fds &= current

    # ---- probe supervision (tpuslo.runtime.ProbeSupervisor) ----------
    from tpuslo.runtime import (
        ProbeSupervisor,
        RuntimeObserver,
        SupervisorConfig,
    )

    if runtime_observer is None:
        runtime_observer = RuntimeObserver()

    def _restart_probe(signal: str) -> bool:
        pm.detach_signal(signal)
        restarted = signal in pm.attach_all([signal]).attached_signals
        _sync_ring_fds()
        return restarted

    def _flap_shed(signal: str, reason: str) -> None:
        # Route through the shed list so restore_one can bring the
        # signal back (reverse cost order) once the hold-down expires.
        pm.import_shed([signal])
        _sync_ring_fds()
        metrics.set_enabled_signals(pm.attached_signals)
        runtime_observer.flap_shed(signal)

    supervisor = ProbeSupervisor(
        config=SupervisorConfig(
            heartbeat_timeout_s=cfg.runtime.supervisor_heartbeat_timeout_s,
            flap_restarts=cfg.runtime.supervisor_flap_restarts,
            flap_window_s=cfg.runtime.supervisor_flap_window_s,
            flap_holddown_s=cfg.runtime.supervisor_flap_holddown_s,
        ),
        restart=_restart_probe,
        shed=_flap_shed,
        log=lambda msg: print(f"agent: {msg}", file=sys.stderr),
    )
    supervisor.watch(attached)
    if runtime is not None:
        # Late registration: a restored "supervisor"/"pm_shed" section
        # pending from main's restore pass applies here.
        runtime.register(
            "supervisor", supervisor.export_state, supervisor.restore_state
        )
        runtime.register(
            "pm_shed",
            lambda: {"signals": pm.shed_signals},
            lambda s: pm.import_shed(list(s.get("signals") or [])),
        )
        metrics.set_enabled_signals(pm.attached_signals)

    # Userspace side-channel ring: hello tracer + HBM sampler share it,
    # plus whatever external producer --ring-path points at.
    tracer = None
    sampler = None
    side_ring = args.ring_path
    side_ring_owned = False
    if not side_ring and (args.hello or sigconst.SIGNAL_HBM_UTILIZATION_PCT
                          in signal_set):
        # mkstemp (not the race-prone, deprecated mktemp): the path is
        # created 0600 and owned by us; the ring producer re-initializes
        # it in place (O_TRUNC) before the consumer maps it.
        fd, side_ring = tempfile.mkstemp(
            prefix="tpuslo-ring-", suffix=".buf"
        )
        os.close(fd)
        side_ring_owned = True
    if args.hello and side_ring:
        tracer = HelloTracer(side_ring, interval_s=5.0)
        tracer.start()
    if side_ring and sigconst.SIGNAL_HBM_UTILIZATION_PCT in signal_set:
        try:
            sampler = HBMSampler(side_ring)
        except Exception:  # noqa: BLE001 — sampler is best-effort
            sampler = None
    if side_ring:
        try:
            consumer.add_userspace_ring(side_ring)
        except Exception as exc:  # noqa: BLE001
            print(f"agent: side ring attach failed: {exc}", file=sys.stderr)

    meta_template = Metadata(
        node=args.node,
        namespace=args.namespace,
        pod=f"{args.workload}-agent",
        container=args.workload,
        pid=1,
        tid=1,
        tpu_chip=args.tpu_chip,
        slice_id=args.slice_id,
        host_index=args.host_index,
        xla_program_id=args.xla_program_id,
    )

    if args.event_kind == "slo":
        print(
            "agent: ring mode emits probe events only "
            "(SLO events come from the observed workload)",
            file=sys.stderr,
        )

    def emit_probe_event(event) -> None:
        if not limiter.allow():
            metrics.dropped.labels(reason="rate_limit").inc()
            return
        if gate is not None:
            # Real-probe events are exactly the skewed/duplicated/
            # corrupt surface the gate exists for; late events are
            # still emitted (downstream consumers run the re-match).
            from tpuslo.ingest import ADMITTED, LATE
            from tpuslo.schema import ProbeEventV1

            payload = event.to_dict()
            outcome, gated = gate.admit(payload)
            if outcome not in (ADMITTED, LATE):
                return
            if gated is not payload:
                # The gate copies only when it skew-corrected the
                # timestamp; everything else keeps the typed event.
                try:
                    event = ProbeEventV1.from_dict(gated)
                except (TypeError, ValueError, KeyError):
                    metrics.dropped.labels(reason="malformed").inc()
                    return
        if not validate_probe(event):
            metrics.dropped.labels(reason="schema").inc()
            return
        try:
            writers.emit_probe([event])
            metrics.observe_probe(event.signal, event.value)
        except Exception as exc:  # noqa: BLE001
            metrics.dropped.labels(reason="emit").inc()
            print(f"agent: probe emit failed: {exc}", file=sys.stderr)

    cycles = 0
    try:
        while True:
            if sampler is not None:
                sampler.sample_once()
            for sample in consumer.poll(timeout_ms=int(args.interval_s * 500)):
                supervisor.beat(sample.signal)
                event = to_probe_event(sample, meta_template, enricher)
                if event is None:
                    if sample.signal == "hello_heartbeat_total":
                        metrics.mark_cycle()
                    continue
                emit_probe_event(event)
            if ici_prober is not None:
                # Active interconnect probe rides the same emit path as
                # kernel-ring events (synthetic loop does the same).
                for event in ici_prober.maybe_probe(time.monotonic()):
                    emit_probe_event(event)

            for action in supervisor.evaluate():
                if action.action == "restarted":
                    runtime_observer.probe_restarted(action.signal)
                print(
                    f"agent: supervisor: {action.signal} "
                    f"{action.action} {action.detail}".rstrip(),
                    file=sys.stderr,
                )

            result = guard.evaluate()
            if result.valid:
                metrics.cpu_overhead_pct.set(result.cpu_pct)
                if result.over_budget:
                    recovery.note(result)  # breaks the recovery streak
                    shed = pm.shed_highest_cost()
                    if shed:
                        print(
                            f"agent: overhead {result.cpu_pct:.2f}%, "
                            f"detached {shed}",
                            file=sys.stderr,
                        )
                        supervisor.forget(shed)
                        metrics.set_enabled_signals(pm.attached_signals)
                        # Detach closed that object's ring fd; forget it
                        # so a restored probe reusing the fd number
                        # re-registers with the consumer.
                        known_fds &= set(pm.ringbuf_fds())
                elif recovery.note(result):
                    shed_list = pm.shed_signals
                    candidate = shed_list[-1] if shed_list else None
                    if candidate is not None and not supervisor.may_restore(
                        candidate
                    ):
                        # Flap hold-down outranks the overhead-guard
                        # recovery streak: quiet CPU cycles say nothing
                        # about why the supervisor shed a flapping probe.
                        print(
                            f"agent: restore of {candidate} held down "
                            "(flapping)",
                            file=sys.stderr,
                        )
                        restored = None
                    else:
                        restored = pm.restore_one()
                    if restored:
                        print(
                            f"agent: overhead {result.cpu_pct:.2f}% under "
                            f"budget for {recovery.cycles} cycles, "
                            f"re-attached {restored}",
                            file=sys.stderr,
                        )
                        supervisor.note_restored(restored)
                        metrics.signals_restored.labels(
                            signal=restored
                        ).inc()
                        metrics.set_enabled_signals(pm.attached_signals)
                        _sync_ring_fds()
            metrics.mark_cycle()
            if runtime is not None and runtime.enabled:
                runtime.maybe_snapshot()
                age = runtime.store.age_s()
                if age != float("inf"):
                    # Kept current even across failed saves: the
                    # staleness alert must fire exactly then.
                    metrics.runtime_snapshot_age_seconds.set(age)
            cycles += 1
            if (
                args.stats_interval_cycles
                and cycles % args.stats_interval_cycles == 0
            ):
                _print_stats(gate)
            if args.count and cycles >= args.count:
                break
            time.sleep(args.interval_s)
    finally:
        if tracer is not None:
            tracer.stop()
        if sampler is not None:
            sampler.close()
        consumer.close()
        pm.detach_all()
        if side_ring_owned:
            try:
                os.unlink(side_ring)
            except OSError:
                pass


if __name__ == "__main__":
    raise SystemExit(main())
