"""Continuous device profiler: sampled xprof windows on a live process.

The PR 14 ledger accounts for every nanosecond of device time, but only
offline — nothing in the live loop ever captured a window, folded it,
and put the result on the probe spine.  This module closes that loop
(ROADMAP #3; Host-Side Telemetry's always-on-profiling-under-a-budget
result is the viability argument, PAPERS.md):

* **capture** — short periodic windows on a stride of agent cycles.
  Two lanes share ONE parse path (``xla_spans.parse_trace_events``):
  the real lane wraps ``jax.profiler.trace`` via ``xla_spans.capture``
  when JAX and a workload callable are available; the seeded
  ``synthetic.synthesize_xprof_trace`` lane is the platform-independent
  CI feed.
* **fold** — each window runs the full ``build_ledger`` join ladder,
  with the capture's compile lanes folded in as :class:`CompileEvent`s
  (fingerprint / module-name / first-execution-window — the tier-3
  rules), so the compile tier finally sees live data.
* **emit** — the window's deltas become contract-valid ``ProbeEventV1``
  payloads (``device_idle_gap_ms``, ``device_eviction_events_total``,
  ``device_unexplained_share``, ``device_mfu_pct``) shaped exactly like
  ``xla_spans._launch_signal_events`` output, ready for the columnar
  loop's ``from_payloads`` → admission → writer path.  A roofline
  verdict (``verdict_from_ledger``) rides on the window record.
* **govern** — an EMA of capture+parse cost against the cycle budget,
  amortised over the stride (the cost is paid once per ``stride``
  cycles).  Past the 3% budget for a grace streak the stride doubles
  (capped); sustained headroom below half budget re-engages the base
  stride.  Every degradation is counted, and a pending eviction notice
  FORCES the next capture even while degraded — degradation trades
  frequency, never an eviction-bearing window.

Join-rate reporting (the 0.556 lesson): every window carries BOTH the
raw exact-identity rate and the tiered substantive rate, read straight
off the window's ledger — one source, no second derivation.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

from tpuslo.deviceplane.ledger import build_ledger
from tpuslo.deviceplane.roofline import (
    decode_step_cost,
    verdict_from_ledger,
)
from tpuslo.deviceplane.synthetic import (
    STEP_FINGERPRINT,
    synthesize_xprof_trace,
)
from tpuslo.otel.xla_spans import parse_trace_events
from tpuslo.signals import constants as sig

#: Wall-clock source bound at module import so hot methods hold a
#: reference instead of reading the clock primitive inline (hot-path
#: manifest rule); ``perf_counter_ns`` times the capture itself.
_CLOCK_NS = time.time_ns
_PERF_NS = time.perf_counter_ns

#: Overhead budget the governor defends: capture+parse may cost at most
#: this share of the serving loop's cycle budget, amortised over the
#: capture stride.
DEFAULT_OVERHEAD_BUDGET_PCT = 3.0


def seeded_cost_model(batch: int = 8) -> tuple[float, float, tuple[float, float]]:
    """(bytes/step, FLOPs/step, decode-realistic ``step_dur_us`` bounds)
    for the seeded lane — llama32_1b at ``batch``, the serving lanes'
    operating point (same fold as the deviceplane sweep's roofline
    lane, ~30-40% of the v5e HBM roof → memory-bound verdicts)."""
    from tpuslo.models.llama import kv_cache_bytes, llama32_1b, param_count

    cfg = llama32_1b(max_seq_len=1024)
    n_params = param_count(cfg)
    step_bytes, step_flops = decode_step_cost(
        n_params, kv_cache_bytes(cfg, batch), batch=batch
    )
    decode_ms = step_bytes / (0.35 * 819e9) * 1e3
    return step_bytes, step_flops, (decode_ms * 900.0, decode_ms * 1150.0)


def concat_window_docs(
    docs: Sequence[dict[str, Any]],
    compile_event_lists: Sequence[Sequence[dict[str, Any]]] = (),
) -> tuple[dict[str, Any], list[dict[str, Any]]]:
    """Splice per-window trace docs into one contiguous capture.

    Each window's events are shifted so its first span starts exactly
    where the previous window's last span ended — the device timeline
    a single long capture would have produced (no artificial
    inter-window idle).  Compile-event ``end_us`` shifts with its
    window.  This is the parity fixture: per-window ledger buckets must
    sum to the one big ``build_ledger`` over the splice.
    """
    out_events: list[dict[str, Any]] = []
    out_compiles: list[dict[str, Any]] = []
    cursor = 0.0
    first = True
    for i, doc in enumerate(docs):
        events = doc.get("traceEvents", [])
        xs = [e for e in events if e.get("ph") == "X"]
        if not xs:
            continue
        lo = min(float(e["ts"]) for e in xs)
        hi = max(float(e["ts"]) + float(e.get("dur", 0.0)) for e in xs)
        offset = 0.0 if first else cursor - lo
        for e in events:
            if e.get("ph") == "X":
                shifted = dict(e)
                shifted["ts"] = float(e["ts"]) + offset
                out_events.append(shifted)
            elif first:
                out_events.append(e)  # lane metadata once
        if i < len(compile_event_lists):
            for ce in compile_event_lists[i]:
                shifted_ce = dict(ce)
                shifted_ce["end_us"] = float(ce.get("end_us", 0.0)) + offset
                out_compiles.append(shifted_ce)
        cursor = hi + offset
        first = False
    return {"traceEvents": out_events}, out_compiles


@dataclass(slots=True)
class ProfilerWindow:
    """One capture window's ledger deltas — the unit the spine sees."""

    index: int
    cycle: int
    ts_unix_nano: int
    window_ms: float
    idle_gap_ms: float
    eviction_events: int
    unexplained_share: float
    #: Roofline MFU for the window's serving program; -1.0 when the
    #: ledger joined nothing (no denominator — never invent one).
    mfu_pct: float
    #: Roofline verdict ("memory_bound"/"compute_bound", "" when none).
    verdict: str
    #: Raw exact-identity join rate over ALL launches (reported next to
    #: the tiered rate — the 0.556 lesson), straight off the ledger.
    raw_join_rate: float
    #: Tiered substantive rate — the one gates hold at >= 0.9.
    substantive_join_rate: float
    launches: int
    #: Compile fingerprints first seen in this window (live compile-tier
    #: feed: a burst here is a recompile storm reaching the chip).
    new_compilations: int
    capture_cost_ms: float
    stride_cycles: int
    degraded: bool
    #: True when a pending eviction notice forced this capture ahead of
    #: the stride (degradation never drops an eviction window).
    forced: bool
    verdict_detail: str = ""

    def to_dict(self) -> dict[str, Any]:
        return {
            "index": self.index,
            "cycle": self.cycle,
            "ts_unix_nano": self.ts_unix_nano,
            "window_ms": round(self.window_ms, 3),
            "idle_gap_ms": round(self.idle_gap_ms, 4),
            "eviction_events": self.eviction_events,
            "unexplained_share": round(self.unexplained_share, 4),
            "mfu_pct": round(self.mfu_pct, 2),
            "verdict": self.verdict,
            "raw_join_rate": round(self.raw_join_rate, 4),
            "substantive_join_rate": round(self.substantive_join_rate, 4),
            "launches": self.launches,
            "new_compilations": self.new_compilations,
            "capture_cost_ms": round(self.capture_cost_ms, 3),
            "stride_cycles": self.stride_cycles,
            "degraded": self.degraded,
            "forced": self.forced,
            "verdict_detail": self.verdict_detail,
        }


class ContinuousProfiler:
    """Stride-gated capture windows under a measured-overhead governor.

    ``tick()`` once per agent cycle; it returns a
    :class:`ProfilerWindow` on capture cycles and ``None`` otherwise.
    ``probe_payloads(window)`` turns a window into the four
    contract-valid probe payload dicts for the columnar loop.
    """

    def __init__(
        self,
        source: str = "synthetic",
        seed: int = 1337,
        cycle_budget_ms: float = 1000.0,
        overhead_budget_pct: float = DEFAULT_OVERHEAD_BUDGET_PCT,
        ema_alpha: float = 0.1,
        grace_cycles: int = 3,
        stride_cycles: int = 5,
        max_stride_cycles: int = 40,
        window_steps: int = 8,
        history: int = 32,
        bytes_per_step: float = 0.0,
        flops_per_step: float = 0.0,
        program_id: str = STEP_FINGERPRINT,
        node: str = "",
        namespace: str = "llm-slo",
        pod: str = "",
        chip: str = "accel0",
        slice_id: str = "",
        host_index: int = -1,
        log_dir: str = "",
        work_fn: Callable[[], None] | None = None,
        synthetic_preempt_window: int = -1,
        synthetic_preempt_gap_ms: float = 250.0,
        synthetic_orphan_helpers: int = 2,
        synthetic_warmups: int = 1,
        synthetic_lane_split_every: int = 5,
        synthetic_helpers_per_step: int = 1,
        step_dur_us: tuple[float, float] = (1800.0, 2600.0),
        capture_fn: Callable[[int], tuple[list[Any], list[Any]]] | None = None,
        cost_fn: Callable[[float], float] | None = None,
        observer: Any | None = None,
    ):
        if source not in ("synthetic", "xprof"):
            raise ValueError(f"unknown profiler source: {source!r}")
        if source == "xprof" and capture_fn is None:
            if not log_dir:
                raise ValueError("xprof source needs a log_dir")
            if work_fn is None:
                raise ValueError(
                    "xprof source needs a work_fn to bracket (the "
                    "capture window must contain device work)"
                )
            import importlib.util

            if importlib.util.find_spec("jax") is None:
                raise RuntimeError(
                    "xprof source needs jax; use source='synthetic' "
                    "for the platform-independent lane"
                )
        self.source = source
        self.seed = int(seed)
        self.cycle_budget_ms = float(cycle_budget_ms)
        self.overhead_budget_pct = float(overhead_budget_pct)
        self.ema_alpha = float(ema_alpha)
        self.grace_cycles = max(int(grace_cycles), 1)
        self.base_stride_cycles = max(int(stride_cycles), 1)
        self.max_stride_cycles = max(
            int(max_stride_cycles), self.base_stride_cycles
        )
        self.window_steps = max(int(window_steps), 2)
        self.history = max(int(history), 1)
        self.bytes_per_step = float(bytes_per_step)
        self.flops_per_step = float(flops_per_step)
        self.program_id = program_id
        self.node = node
        self.namespace = namespace
        self.pod = pod or node
        self.chip = chip
        self.slice_id = slice_id
        self.host_index = int(host_index)
        self.log_dir = log_dir
        self._work_fn = work_fn
        self.synthetic_preempt_window = int(synthetic_preempt_window)
        self.synthetic_preempt_gap_ms = float(synthetic_preempt_gap_ms)
        self.synthetic_orphan_helpers = int(synthetic_orphan_helpers)
        self.synthetic_warmups = int(synthetic_warmups)
        self.synthetic_lane_split_every = int(synthetic_lane_split_every)
        self.synthetic_helpers_per_step = int(synthetic_helpers_per_step)
        self.step_dur_us = (float(step_dur_us[0]), float(step_dur_us[1]))
        self._capture_fn = capture_fn
        self._cost_fn = cost_fn
        self._observer = observer

        # Governor state.
        self.stride_cycles = self.base_stride_cycles
        self.degraded = False
        self.overhead_ema_pct = 0.0
        self._ema_primed = False
        self._streak_hot = 0
        self._streak_cool = 0

        # Loop state.
        self._cycle = 0
        self._last_capture_cycle = 0
        self._pending_evictions = 0
        self._window_index = 0
        self._seen_fingerprints: set[str] = set()
        self._windows: list[ProfilerWindow] = []
        #: Full roofline verdict dicts by window index — the window
        #: record keeps the compact verdict/MFU/detail triple; the
        #: provenance chain wants the whole block (achieved GB/s, roof
        #: percentages).  Trimmed alongside the window ring.
        self._roofline_by_index: dict[int, dict[str, Any]] = {}

        # Counters (observable: metrics + sloctl read these).
        self.windows_captured = 0
        self.windows_forced = 0
        self.degradations = 0
        self.reengagements = 0
        self.eviction_windows = 0

    # ---- eviction notices -------------------------------------------

    def notice_eviction(self, count: int = 1) -> None:
        """Runtime eviction/preemption notice: forces the next capture
        (even while degraded) and rides the window's event count."""
        self._pending_evictions += max(int(count), 0)

    # ---- capture lanes ----------------------------------------------

    def window_trace_doc(
        self, index: int
    ) -> tuple[dict[str, Any], list[dict[str, Any]]]:
        """The synthetic lane's deterministic per-window trace: window
        ``index`` always yields the same document (parity fixtures
        regenerate windows from indexes alone)."""
        gap_ms = (
            self.synthetic_preempt_gap_ms
            if index == self.synthetic_preempt_window
            else 0.0
        )
        doc, compiles, _truth = synthesize_xprof_trace(
            seed=self.seed + index,
            steps=self.window_steps,
            lane_split_every=self.synthetic_lane_split_every,
            helpers_per_step=self.synthetic_helpers_per_step,
            orphan_helpers=self.synthetic_orphan_helpers,
            warmup_launches=self.synthetic_warmups,
            preemption_gap_ms=gap_ms,
            step_dur_us=self.step_dur_us,
        )
        return doc, compiles

    def _capture(self, index: int) -> tuple[list[Any], list[Any]]:
        if self._capture_fn is not None:
            return self._capture_fn(index)
        if self.source == "xprof":
            from tpuslo.otel.xla_spans import capture as xla_capture

            with xla_capture(self.log_dir, include_ops=True) as cap:
                self._work_fn()
            return cap.spans, []
        doc, compiles = self.window_trace_doc(index)
        return parse_trace_events(doc, include_ops=True), compiles

    # ---- the governor (PR 5 tracer style) ---------------------------

    def _note_overhead(self, cost_ms: float) -> None:
        # Cost is paid once per stride cycles: amortise before
        # comparing against the budget, so degrading the stride
        # genuinely buys headroom.
        pct = 100.0 * cost_ms / (self.cycle_budget_ms * self.stride_cycles)
        if not self._ema_primed:
            self.overhead_ema_pct = pct
            self._ema_primed = True
        else:
            self.overhead_ema_pct = (
                self.ema_alpha * pct
                + (1.0 - self.ema_alpha) * self.overhead_ema_pct
            )
        if self.overhead_ema_pct > self.overhead_budget_pct:
            self._streak_cool = 0
            self._streak_hot += 1
            if (
                self._streak_hot >= self.grace_cycles
                and self.stride_cycles < self.max_stride_cycles
            ):
                self.stride_cycles = min(
                    self.stride_cycles * 2, self.max_stride_cycles
                )
                self.degraded = True
                self.degradations += 1
                self._streak_hot = 0
                if self._observer is not None:
                    self._observer.degraded(self.stride_cycles)
        elif (
            self.degraded
            and self.overhead_ema_pct < self.overhead_budget_pct * 0.5
        ):
            self._streak_hot = 0
            self._streak_cool += 1
            if self._streak_cool >= self.grace_cycles:
                self.stride_cycles = self.base_stride_cycles
                self.degraded = False
                self.reengagements += 1
                self._streak_cool = 0
                if self._observer is not None:
                    self._observer.reengaged(self.stride_cycles)
        else:
            self._streak_hot = 0
            self._streak_cool = 0

    # ---- the loop ----------------------------------------------------

    def tick(self) -> ProfilerWindow | None:
        """One agent cycle.  Captures when the stride elapses or an
        eviction notice is pending; returns the folded window then."""
        self._cycle += 1
        due = (self._cycle - self._last_capture_cycle) >= self.stride_cycles
        forced = self._pending_evictions > 0 and not due
        if not due and not forced:
            return None
        return self._capture_window(forced=forced)

    def _capture_window(self, forced: bool) -> ProfilerWindow:
        index = self._window_index
        t0 = _PERF_NS()
        spans, compiles = self._capture(index)
        ledger = build_ledger(spans, compiles)
        cost_ms = (_PERF_NS() - t0) / 1e6
        if self._cost_fn is not None:
            cost_ms = float(self._cost_fn(cost_ms))

        evictions = self._pending_evictions
        if (
            self.source == "synthetic"
            and self._capture_fn is None
            and index == self.synthetic_preempt_window
        ):
            # The injected preemption gap comes with its runtime
            # eviction notice, like a real maintenance event would.
            evictions += 1
        self._pending_evictions = 0

        new_fps = 0
        for ce in compiles:
            fp = str(
                ce.get("program_id", "")
                if isinstance(ce, dict)
                else getattr(ce, "program_id", "")
            )
            if fp and fp not in self._seen_fingerprints:
                self._seen_fingerprints.add(fp)
                new_fps += 1

        mfu_pct = -1.0
        verdict = ""
        verdict_detail = ""
        if self.bytes_per_step > 0.0 and self.flops_per_step > 0.0:
            rv = verdict_from_ledger(
                ledger,
                self.bytes_per_step,
                self.flops_per_step,
                program_id=self.program_id,
            )
            if rv is not None:
                mfu_pct = float(rv["mfu_pct"])
                verdict = rv["verdict"]
                verdict_detail = rv["detail"]
                self._roofline_by_index[index] = rv

        window = ProfilerWindow(
            index=index,
            cycle=self._cycle,
            ts_unix_nano=_CLOCK_NS(),
            window_ms=ledger.total_us / 1000.0,
            idle_gap_ms=ledger.idle_gap_ms(),
            eviction_events=evictions,
            unexplained_share=ledger.unexplained_share,
            mfu_pct=mfu_pct,
            verdict=verdict,
            raw_join_rate=ledger.raw_join_rate,
            substantive_join_rate=ledger.substantive_join_rate,
            launches=len(ledger.launches),
            new_compilations=new_fps,
            capture_cost_ms=cost_ms,
            stride_cycles=self.stride_cycles,
            degraded=self.degraded,
            forced=forced,
            verdict_detail=verdict_detail,
        )
        self._window_index += 1
        self._last_capture_cycle = self._cycle
        self.windows_captured += 1
        if forced:
            self.windows_forced += 1
        if evictions > 0:
            self.eviction_windows += 1
        self._windows.append(window)
        if len(self._windows) > self.history:
            del self._windows[: len(self._windows) - self.history]
        live = {w.index for w in self._windows}
        for stale in [
            k for k in self._roofline_by_index if k not in live
        ]:
            del self._roofline_by_index[stale]
        self._note_overhead(cost_ms)
        if self._observer is not None:
            self._observer.window(window, self.overhead_ema_pct)
        return window

    # ---- emission -----------------------------------------------------

    def probe_payloads(self, window: ProfilerWindow) -> list[dict[str, Any]]:
        """The window's four device signals as contract-valid probe
        payload dicts (``xla_spans._launch_signal_events`` shape) for
        ``columnar.from_payloads``."""
        from tpuslo.signals.generator import signal_status

        tpu: dict[str, Any] = {"chip": self.chip}
        if self.slice_id:
            tpu["slice_id"] = self.slice_id
        if self.host_index >= 0:
            tpu["host_index"] = self.host_index
        if self.program_id:
            tpu["program_id"] = self.program_id
        values = (
            (sig.SIGNAL_DEVICE_IDLE_GAP_MS, window.idle_gap_ms, "ms"),
            (
                sig.SIGNAL_DEVICE_EVICTION_EVENTS,
                float(window.eviction_events),
                "count",
            ),
            (
                sig.SIGNAL_DEVICE_UNEXPLAINED_SHARE,
                window.unexplained_share,
                "ratio",
            ),
            (sig.SIGNAL_DEVICE_MFU_PCT, max(window.mfu_pct, 0.0), "pct"),
        )
        out: list[dict[str, Any]] = []
        for name, value, unit in values:
            out.append(
                {
                    "ts_unix_nano": window.ts_unix_nano,
                    "signal": name,
                    "node": self.node,
                    "namespace": self.namespace,
                    "pod": self.pod or self.node,
                    "container": "xprof",
                    "pid": 0,
                    "tid": 0,
                    "value": round(float(value), 4),
                    "unit": unit,
                    "status": signal_status(name, value),
                    "tpu": dict(tpu),
                }
            )
        return out

    def window_signal_values(
        self, window: ProfilerWindow
    ) -> dict[str, float]:
        """signal→value map for the attribution engine (same values the
        probe payloads carry — one source)."""
        return {
            sig.SIGNAL_DEVICE_IDLE_GAP_MS: window.idle_gap_ms,
            sig.SIGNAL_DEVICE_EVICTION_EVENTS: float(
                window.eviction_events
            ),
            sig.SIGNAL_DEVICE_UNEXPLAINED_SHARE: window.unexplained_share,
            sig.SIGNAL_DEVICE_MFU_PCT: max(window.mfu_pct, 0.0),
        }

    # ---- state / introspection ---------------------------------------

    def windows(self) -> list[ProfilerWindow]:
        return list(self._windows)

    def window_roofline(self, index: int) -> dict[str, Any]:
        """The full roofline verdict block for a retained window
        (empty when the window carried no cost model or has aged out
        of the ring)."""
        return dict(self._roofline_by_index.get(index, {}))

    def stats(self) -> dict[str, Any]:
        return {
            "source": self.source,
            "cycle": self._cycle,
            "windows_captured": self.windows_captured,
            "windows_forced": self.windows_forced,
            "eviction_windows": self.eviction_windows,
            "degradations": self.degradations,
            "reengagements": self.reengagements,
            "degraded": self.degraded,
            "stride_cycles": self.stride_cycles,
            "base_stride_cycles": self.base_stride_cycles,
            "overhead_ema_pct": round(self.overhead_ema_pct, 4),
            "overhead_budget_pct": self.overhead_budget_pct,
        }

    def export_state(self) -> dict[str, Any]:
        return {
            **self.stats(),
            "last_capture_cycle": self._last_capture_cycle,
            "window_index": self._window_index,
            "pending_evictions": self._pending_evictions,
            "seen_fingerprints": sorted(self._seen_fingerprints),
            "windows": [w.to_dict() for w in self._windows],
        }

    def restore_state(self, state: dict[str, Any]) -> None:
        if not isinstance(state, dict):
            return
        self._cycle = int(state.get("cycle", 0))
        self._last_capture_cycle = int(state.get("last_capture_cycle", 0))
        self._window_index = int(state.get("window_index", 0))
        self._pending_evictions = int(state.get("pending_evictions", 0))
        self.windows_captured = int(state.get("windows_captured", 0))
        self.windows_forced = int(state.get("windows_forced", 0))
        self.eviction_windows = int(state.get("eviction_windows", 0))
        self.degradations = int(state.get("degradations", 0))
        self.reengagements = int(state.get("reengagements", 0))
        self.degraded = bool(state.get("degraded", False))
        self.stride_cycles = max(
            int(state.get("stride_cycles", self.base_stride_cycles)), 1
        )
        self.overhead_ema_pct = float(state.get("overhead_ema_pct", 0.0))
        self._ema_primed = self.windows_captured > 0
        self._seen_fingerprints = {
            str(fp) for fp in state.get("seen_fingerprints", ())
        }
        restored: list[ProfilerWindow] = []
        for raw in state.get("windows", ()):
            try:
                restored.append(
                    ProfilerWindow(
                        index=int(raw["index"]),
                        cycle=int(raw["cycle"]),
                        ts_unix_nano=int(raw["ts_unix_nano"]),
                        window_ms=float(raw["window_ms"]),
                        idle_gap_ms=float(raw["idle_gap_ms"]),
                        eviction_events=int(raw["eviction_events"]),
                        unexplained_share=float(raw["unexplained_share"]),
                        mfu_pct=float(raw["mfu_pct"]),
                        verdict=str(raw.get("verdict", "")),
                        raw_join_rate=float(raw["raw_join_rate"]),
                        substantive_join_rate=float(
                            raw["substantive_join_rate"]
                        ),
                        launches=int(raw["launches"]),
                        new_compilations=int(
                            raw.get("new_compilations", 0)
                        ),
                        capture_cost_ms=float(raw["capture_cost_ms"]),
                        stride_cycles=int(raw["stride_cycles"]),
                        degraded=bool(raw["degraded"]),
                        forced=bool(raw.get("forced", False)),
                        verdict_detail=str(raw.get("verdict_detail", "")),
                    )
                )
            except (KeyError, TypeError, ValueError):
                continue
        if restored:
            self._windows = restored[-self.history:]


# ---- seeded sweep gate ------------------------------------------------

#: Gate floors (bench digest + m5gate hold these).
MAX_OVERHEAD_PCT = 3.0
MIN_WINDOW_SUBSTANTIVE_JOIN = 0.9
MAX_PARITY_DRIFT_US = 0.5


@dataclass
class ProfilerReport:
    """One profiler sweep's evidence (m5gate/bench digest shape)."""

    seed: int
    overhead: dict[str, Any] = field(default_factory=dict)
    governor: dict[str, Any] = field(default_factory=dict)
    joins: dict[str, Any] = field(default_factory=dict)
    parity: dict[str, Any] = field(default_factory=dict)
    preemption: dict[str, Any] = field(default_factory=dict)
    failures: list[str] = field(default_factory=list)

    @property
    def passed(self) -> bool:
        return not self.failures

    def to_dict(self) -> dict[str, Any]:
        return {
            "seed": self.seed,
            "passed": self.passed,
            "overhead": self.overhead,
            "governor": self.governor,
            "joins": self.joins,
            "parity": self.parity,
            "preemption": self.preemption,
            "failures": list(self.failures),
        }


def _sweep_profiler(seed: int, cycles: int, **kwargs: Any) -> ContinuousProfiler:
    step_bytes, step_flops, step_dur = seeded_cost_model()
    defaults: dict[str, Any] = dict(
        source="synthetic",
        seed=seed,
        cycle_budget_ms=1000.0,
        stride_cycles=2,
        grace_cycles=2,
        window_steps=8,
        history=max(cycles, 8),
        bytes_per_step=step_bytes,
        flops_per_step=step_flops,
        step_dur_us=step_dur,
        node="sweep-host",
    )
    defaults.update(kwargs)
    return ContinuousProfiler(**defaults)


def _overhead_lane(report: ProfilerReport, seed: int, cycles: int) -> None:
    prof = _sweep_profiler(seed, cycles)
    windows = [w for _ in range(cycles) if (w := prof.tick()) is not None]
    report.overhead = {
        "cycles": cycles,
        "windows": len(windows),
        "overhead_ema_pct": round(prof.overhead_ema_pct, 4),
        "budget_pct": prof.overhead_budget_pct,
        "mean_capture_cost_ms": round(
            sum(w.capture_cost_ms for w in windows) / max(len(windows), 1),
            3,
        ),
        "degradations": prof.degradations,
    }
    if not windows:
        report.failures.append("overhead: no windows captured")
        return
    if prof.overhead_ema_pct > MAX_OVERHEAD_PCT:
        report.failures.append(
            f"overhead: EMA {prof.overhead_ema_pct:.3f}% > "
            f"{MAX_OVERHEAD_PCT}% budget"
        )


def _governor_lane(report: ProfilerReport, seed: int) -> None:
    # Forced-slow captures (cost_fn pins the measured cost far over
    # budget) must degrade the stride; restoring headroom must
    # re-engage; an eviction notice must force a capture mid-stride
    # even while degraded.
    slow = {"cost_ms": 400.0}
    prof = _sweep_profiler(
        seed + 1, 64, cost_fn=lambda _ms: slow["cost_ms"],
        stride_cycles=2, max_stride_cycles=16, grace_cycles=2,
    )
    degraded_at = -1
    for cycle in range(64):
        prof.tick()
        if prof.degraded and degraded_at < 0:
            degraded_at = cycle + 1
        if prof.degraded:
            break
    stride_after_degrade = prof.stride_cycles
    if not prof.degraded:
        report.failures.append("governor: forced-slow capture never degraded")
    if stride_after_degrade <= prof.base_stride_cycles:
        report.failures.append(
            "governor: degradation did not lengthen the stride"
        )

    # Eviction notice while degraded: next tick must capture.
    prof.notice_eviction()
    forced_window = prof.tick()
    if forced_window is None or forced_window.eviction_events < 1:
        report.failures.append(
            "governor: eviction notice did not force a capture while "
            "degraded"
        )

    # Sustained headroom: EMA decays below half budget -> re-engage.
    slow["cost_ms"] = 1.0
    reengaged_at = -1
    for cycle in range(400):
        prof.tick()
        if not prof.degraded:
            reengaged_at = cycle + 1
            break
    if reengaged_at < 0:
        report.failures.append(
            "governor: sustained headroom never re-engaged the stride"
        )
    report.governor = {
        "degraded_at_cycle": degraded_at,
        "stride_after_degrade": stride_after_degrade,
        "forced_capture_evictions": (
            forced_window.eviction_events if forced_window else 0
        ),
        "reengaged_after_cycles": reengaged_at,
        "degradations": prof.degradations,
        "reengagements": prof.reengagements,
    }


def _join_lane(report: ProfilerReport, seed: int, cycles: int) -> None:
    prof = _sweep_profiler(seed + 2, cycles, stride_cycles=1)
    windows = [w for _ in range(cycles) if (w := prof.tick()) is not None]
    worst = min((w.substantive_join_rate for w in windows), default=0.0)
    raw = [w.raw_join_rate for w in windows]
    report.joins = {
        "windows": len(windows),
        "min_substantive_join_rate": round(worst, 4),
        "floor": MIN_WINDOW_SUBSTANTIVE_JOIN,
        "mean_raw_join_rate": round(sum(raw) / max(len(raw), 1), 4),
    }
    if worst < MIN_WINDOW_SUBSTANTIVE_JOIN:
        report.failures.append(
            f"joins: window substantive join {worst:.4f} < "
            f"{MIN_WINDOW_SUBSTANTIVE_JOIN}"
        )
    # The raw rate must be REPORTED strictly below the tiered rate on
    # the seeded lane (helpers/warmups carry no exact identity): if the
    # two ever collapse together the single-sourcing broke.
    if windows and not all(
        w.raw_join_rate < w.substantive_join_rate for w in windows
    ):
        report.failures.append(
            "joins: raw exact-identity rate not distinct from the "
            "tiered substantive rate"
        )


def _parity_lane(report: ProfilerReport, seed: int, n_windows: int) -> None:
    # Per-window ledger buckets must sum to one big build_ledger over
    # the spliced capture.  Orphan helpers stay out of this lane: in a
    # spliced trace a later window's head-of-trace orphans sit after
    # earlier step frames and the frame tier legitimately claims them —
    # a real cross-window recovery, not an accounting error.
    prof = _sweep_profiler(
        seed + 3, n_windows, stride_cycles=1, synthetic_orphan_helpers=0
    )
    docs: list[dict[str, Any]] = []
    compile_lists: list[list[dict[str, Any]]] = []
    per_window: dict[str, float] = {}
    windows_total_us = 0.0
    for _ in range(n_windows):
        w = prof.tick()
        assert w is not None
        doc, compiles = prof.window_trace_doc(w.index)
        docs.append(doc)
        compile_lists.append(compiles)
        ledger = build_ledger(parse_trace_events(doc, include_ops=True), compiles)
        for bucket, us in ledger.buckets_us.items():
            per_window[bucket] = per_window.get(bucket, 0.0) + us
        windows_total_us += ledger.total_us
    spliced_doc, spliced_compiles = concat_window_docs(docs, compile_lists)
    full = build_ledger(
        parse_trace_events(spliced_doc, include_ops=True), spliced_compiles
    )
    drift = {
        bucket: abs(per_window.get(bucket, 0.0) - us)
        for bucket, us in full.buckets_us.items()
    }
    worst_bucket, worst_us = max(
        drift.items(), key=lambda kv: kv[1], default=("", 0.0)
    )
    report.parity = {
        "windows": n_windows,
        "window_bucket_sums_ms": {
            b: round(us / 1000.0, 3) for b, us in sorted(per_window.items())
        },
        "full_capture_buckets_ms": {
            b: round(us / 1000.0, 3)
            for b, us in sorted(full.buckets_us.items())
        },
        "worst_bucket_drift_us": round(worst_us, 3),
        "worst_bucket": worst_bucket,
        "total_drift_us": round(abs(windows_total_us - full.total_us), 3),
    }
    if worst_us > MAX_PARITY_DRIFT_US:
        report.failures.append(
            f"parity: bucket {worst_bucket} drifts {worst_us:.3f}us "
            f"between per-window and spliced ledgers"
        )
    if abs(windows_total_us - full.total_us) > MAX_PARITY_DRIFT_US:
        report.failures.append(
            "parity: window totals do not sum to the spliced capture"
        )


def _preemption_lane(report: ProfilerReport, seed: int) -> None:
    # The injected preemption window must surface as a tpu_preemption
    # attribution from the window's own signal values — the live e2e
    # the acceptance criterion drives through the agent.
    from tpuslo.attribution.bayesian import BayesianAttributor

    prof = _sweep_profiler(
        seed + 4, 8, stride_cycles=1,
        synthetic_preempt_window=3, synthetic_preempt_gap_ms=300.0,
    )
    windows = [w for _ in range(8) if (w := prof.tick()) is not None]
    hit = next((w for w in windows if w.eviction_events > 0), None)
    clean = [w for w in windows if w.eviction_events == 0]
    if hit is None:
        report.failures.append("preemption: injected window never captured")
        report.preemption = {"windows": len(windows)}
        return
    attributor = BayesianAttributor()
    posteriors = attributor.attribute(prof.window_signal_values(hit))
    top = posteriors[0]
    baseline_gap = max((w.idle_gap_ms for w in clean), default=0.0)
    report.preemption = {
        "window_index": hit.index,
        "idle_gap_ms": round(hit.idle_gap_ms, 3),
        "baseline_max_idle_gap_ms": round(baseline_gap, 3),
        "eviction_events": hit.eviction_events,
        "top_domain": top.domain,
        "posterior": round(top.posterior, 4),
        "verdict": hit.verdict,
    }
    if top.domain != "tpu_preemption":
        report.failures.append(
            f"preemption: window attributed to {top.domain}, not "
            "tpu_preemption"
        )
    if hit.idle_gap_ms <= baseline_gap + 100.0:
        report.failures.append(
            "preemption: injected gap did not dominate the idle-gap "
            "signal"
        )


def run_profiler_sweep(
    seed: int = 1337, cycles: int = 24, parity_windows: int = 5
) -> ProfilerReport:
    """The profiler's seeded CI gate: overhead, governor, per-window
    joins, window/full-capture parity, and the preemption e2e."""
    report = ProfilerReport(seed=seed)
    _overhead_lane(report, seed, cycles)
    _governor_lane(report, seed)
    _join_lane(report, seed, min(cycles, 12))
    _parity_lane(report, seed, parity_windows)
    _preemption_lane(report, seed)
    return report
