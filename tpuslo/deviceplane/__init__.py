"""Device-plane truth: per-launch device-time ledger + roofline verdicts.

The observability layer that accounts for every nanosecond of device
time and attaches an actionable verdict to it (ROADMAP #3 / ISSUE 14):

* :mod:`tpuslo.deviceplane.ledger` — tiered joins over xprof spans
  (exact identity → compile-event attribution → thread-lane windowed
  recovery → per-step frames) folding every module launch into exactly
  one bucket (joined / helper / compile / idle-gap / unexplained), with
  the buckets provably summing to total device time;
* :mod:`tpuslo.deviceplane.roofline` — per-launch bytes/FLOP estimates
  folded into a memory- vs compute-bound verdict against the chip's
  public HBM and MXU roofs, attached to serving-path attributions;
* :mod:`tpuslo.deviceplane.synthetic` — seeded synthetic-xprof traces
  (trace-viewer JSON, parsed through the REAL
  ``xla_spans.parse_trace_events`` path) so the ledger is gated
  off-chip;
* :mod:`tpuslo.deviceplane.sweep` — the release gate
  (``m5gate --deviceplane-sweep``);
* :mod:`tpuslo.deviceplane.profiler` — the continuous profiler
  (ISSUE 20): stride-gated live capture windows folded through the
  ledger under a measured-overhead governor, emitting per-window
  device signals onto the probe spine (``m5gate --profiler-sweep``).
"""

from tpuslo.deviceplane.dispatch import DispatchLedger
from tpuslo.deviceplane.ledger import (
    BUCKET_COMPILE,
    BUCKET_HELPER,
    BUCKET_IDLE_GAP,
    BUCKET_JOINED,
    BUCKET_UNEXPLAINED,
    TIER_COMPILE_EVENT,
    TIER_FRAME,
    TIER_IDENTITY,
    TIER_LANE_WINDOW,
    CompileEvent,
    DeviceLedger,
    DeviceWindow,
    LaunchRecord,
    build_ledger,
)
from tpuslo.deviceplane.roofline import (
    VERDICT_COMPUTE_BOUND,
    VERDICT_MEMORY_BOUND,
    attach_roofline,
    decode_step_cost,
    roofline_verdict,
)
from tpuslo.deviceplane.profiler import (
    ContinuousProfiler,
    ProfilerReport,
    ProfilerWindow,
    concat_window_docs,
    run_profiler_sweep,
)
from tpuslo.deviceplane.sweep import DeviceplaneReport, run_deviceplane_sweep
from tpuslo.deviceplane.synthetic import synthesize_xprof_trace

__all__ = [
    "BUCKET_COMPILE",
    "BUCKET_HELPER",
    "BUCKET_IDLE_GAP",
    "BUCKET_JOINED",
    "BUCKET_UNEXPLAINED",
    "TIER_COMPILE_EVENT",
    "TIER_FRAME",
    "TIER_IDENTITY",
    "TIER_LANE_WINDOW",
    "CompileEvent",
    "ContinuousProfiler",
    "DeviceLedger",
    "DeviceWindow",
    "DeviceplaneReport",
    "DispatchLedger",
    "LaunchRecord",
    "ProfilerReport",
    "ProfilerWindow",
    "VERDICT_COMPUTE_BOUND",
    "VERDICT_MEMORY_BOUND",
    "attach_roofline",
    "build_ledger",
    "concat_window_docs",
    "decode_step_cost",
    "roofline_verdict",
    "run_deviceplane_sweep",
    "run_profiler_sweep",
    "synthesize_xprof_trace",
]
