"""Per-launch device-time ledger over xprof span captures.

The real-chip evidence had ``xla_launch_join_rate`` at 0.556 — half of
device time unexplained — because the only join the pipeline served was
the exact ``(program_id, launch_id)`` identity: dispatch-only helper
programs, anonymous launches (no ``run_id``), and launches whose op
events landed on a different trace lane all fell out of the
denominator with no accounting.  This module closes that gap with a
tiered join ladder (THAPI's multi-tier heterogeneous-API join and
CrossTrace's cross-thread span correlation are the tier designs —
PAPERS.md):

1. **identity** — ops contained in the launch's own window on its own
   device, launch carries a ``run_id``: the exact join the
   ``xla_launch`` correlation tier already serves.
2. **lane_window** — the launch has no ops on its own trace lane, but
   an ops-only satellite lane (xprof splitting op events onto a
   sibling pid) carries ops fully contained in the launch window:
   windowed containment recovers them.
3. **compile_event** — anonymous/helper launches tie to their owning
   compilation by program fingerprint, module-name prefix, or a
   bounded time window after the compile finished.
4. **frame** — per-step frames bucket the remainder: a dispatch-only
   helper between step N's launch and step N+1's belongs to step N.

Every module launch lands in exactly ONE bucket — ``joined`` /
``helper`` / ``compile`` / ``idle_gap`` / ``unexplained`` — and the
buckets provably sum to total device time (the per-device observation
window), which is the invariant the sweep gate asserts.

Accounting rule: overlapping launches on one device each own only the
time not already owned by an earlier-starting launch (a sweep clip),
so bucket sums cannot double-count; the idle gap is the window minus
the merged busy time.  All functions are pure folds over the span
lists (hot-path manifest: no wall-clock reads, no serialization).
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass, field
from typing import Any, Iterable, Sequence

from tpuslo.otel.xla_spans import MODULES_LANE, OPS_LANE, XLASpan

# Buckets: every launch (and every idle nanosecond) lands in exactly one.
BUCKET_JOINED = "joined"
BUCKET_HELPER = "helper"
BUCKET_COMPILE = "compile"
BUCKET_IDLE_GAP = "idle_gap"
BUCKET_UNEXPLAINED = "unexplained"

ALL_BUCKETS = (
    BUCKET_JOINED,
    BUCKET_HELPER,
    BUCKET_COMPILE,
    BUCKET_IDLE_GAP,
    BUCKET_UNEXPLAINED,
)

# Join tiers, strongest first (a launch keeps the first tier that
# explains it).
TIER_IDENTITY = "identity"
TIER_LANE_WINDOW = "lane_window"
TIER_COMPILE_EVENT = "compile_event"
TIER_FRAME = "frame"
TIER_NONE = "none"

ALL_TIERS = (TIER_IDENTITY, TIER_LANE_WINDOW, TIER_COMPILE_EVENT, TIER_FRAME)

# Unattributed-launch reason classes (superset of the historical
# ``launch_match_breakdown`` vocabulary, which this ledger now feeds).
REASON_NO_OPS_LANE = "no_ops_lane"
REASON_NO_CONTAINED_OPS = "no_contained_ops"
REASON_OVERLAPPING = "ops_assigned_to_overlapping_launch"
REASON_ANONYMOUS = "anonymous_launch"
REASON_SPLIT_LANE = "ops_on_split_lane"

#: Default window after a compile event's end within which an otherwise
#: unidentifiable launch is attributed to that compilation (first
#: execution of a freshly compiled program).
DEFAULT_COMPILE_ATTACH_WINDOW_US = 50_000.0


@dataclass(slots=True)
class CompileEvent:
    """One finished XLA compilation (ServeEngine.compile_events shape)."""

    program_id: str = ""
    module_name: str = ""
    end_us: float = 0.0
    duration_ms: float = 0.0

    @classmethod
    def from_any(cls, raw: Any) -> "CompileEvent":
        if isinstance(raw, CompileEvent):
            return raw
        if isinstance(raw, dict):
            return cls(
                program_id=str(raw.get("program_id", "")),
                module_name=str(
                    raw.get("module_name", raw.get("name", ""))
                ),
                end_us=float(raw.get("end_us", 0.0)),
                duration_ms=float(raw.get("duration_ms", 0.0)),
            )
        raise TypeError(f"not a compile event: {raw!r}")


@dataclass(slots=True)
class LaunchRecord:
    """One module launch's ledger entry."""

    name: str
    module_name: str
    program_id: str
    launch_id: int
    device_pid: int
    start_us: float
    duration_us: float
    #: Time this launch owns after the overlap clip (what its bucket
    #: receives) — equal to ``duration_us`` on a serial device timeline.
    owned_us: float
    #: Summed ops-lane device time inside this launch (0 for helpers).
    ops_us: float = 0.0
    ops_count: int = 0
    #: Where the ops came from: "own" lane, a recovered split "lane",
    #: or "" for dispatch-only helpers.
    ops_source: str = ""
    tier: str = TIER_NONE
    bucket: str = BUCKET_UNEXPLAINED
    reason: str = ""

    def to_dict(self) -> dict[str, Any]:
        return {
            "module": self.module_name or self.name,
            "program_id": self.program_id,
            "launch_id": self.launch_id,
            "device_pid": self.device_pid,
            "duration_us": round(self.duration_us, 1),
            "ops_us": round(self.ops_us, 1),
            "tier": self.tier,
            "bucket": self.bucket,
            "reason": self.reason,
        }


@dataclass(slots=True)
class DeviceWindow:
    """One device's observation window and busy/idle split."""

    device_pid: int
    window_start_us: float
    window_end_us: float
    busy_us: float
    idle_gap_us: float

    @property
    def window_us(self) -> float:
        return max(self.window_end_us - self.window_start_us, 0.0)


@dataclass(slots=True)
class DeviceLedger:
    """The full ledger: per-launch records, per-device windows, bucket
    totals, and the join rates the serving bench publishes."""

    launches: list[LaunchRecord] = field(default_factory=list)
    devices: list[DeviceWindow] = field(default_factory=list)
    #: bucket -> microseconds (sums to ``total_us`` — the invariant).
    buckets_us: dict[str, float] = field(default_factory=dict)
    tier_counts: dict[str, int] = field(default_factory=dict)
    reasons: dict[str, int] = field(default_factory=dict)
    #: Exact-identity matches over ALL module launches (helpers
    #: included) — the historical headline number, REPORTED ONLY; the
    #: substantive rate is the one gates consume.
    raw_join_rate: float = 0.0
    #: Fraction of ops-bearing launches whose identity a join can
    #: actually serve after the full tier ladder.
    substantive_join_rate: float = 0.0
    #: Exact-identity-only variant of the substantive rate (the number
    #: ``launch_match_breakdown`` historically published).
    exact_substantive_join_rate: float = 0.0
    launches_with_ops: int = 0
    orphan_ops_count: int = 0
    orphan_ops_unclaimed: int = 0

    @property
    def total_us(self) -> float:
        return sum(d.window_us for d in self.devices)

    @property
    def bucket_sum_us(self) -> float:
        return sum(self.buckets_us.values())

    @property
    def unexplained_share(self) -> float:
        total = self.total_us
        if total <= 0.0:
            return 0.0
        return self.buckets_us.get(BUCKET_UNEXPLAINED, 0.0) / total

    def bucket_ms(self) -> dict[str, float]:
        return {b: self.buckets_us.get(b, 0.0) / 1000.0 for b in ALL_BUCKETS}

    def idle_gap_ms(self) -> float:
        return self.buckets_us.get(BUCKET_IDLE_GAP, 0.0) / 1000.0

    def to_dict(self, example_cap: int = 12) -> dict[str, Any]:
        unexplained = [
            rec.to_dict()
            for rec in self.launches
            if rec.bucket == BUCKET_UNEXPLAINED
        ]
        return {
            "launches": len(self.launches),
            "launches_with_ops": self.launches_with_ops,
            "devices": len(self.devices),
            "total_device_time_ms": round(self.total_us / 1000.0, 3),
            "buckets_ms": {
                b: round(us / 1000.0, 3)
                for b, us in sorted(self.buckets_us.items())
            },
            "bucket_sum_ms": round(self.bucket_sum_us / 1000.0, 3),
            "unexplained_share": round(self.unexplained_share, 4),
            "tier_counts": dict(sorted(self.tier_counts.items())),
            "reasons": dict(sorted(self.reasons.items())),
            "raw_join_rate": round(self.raw_join_rate, 4),
            "substantive_join_rate": round(self.substantive_join_rate, 4),
            "exact_substantive_join_rate": round(
                self.exact_substantive_join_rate, 4
            ),
            "orphan_ops": {
                "total": self.orphan_ops_count,
                "unclaimed": self.orphan_ops_unclaimed,
            },
            "unexplained_examples": unexplained[:example_cap],
        }


def _compile_index(
    compile_events: Iterable[Any],
) -> tuple[dict[str, CompileEvent], list[CompileEvent], list[float]]:
    """(by program_id, by end-time order, sorted end times)."""
    events = [CompileEvent.from_any(e) for e in compile_events]
    by_id = {e.program_id: e for e in events if e.program_id}
    ordered = sorted(events, key=lambda e: e.end_us)
    return by_id, ordered, [e.end_us for e in ordered]


def _match_compile(
    rec: LaunchRecord,
    by_id: dict[str, CompileEvent],
    ordered: list[CompileEvent],
    end_times: list[float],
    attach_window_us: float,
    allow_time_window: bool,
) -> CompileEvent | None:
    """Owning compilation for a helper/anonymous launch, or None.

    Fingerprint identity first (canonical), then module-name prefix
    (helper programs are named after the compilation that emitted
    them), then — for ops-bearing launches only — the bounded
    first-execution window after a compile.  Dispatch-only helpers
    never time-window join: a glue launch that merely HAPPENS to follow
    a compile proves nothing, and claiming it would hide real
    unexplained time (the bucket this ledger exists to expose).
    """
    if rec.program_id and rec.program_id in by_id:
        return by_id[rec.program_id]
    name = rec.module_name or rec.name
    if name:
        for event in ordered:
            if not event.module_name:
                continue
            if name.startswith(event.module_name) or event.module_name.startswith(
                name
            ):
                return event
    if not allow_time_window:
        return None
    # Nearest compile that finished at or before this launch's start,
    # within the attach window.
    idx = bisect.bisect_right(end_times, rec.start_us) - 1
    if idx >= 0:
        event = ordered[idx]
        if rec.start_us - event.end_us <= attach_window_us:
            return event
    return None


def _contained_ops(
    mods: list[XLASpan], ops: list[XLASpan]
) -> tuple[dict[int, float], dict[int, int], list[int]]:
    """Assign each op to the latest-starting module span containing it.

    Returns ``(ops_us by module index, ops count by module index,
    unassigned op indexes)`` — the same containment rule as
    ``xla_spans._sum_ops_by_launch`` so the two stay join-compatible.
    """
    starts = [m.start_us for m in mods]
    ops_us: dict[int, float] = {}
    ops_n: dict[int, int] = {}
    unassigned: list[int] = []
    for i, op in enumerate(ops):
        idx = bisect.bisect_right(starts, op.start_us) - 1
        if idx < 0:
            unassigned.append(i)
            continue
        mod = mods[idx]
        if not op.start_us < mod.start_us + mod.duration_us:
            unassigned.append(i)
            continue
        ops_us[idx] = ops_us.get(idx, 0.0) + op.duration_us
        ops_n[idx] = ops_n.get(idx, 0) + 1
    return ops_us, ops_n, unassigned


def build_ledger(
    spans: Sequence[XLASpan],
    compile_events: Iterable[Any] = (),
    compile_attach_window_us: float = DEFAULT_COMPILE_ATTACH_WINDOW_US,
) -> DeviceLedger:
    """Fold one capture's spans into the device-time ledger.

    ``spans`` is a module+ops span list (``capture(include_ops=True)``
    or :func:`tpuslo.deviceplane.synthetic.synthesize_xprof_trace`
    parsed through ``parse_trace_events``); ``compile_events`` is any
    iterable of :class:`CompileEvent`-shaped records (e.g.
    ``ServeEngine.compile_events`` dicts with ``program_id``/
    ``module_name``/``end_us``).
    """
    ledger = DeviceLedger()
    mods_by_dev: dict[int, list[XLASpan]] = {}
    ops_by_dev: dict[int, list[XLASpan]] = {}
    for span in spans:
        if span.lane == MODULES_LANE:
            mods_by_dev.setdefault(span.device_pid, []).append(span)
        elif span.lane == OPS_LANE:
            ops_by_dev.setdefault(span.device_pid, []).append(span)

    # Satellite lanes: pids that carry ops but no module lane at all —
    # xprof split those ops off their device's timeline.  They are
    # candidates for the lane_window tier, never devices themselves.
    # A satellite lane belongs to exactly ONE device; with overlapping
    # device timelines an op can sit inside several devices' launch
    # windows, so each lane is associated with the device whose module
    # windows contain the MOST of its ops (best containment fit), and
    # only that device may claim from it.
    sorted_mods = {
        pid: sorted(mods, key=lambda s: s.start_us)
        for pid, mods in mods_by_dev.items()
    }
    mod_starts = {
        pid: [m.start_us for m in mods]
        for pid, mods in sorted_mods.items()
    }

    def _containment_count(pid: int, ops: list[XLASpan]) -> int:
        mods = sorted_mods[pid]
        starts = mod_starts[pid]
        n = 0
        for op in ops:
            idx = bisect.bisect_right(starts, op.start_us) - 1
            if idx < 0:
                continue
            mod = mods[idx]
            if op.start_us + op.duration_us <= mod.start_us + mod.duration_us:
                n += 1
        return n

    device_rank = {pid: i for i, pid in enumerate(sorted(mods_by_dev))}
    lane_pids = sorted(
        pid for pid in ops_by_dev if pid not in mods_by_dev
    )
    lane_rank = {pid: i for i, pid in enumerate(lane_pids)}
    orphan_by_dev: dict[int, list[XLASpan]] = {}
    orphan_total = 0
    orphan_unowned = 0
    for lane_pid in lane_pids:
        lane_ops = ops_by_dev[lane_pid]
        orphan_total += len(lane_ops)
        best_pid, best_key = -1, (0, -1)
        for pid in sorted(mods_by_dev):
            n = _containment_count(pid, lane_ops)
            if n == 0:
                continue
            # Containment fit first; on a tie (overlapping device
            # timelines make full-window containment coincidental),
            # prefer rank alignment — xprof emits satellite lanes in
            # device order.
            key = (n, 1 if device_rank[pid] == lane_rank[lane_pid] else 0)
            if key > best_key:
                best_pid, best_key = pid, key
        if best_pid >= 0:
            orphan_by_dev.setdefault(best_pid, []).extend(lane_ops)
        else:
            orphan_unowned += len(lane_ops)
    for pool in orphan_by_dev.values():
        pool.sort(key=lambda s: s.start_us)
    ledger.orphan_ops_count = orphan_total

    by_id, ordered_compiles, compile_ends = _compile_index(compile_events)

    exact_identity = 0
    substantive = 0
    with_own_ops = 0
    anon_with_own_ops = 0

    total_unclaimed = orphan_unowned
    for pid in sorted(mods_by_dev):
        mods = sorted_mods[pid]
        ops = sorted(ops_by_dev.get(pid, ()), key=lambda s: s.start_us)
        device_has_ops = bool(ops)
        orphan_ops = orphan_by_dev.get(pid, [])
        orphan_starts = [o.start_us for o in orphan_ops]
        orphan_claimed = [False] * len(orphan_ops)
        ops_us, ops_n, _unassigned = _contained_ops(mods, ops)

        # Observation window: every span the device emitted, ops
        # included (an op outside any module window still proves the
        # device was observed then).
        lo = min(s.start_us for s in (mods + ops))
        hi = max(s.start_us + s.duration_us for s in (mods + ops))

        # Overlap clip: each launch owns the part of its window no
        # earlier-starting launch already owns; merged busy time is the
        # running union, so owned times sum to it exactly.
        frontier = lo
        busy = 0.0
        records: list[LaunchRecord] = []
        for i, mod in enumerate(mods):
            end = mod.start_us + mod.duration_us
            owned = max(0.0, min(end, hi) - max(mod.start_us, frontier))
            frontier = max(frontier, end)
            busy += owned
            records.append(
                LaunchRecord(
                    name=mod.name,
                    module_name=mod.module_name,
                    program_id=mod.program_id,
                    launch_id=mod.launch_id,
                    device_pid=pid,
                    start_us=mod.start_us,
                    duration_us=mod.duration_us,
                    owned_us=owned,
                    ops_us=ops_us.get(i, 0.0),
                    ops_count=ops_n.get(i, 0),
                )
            )

        ledger.devices.append(
            DeviceWindow(
                device_pid=pid,
                window_start_us=lo,
                window_end_us=hi,
                busy_us=busy,
                idle_gap_us=max(hi - lo, 0.0) - busy,
            )
        )

        # --- tier ladder ------------------------------------------------
        for i, rec in enumerate(records):
            if rec.ops_count > 0:
                rec.ops_source = "own"
                with_own_ops += 1
                if rec.launch_id >= 0:
                    rec.tier = TIER_IDENTITY
                    rec.bucket = BUCKET_JOINED
                    exact_identity += 1
                    substantive += 1
                    continue
                anon_with_own_ops += 1
                rec.reason = REASON_ANONYMOUS
                event = _match_compile(
                    rec, by_id, ordered_compiles, compile_ends,
                    compile_attach_window_us, allow_time_window=True,
                )
                if event is not None:
                    rec.tier = TIER_COMPILE_EVENT
                    rec.bucket = BUCKET_COMPILE
                    substantive += 1
                continue

            # No ops on the launch's own lane: probe the satellite
            # pool for ops fully contained in this launch's window.
            lane_us = 0.0
            lane_n = 0
            start = bisect.bisect_left(orphan_starts, rec.start_us)
            j = start
            launch_end = rec.start_us + rec.duration_us
            while j < len(orphan_ops) and orphan_ops[j].start_us < launch_end:
                if not orphan_claimed[j]:
                    op = orphan_ops[j]
                    if op.start_us + op.duration_us <= launch_end:
                        orphan_claimed[j] = True
                        lane_us += op.duration_us
                        lane_n += 1
                j += 1
            if lane_n > 0:
                rec.ops_us = lane_us
                rec.ops_count = lane_n
                rec.ops_source = "lane"
                rec.reason = REASON_SPLIT_LANE
                if rec.launch_id >= 0:
                    rec.tier = TIER_LANE_WINDOW
                    rec.bucket = BUCKET_JOINED
                    substantive += 1
                else:
                    rec.reason = REASON_ANONYMOUS
                    event = _match_compile(
                        rec, by_id, ordered_compiles, compile_ends,
                        compile_attach_window_us, allow_time_window=True,
                    )
                    if event is not None:
                        rec.tier = TIER_COMPILE_EVENT
                        rec.bucket = BUCKET_COMPILE
                        substantive += 1
                continue

            # Dispatch-only helper (or a launch on an ops-less device).
            if not device_has_ops:
                rec.reason = REASON_NO_OPS_LANE
            elif any(
                rec.start_us <= op.start_us < launch_end for op in ops
            ):
                rec.reason = REASON_OVERLAPPING
            else:
                rec.reason = REASON_NO_CONTAINED_OPS
            event = _match_compile(
                rec, by_id, ordered_compiles, compile_ends,
                compile_attach_window_us, allow_time_window=False,
            )
            if event is not None:
                rec.tier = TIER_COMPILE_EVENT
                rec.bucket = BUCKET_HELPER

        # --- frame tier: step launches bucket the leftover helpers -----
        steps = [
            r for r in records if r.tier in (TIER_IDENTITY, TIER_LANE_WINDOW)
        ]
        step_starts = [s.start_us for s in steps]
        for rec in records:
            if rec.tier != TIER_NONE or rec.ops_count > 0:
                continue
            idx = bisect.bisect_right(step_starts, rec.start_us) - 1
            if idx >= 0:
                rec.tier = TIER_FRAME
                rec.bucket = BUCKET_HELPER

        ledger.launches.extend(records)
        total_unclaimed += orphan_claimed.count(False)

    # Ops-bearing launches after lane recovery (the substantive
    # denominator): own-lane ops + lane-window recoveries.
    launches_with_ops = sum(
        1 for rec in ledger.launches if rec.ops_count > 0
    )

    for rec in ledger.launches:
        ledger.buckets_us[rec.bucket] = (
            ledger.buckets_us.get(rec.bucket, 0.0) + rec.owned_us
        )
        if rec.tier != TIER_NONE:
            ledger.tier_counts[rec.tier] = (
                ledger.tier_counts.get(rec.tier, 0) + 1
            )
        if rec.reason and rec.bucket == BUCKET_UNEXPLAINED:
            ledger.reasons[rec.reason] = ledger.reasons.get(rec.reason, 0) + 1
    ledger.buckets_us[BUCKET_IDLE_GAP] = sum(
        d.idle_gap_us for d in ledger.devices
    )
    for bucket in ALL_BUCKETS:
        ledger.buckets_us.setdefault(bucket, 0.0)

    ledger.launches_with_ops = launches_with_ops
    ledger.orphan_ops_unclaimed = total_unclaimed
    total_launches = len(ledger.launches)
    ledger.raw_join_rate = (
        exact_identity / total_launches if total_launches else 0.0
    )
    ledger.substantive_join_rate = (
        substantive / launches_with_ops if launches_with_ops else 0.0
    )
    ledger.exact_substantive_join_rate = (
        (with_own_ops - anon_with_own_ops) / with_own_ops
        if with_own_ops
        else 0.0
    )
    return ledger


def idle_gap_probe_values(ledger: DeviceLedger) -> dict[str, float]:
    """Device-plane signal values derived from one ledger window —
    the feed for ``device_idle_gap_ms`` (``device_eviction_events_total``
    comes from the runtime's eviction notices, not the trace)."""
    return {"device_idle_gap_ms": round(ledger.idle_gap_ms(), 4)}
