"""Per-dispatch device-time accounting for the serving front door.

The xprof ledger (:mod:`tpuslo.deviceplane.ledger`) is the precise
device-plane truth but needs a profiler capture; the serving loop needs
a number it can afford EVERY dispatch.  On an asynchronous backend the
fused multi-round dispatch returns immediately (enqueue) and the ONE
fused ``device_get`` blocks until the device finishes the chained
rounds — so the read-wait is the host-side proxy for device busy time
per dispatch, and the dispatch call itself measures host dispatch
overhead.  :class:`DispatchLedger` folds both, per step and
cumulatively, and the front door attaches the totals to its self-trace
span attrs (tail-sampled with the PR 5 machinery, no new tracer).

Hot-path discipline: ``note`` is integer arithmetic on a slotted
object — timestamps arrive as ``perf_counter_ns`` deltas from the
caller, never from the wall clock (TPL120).
"""

from __future__ import annotations

from typing import Any


class DispatchLedger:
    """Cumulative + last-step device-time proxy for one serving loop."""

    __slots__ = (
        "steps",
        "dispatch_ns_total",
        "read_ns_total",
        "tokens_total",
        "last_dispatch_ns",
        "last_read_ns",
        "last_tokens",
        "last_slots",
    )

    def __init__(self) -> None:
        self.steps = 0
        self.dispatch_ns_total = 0
        self.read_ns_total = 0
        self.tokens_total = 0
        self.last_dispatch_ns = 0
        self.last_read_ns = 0
        self.last_tokens = 0
        self.last_slots = 0

    def note(
        self, dispatch_ns: int, read_ns: int, tokens: int, slots: int
    ) -> None:
        """Record one fused dispatch's timings (perf_counter_ns deltas)."""
        self.steps += 1
        self.dispatch_ns_total += dispatch_ns
        self.read_ns_total += read_ns
        self.tokens_total += tokens
        self.last_dispatch_ns = dispatch_ns
        self.last_read_ns = read_ns
        self.last_tokens = tokens
        self.last_slots = slots

    @property
    def device_wait_ms_total(self) -> float:
        """Cumulative read-wait: the device-busy proxy."""
        return self.read_ns_total / 1e6

    @property
    def dispatch_ms_total(self) -> float:
        return self.dispatch_ns_total / 1e6

    def last(self) -> dict[str, Any]:
        """The most recent dispatch's span-attr block."""
        return {
            "dispatch_ms": round(self.last_dispatch_ns / 1e6, 4),
            "device_wait_ms": round(self.last_read_ns / 1e6, 4),
            "tokens": self.last_tokens,
            "slots": self.last_slots,
        }

    def totals(self) -> dict[str, Any]:
        tokens = max(self.tokens_total, 1)
        return {
            "steps": self.steps,
            "dispatch_ms_total": round(self.dispatch_ms_total, 3),
            "device_wait_ms_total": round(self.device_wait_ms_total, 3),
            "tokens_total": self.tokens_total,
            "device_wait_ms_per_token": round(
                self.device_wait_ms_total / tokens, 5
            ),
        }
