"""Roofline verdicts: is this launch memory- or compute-bound?

Decode MFU ~1% read as "terrible" in the round-4 evidence while the
same number was ~30% of the HBM roof — the regressions that matter in
serving are memory-bound, and an attribution that names a fault domain
without saying WHICH roof the workload sits under leaves the operator
guessing at the fix (more batch? fewer bytes? faster dispatch?).  This
module folds per-launch bytes and FLOP estimates into a verdict
against the chip's public roofs (v5e: 819 GB/s HBM, 197 TFLOP/s bf16)
and attaches it to serving-path ``IncidentAttribution`` as the
``roofline`` schema block ``sloctl explain`` renders.

The verdict rule is the classical roofline: achieved fractions of each
roof are compared — the binding roof is the one the launch uses the
larger fraction of.  ``detail`` spells out the actionable reading
(memory-bound decode leaves MFU meaningless; compute-bound prefill
leaves bandwidth meaningless).
"""

from __future__ import annotations

from typing import Any

from tpuslo.deviceplane.ledger import DeviceLedger

VERDICT_MEMORY_BOUND = "memory_bound"
VERDICT_COMPUTE_BOUND = "compute_bound"

#: v5e public roofs — the flagship serving chip of the evidence runs.
#: Other chips resolve through the serving-bench tables at call time.
V5E_PEAK_HBM_BW = 819e9
V5E_PEAK_BF16_FLOPS = 197e12


def peaks_for_chip(device_kind: str = "v5e") -> tuple[float, float]:
    """(HBM bytes/s, bf16 FLOP/s) roofs for a device kind — resolved
    through the serving bench's public-spec tables (single source)."""
    from tpuslo.benchmark.serving_bench import (
        PEAK_BF16_FLOPS,
        PEAK_HBM_BW,
        _lookup,
    )

    bw = _lookup(PEAK_HBM_BW, device_kind) or V5E_PEAK_HBM_BW
    flops = _lookup(PEAK_BF16_FLOPS, device_kind) or V5E_PEAK_BF16_FLOPS
    return bw, flops


def decode_step_cost(
    n_params: float,
    kv_cache_bytes: float,
    batch: int = 1,
    param_bytes: float = 2.0,
) -> tuple[float, float]:
    """(bytes, FLOPs) one decode step must move/compute.

    Bytes: weights stream once per step regardless of batch; the dense
    cache reads its FULL allocation every step (same accounting as
    ``serving_bench.decode_step_hbm_bytes``).  FLOPs: 2 MACs per
    parameter per token, ``batch`` tokens per step.
    """
    step_bytes = n_params * param_bytes + kv_cache_bytes
    step_flops = 2.0 * n_params * batch
    return step_bytes, step_flops


def roofline_verdict(
    device_time_ms: float,
    bytes_moved: float,
    flops: float,
    peak_bw: float = V5E_PEAK_HBM_BW,
    peak_flops: float = V5E_PEAK_BF16_FLOPS,
    launch_name: str = "",
) -> dict[str, Any]:
    """Fold one launch's cost estimate into a schema-ready verdict.

    ``device_time_ms`` is the launch's measured device time (ledger
    ``joined`` time for the program); ``bytes_moved``/``flops`` the
    cost model's estimate for one execution.
    """
    seconds = max(device_time_ms, 1e-6) / 1e3
    achieved_bw = bytes_moved / seconds
    achieved_flops = flops / seconds
    bw_frac = achieved_bw / peak_bw if peak_bw else 0.0
    flop_frac = achieved_flops / peak_flops if peak_flops else 0.0
    memory_bound = bw_frac >= flop_frac
    verdict = VERDICT_MEMORY_BOUND if memory_bound else VERDICT_COMPUTE_BOUND
    bound_pct = 100.0 * max(bw_frac, flop_frac)
    if memory_bound:
        detail = (
            f"memory-bound: {100 * bw_frac:.1f}% of the "
            f"{peak_bw / 1e9:.0f} GB/s HBM roof vs "
            f"{100 * flop_frac:.1f}% MFU — MFU is the wrong lens here; "
            "headroom means underfilled DMAs or dispatch overhead, and "
            "the levers are bytes/step (quantized KV/weights) or batch"
        )
    else:
        detail = (
            f"compute-bound: {100 * flop_frac:.1f}% MFU vs "
            f"{100 * bw_frac:.1f}% of the HBM roof — the MXU is the "
            "wall; the levers are FLOPs/token (shorter context, "
            "sparsity) or a bigger chip"
        )
    out: dict[str, Any] = {
        "verdict": verdict,
        "achieved_gb_per_sec": round(achieved_bw / 1e9, 2),
        "peak_gb_per_sec": round(peak_bw / 1e9, 1),
        "hbm_bw_pct": round(100.0 * bw_frac, 2),
        "mfu_pct": round(100.0 * flop_frac, 2),
        "bound_pct": round(bound_pct, 2),
        "device_time_ms": round(device_time_ms, 4),
        "detail": detail,
    }
    if launch_name:
        out["launch"] = launch_name
    return out


def verdict_from_ledger(
    ledger: DeviceLedger,
    bytes_per_step: float,
    flops_per_step: float,
    program_id: str = "",
    peak_bw: float = V5E_PEAK_HBM_BW,
    peak_flops: float = V5E_PEAK_BF16_FLOPS,
) -> dict[str, Any] | None:
    """Roofline verdict for the ledger's serving program.

    Uses the MEAN joined device time per launch of ``program_id`` (or
    of every joined launch when unset) so one stalled step does not
    masquerade as a bandwidth collapse; returns None when the ledger
    joined nothing (no device-time denominator — never invent one).
    """
    times = [
        rec.duration_us / 1e3
        for rec in ledger.launches
        if rec.bucket == "joined"
        and (not program_id or rec.program_id == program_id)
    ]
    if not times:
        return None
    mean_ms = sum(times) / len(times)
    name = program_id or "joined-launch-mean"
    out = roofline_verdict(
        mean_ms, bytes_per_step, flops_per_step,
        peak_bw=peak_bw, peak_flops=peak_flops, launch_name=name,
    )
    out["launches"] = len(times)
    return out


def attach_roofline(attribution: Any, verdict: dict[str, Any]) -> Any:
    """Attach a verdict block to an ``IncidentAttribution`` (the
    ``roofline`` contract block, TPL101/102-governed)."""
    attribution.roofline = dict(verdict)
    return attribution
