"""Device-plane release gate (``m5gate --deviceplane-sweep``).

Three lanes, all seeded and deterministic, all off-chip:

1. **ledger** — synthetic-xprof traces (every pathology the real
   captures showed: lane-split ops, anonymous warmups, dispatch-only
   helpers, orphan glue, idle gaps, one preemption-sized hole) parsed
   through the REAL ``xla_spans.parse_trace_events`` path and folded
   into the ledger.  Contracts: the five buckets sum to total device
   time (1e-6 relative), substantive join rate >= 0.9, unexplained
   share <= 0.1, and the truth counts (steps, lane splits, helpers,
   orphans) land in their expected tiers.
2. **roofline** — serving-path attributions from the real calibrated
   :class:`BayesianAttributor` over faultreplay serving scenarios each
   get a ledger-derived roofline verdict attached; contracts: EVERY
   attribution carries the block, the decode-modeled verdict is
   memory-bound, the prefill-modeled verdict is compute-bound.
3. **heldout** — the calibrated heldout suite with the two new fault
   domains (``tpu_preemption``, ``host_noisy_neighbor``) in the
   training registry: full-domain macro-F1 at noise sigma 1.0 >= 0.96,
   and each new domain's own F1 >= 0.9 at that noise.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

#: Gate floors (the ISSUE 14 acceptance criteria).
MIN_SUBSTANTIVE_JOIN_RATE = 0.9
MAX_UNEXPLAINED_SHARE = 0.1
MIN_HELDOUT_FULL_DOMAIN_F1 = 0.96
MIN_NEW_DOMAIN_F1 = 0.9
HELDOUT_SIGMA = "1.0"

NEW_SCENARIOS = ("preemption_eviction", "noisy_neighbor_cpu")
NEW_DOMAINS = ("tpu_preemption", "host_noisy_neighbor")

#: Serving scenarios whose attributions must carry roofline verdicts.
SERVING_SCENARIOS = (
    "hbm_pressure",
    "xla_recompile_storm",
    "host_offload_stall",
    "preemption_eviction",
    "noisy_neighbor_cpu",
)


@dataclass
class DeviceplaneReport:
    """One sweep's evidence; ``passed`` iff ``failures`` is empty."""

    seed: int = 0
    ledger_runs: list[dict[str, Any]] = field(default_factory=list)
    roofline: dict[str, Any] = field(default_factory=dict)
    heldout: dict[str, Any] = field(default_factory=dict)
    failures: list[str] = field(default_factory=list)

    @property
    def passed(self) -> bool:
        return not self.failures

    def to_dict(self) -> dict[str, Any]:
        return {
            "passed": self.passed,
            "seed": self.seed,
            "ledger_runs": self.ledger_runs,
            "roofline": self.roofline,
            "heldout": self.heldout,
            "failures": list(self.failures),
        }


def _ledger_lane(
    report: DeviceplaneReport, seed: int, steps: int
) -> None:
    from tpuslo.deviceplane.ledger import (
        BUCKET_UNEXPLAINED,
        TIER_COMPILE_EVENT,
        TIER_IDENTITY,
        TIER_LANE_WINDOW,
        build_ledger,
    )
    from tpuslo.deviceplane.synthetic import synthesize_xprof_trace
    from tpuslo.otel.xla_spans import parse_trace_events

    variants = (
        {"name": "steady", "preemption_gap_ms": 0.0, "devices": 1},
        {"name": "preempted", "preemption_gap_ms": 60.0, "devices": 1},
        {"name": "two_device", "preemption_gap_ms": 0.0, "devices": 2},
    )
    for i, variant in enumerate(variants):
        doc, compiles, truth = synthesize_xprof_trace(
            seed=seed + i,
            steps=steps,
            devices=int(variant["devices"]),
            preemption_gap_ms=float(variant["preemption_gap_ms"]),
        )
        spans = parse_trace_events(doc, include_ops=True)
        ledger = build_ledger(spans, compiles)
        run = {
            "variant": variant["name"],
            "truth": truth,
            "ledger": ledger.to_dict(),
        }
        report.ledger_runs.append(run)
        tag = f"ledger[{variant['name']}]"

        total = ledger.total_us
        drift = abs(ledger.bucket_sum_us - total)
        if total <= 0 or drift > 1e-6 * total:
            report.failures.append(
                f"{tag}: buckets do not sum to total device time "
                f"(sum {ledger.bucket_sum_us:.3f}us vs {total:.3f}us)"
            )
        if ledger.substantive_join_rate < MIN_SUBSTANTIVE_JOIN_RATE:
            report.failures.append(
                f"{tag}: substantive join rate "
                f"{ledger.substantive_join_rate:.4f} < "
                f"{MIN_SUBSTANTIVE_JOIN_RATE}"
            )
        if ledger.unexplained_share > MAX_UNEXPLAINED_SHARE:
            report.failures.append(
                f"{tag}: unexplained share "
                f"{ledger.unexplained_share:.4f} > {MAX_UNEXPLAINED_SHARE}"
            )
        tiers = ledger.tier_counts
        if tiers.get(TIER_IDENTITY, 0) != (
            truth["steps"] - truth["lane_split_steps"]
        ):
            report.failures.append(
                f"{tag}: identity-tier count {tiers.get(TIER_IDENTITY, 0)} "
                f"!= non-split steps "
                f"{truth['steps'] - truth['lane_split_steps']}"
            )
        if tiers.get(TIER_LANE_WINDOW, 0) != truth["lane_split_steps"]:
            report.failures.append(
                f"{tag}: lane_window-tier count "
                f"{tiers.get(TIER_LANE_WINDOW, 0)} != lane-split steps "
                f"{truth['lane_split_steps']}"
            )
        if tiers.get(TIER_COMPILE_EVENT, 0) < truth["warmups"]:
            report.failures.append(
                f"{tag}: compile-tier count {tiers.get(TIER_COMPILE_EVENT, 0)}"
                f" < warmup launches {truth['warmups']}"
            )
        unexplained = [
            rec
            for rec in ledger.launches
            if rec.bucket == BUCKET_UNEXPLAINED
        ]
        if len(unexplained) != truth["orphan_helpers"]:
            report.failures.append(
                f"{tag}: unexplained launches {len(unexplained)} != "
                f"orphan helpers {truth['orphan_helpers']} (the ledger "
                "must neither hide nor invent unexplained time)"
            )
        # The preemption variant's idle gap must dwarf the steady one's.
        if variant["name"] == "preempted":
            steady = report.ledger_runs[0]["ledger"]
            gap = run["ledger"]["buckets_ms"]["idle_gap"]
            steady_gap = steady["buckets_ms"]["idle_gap"]
            if gap < steady_gap + 0.9 * float(variant["preemption_gap_ms"]):
                report.failures.append(
                    f"{tag}: preemption gap not visible in the ledger "
                    f"(idle {gap:.1f}ms vs steady {steady_gap:.1f}ms)"
                )


def _roofline_lane(
    report: DeviceplaneReport, seed: int, steps: int, attributor
) -> None:
    from datetime import datetime, timezone

    from tpuslo.deviceplane.ledger import build_ledger
    from tpuslo.deviceplane.roofline import (
        VERDICT_COMPUTE_BOUND,
        VERDICT_MEMORY_BOUND,
        decode_step_cost,
        roofline_verdict,
        verdict_from_ledger,
    )
    from tpuslo.deviceplane.synthetic import (
        STEP_FINGERPRINT,
        synthesize_xprof_trace,
    )
    from tpuslo.faultreplay import generate_fault_samples
    from tpuslo.models.llama import kv_cache_bytes, llama32_1b, param_count
    from tpuslo.otel.xla_spans import parse_trace_events

    # Decode cost model: llama32_1b at batch 8 — the serving lanes'
    # operating point.  Step durations are drawn at decode-realistic
    # times for that model (~30-40% of the v5e HBM roof), so the
    # modeled verdict must be memory-bound (weights+KV stream per
    # step; FLOPs are 2·params·batch).
    cfg = llama32_1b(max_seq_len=1024)
    n_params = param_count(cfg)
    step_bytes, step_flops = decode_step_cost(
        n_params, kv_cache_bytes(cfg, 8), batch=8
    )
    decode_ms = step_bytes / (0.35 * 819e9) * 1e3
    doc, compiles, _truth = synthesize_xprof_trace(
        seed=seed, steps=steps,
        step_dur_us=(decode_ms * 900.0, decode_ms * 1150.0),
    )
    spans = parse_trace_events(doc, include_ops=True)
    ledger = build_ledger(spans, compiles)
    decode_verdict = verdict_from_ledger(
        ledger, step_bytes, step_flops, program_id=STEP_FINGERPRINT
    )
    report.roofline["decode"] = decode_verdict
    if decode_verdict is None:
        report.failures.append(
            "roofline: no joined launches for the serving program — "
            "no device-time denominator"
        )
        return
    if decode_verdict["verdict"] != VERDICT_MEMORY_BOUND:
        report.failures.append(
            "roofline: decode model must be memory-bound, got "
            f"{decode_verdict['verdict']}"
        )

    # Prefill cost model: same weights, 512 tokens of compute per row —
    # the compute-bound contrast case.
    prefill_flops = 2.0 * n_params * 8 * 512
    prefill_verdict = roofline_verdict(
        device_time_ms=decode_verdict["device_time_ms"] * 8,
        bytes_moved=step_bytes,
        flops=prefill_flops,
        launch_name="jit_prefill",
    )
    report.roofline["prefill"] = prefill_verdict
    if prefill_verdict["verdict"] != VERDICT_COMPUTE_BOUND:
        report.failures.append(
            "roofline: prefill model must be compute-bound, got "
            f"{prefill_verdict['verdict']}"
        )

    # Every serving-path attribution carries the block — through the
    # REAL calibrated attributor, not scripted envelopes — and each
    # envelope round-trips the contract validator WITH the block (the
    # block must be schema-legal, not just attached).
    from tpuslo.deviceplane.roofline import attach_roofline
    from tpuslo.schema import SCHEMA_INCIDENT_ATTRIBUTION, validate

    start = datetime(2026, 8, 1, tzinfo=timezone.utc)
    missing = 0
    total = 0
    correct = 0
    for scenario in SERVING_SCENARIOS:
        samples = generate_fault_samples(scenario, 6, start)
        for sample, attribution in zip(
            samples, attributor.attribute_batch(samples)
        ):
            attach_roofline(attribution, decode_verdict)
            total += 1
            payload = attribution.to_dict()
            if "roofline" not in payload:
                missing += 1
            else:
                validate(payload, SCHEMA_INCIDENT_ATTRIBUTION)
            if attribution.predicted_fault_domain == sample.expected_domain:
                correct += 1
    report.roofline["attributions"] = {
        "total": total,
        "with_verdict": total - missing,
        "top1_correct": correct,
    }
    if missing:
        report.failures.append(
            f"roofline: {missing}/{total} serving attributions missing "
            "the roofline block"
        )
    if correct < total:
        report.failures.append(
            f"roofline: only {correct}/{total} serving attributions "
            "named their injected domain on clean profiles"
        )


def _heldout_lane(
    report: DeviceplaneReport, count: int, attributor
) -> None:
    from tpuslo.attribution.calibrate import (
        TRAIN_SCENARIOS,
        heldout_report,
    )

    for scenario in NEW_SCENARIOS:
        if scenario not in TRAIN_SCENARIOS:
            report.failures.append(
                f"heldout: new scenario {scenario} missing from "
                "TRAIN_SCENARIOS — the full-domain axis would not "
                "cover it"
            )
    rep = heldout_report(attributor, count=count)
    report.heldout = {
        "full_domain": rep.full_domain,
        "clean": rep.clean,
        "lognormal": rep.lognormal,
    }
    score = rep.full_domain.get(HELDOUT_SIGMA, 0.0)
    if score < MIN_HELDOUT_FULL_DOMAIN_F1:
        report.failures.append(
            f"heldout: full-domain macro-F1 at sigma {HELDOUT_SIGMA} "
            f"{score:.4f} < {MIN_HELDOUT_FULL_DOMAIN_F1}"
        )

    # Per-class F1 of the two NEW domains at the gate sigma.
    from tpuslo.attribution.calibrate import _base_samples, corrupt
    from tpuslo.attribution.mapper import expected_domains_for
    from tpuslo.attribution.pipeline import macro_f1

    samples = _base_samples(TRAIN_SCENARIOS, count)
    noisy = corrupt(samples, float(HELDOUT_SIGMA), 42 + 4)
    predictions = attributor.attribute_batch(noisy)
    scored = macro_f1(
        noisy,
        predictions,
        domains=sorted({expected_domains_for(s)[0] for s in noisy}),
    )
    new_f1 = {
        s.domain: round(s.f1, 4)
        for s in scored.per_domain
        if s.domain in NEW_DOMAINS
    }
    report.heldout["new_domain_f1"] = new_f1
    for domain in NEW_DOMAINS:
        f1 = new_f1.get(domain, 0.0)
        if f1 < MIN_NEW_DOMAIN_F1:
            report.failures.append(
                f"heldout: {domain} F1 {f1:.4f} < {MIN_NEW_DOMAIN_F1} "
                f"at sigma {HELDOUT_SIGMA}"
            )


def run_deviceplane_sweep(
    seed: int = 1337,
    steps: int = 24,
    heldout_count: int = 25,
    skip_heldout: bool = False,
) -> DeviceplaneReport:
    """Run the full device-plane gate; see the module docstring."""
    from tpuslo.attribution.calibrate import calibrated_attributor

    report = DeviceplaneReport(seed=seed)
    _ledger_lane(report, seed, steps)
    # ONE calibrated fit serves both attribution lanes (the fit is the
    # sweep's single most expensive step).
    attributor = calibrated_attributor()
    _roofline_lane(report, seed, steps, attributor)
    if not skip_heldout:
        _heldout_lane(report, heldout_count, attributor)
    return report
