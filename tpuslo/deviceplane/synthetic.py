"""Seeded synthetic-xprof traces: the ledger's off-chip gate input.

Emits a trace-viewer JSON document (the exact shape
``jax.profiler.trace`` writes and ``xla_spans.parse_trace_events``
consumes — ``ph: "X"`` duration events plus ``thread_name`` metadata
mapping lanes) that reproduces, deterministically, every pathology the
real-chip captures showed:

* **steps** — the serving program's module launches, ``run_id``-stamped,
  each with contained ops-lane events (the identity tier's bread and
  butter);
* **lane-split steps** — some steps' ops land on a satellite pid that
  carries an ops lane but no module lane (xprof splitting op events off
  the device timeline) — only the lane-window tier can recover these;
* **anonymous warmup launches** — module spans without a ``run_id``
  but WITH ops, placed right after their compile event (the
  compile-event tier's case);
* **dispatch-only helpers** — short module launches with no ops
  anywhere (scalar converts, argmax glue), named after their owning
  compilation;
* **orphan helpers** — helpers with no compile-event tie and no step
  frame (trace head), the honest ``unexplained`` remainder;
* **idle gaps** — host think time between steps, plus one optional
  preemption-sized gap.

The generator returns the trace document, the compile-event list, and
a ground-truth dict the parity tests assert the ledger against.
"""

from __future__ import annotations

import random
from typing import Any

from tpuslo.otel.xla_spans import MODULES_LANE, OPS_LANE

#: Lane tids inside a device pid.
_TID_MODULES = 1
_TID_OPS = 2

STEP_PROGRAM = "jit_frontdoor_step"
STEP_FINGERPRINT = "7421988350991137280"
WARMUP_PROGRAM = "jit_prefill_warmup"
WARMUP_FINGERPRINT = "1133557799224466880"
HELPER_NAME = "jit_frontdoor_step.convert_element_type"
ORPHAN_HELPER_NAME = "jit__unattributed_glue"


def _thread_meta(pid: int, tid: int, name: str) -> dict[str, Any]:
    return {
        "ph": "M",
        "name": "thread_name",
        "pid": pid,
        "tid": tid,
        "args": {"name": name},
    }


def _x(pid: int, tid: int, name: str, ts: float, dur: float,
       args: dict[str, Any] | None = None) -> dict[str, Any]:
    out: dict[str, Any] = {
        "ph": "X", "pid": pid, "tid": tid, "name": name,
        "ts": round(ts, 3), "dur": round(dur, 3),
    }
    if args:
        out["args"] = args
    return out


def synthesize_xprof_trace(
    seed: int = 1337,
    steps: int = 24,
    devices: int = 1,
    lane_split_every: int = 5,
    helpers_per_step: int = 1,
    orphan_helpers: int = 2,
    warmup_launches: int = 1,
    preemption_gap_ms: float = 0.0,
    ops_per_step: int = 5,
    step_dur_us: tuple[float, float] = (1800.0, 2600.0),
) -> tuple[dict[str, Any], list[dict[str, Any]], dict[str, Any]]:
    """One seeded capture: ``(trace_doc, compile_events, truth)``.

    ``lane_split_every``: every Nth step's ops move to the satellite
    ops-only pid (0 disables).  ``preemption_gap_ms`` inserts one
    eviction-sized idle gap mid-capture.  ``step_dur_us`` bounds the
    per-step launch duration draw (pass decode-realistic times when
    the consumer folds a cost model over the launches).
    """
    rng = random.Random(seed)
    events: list[dict[str, Any]] = []
    compile_events: list[dict[str, Any]] = []
    truth: dict[str, Any] = {
        "steps": 0,
        "lane_split_steps": 0,
        "helpers": 0,
        "orphan_helpers": 0,
        "warmups": 0,
        "busy_us": 0.0,
        "idle_us": 0.0,
        "window_us": 0.0,
    }

    for d in range(devices):
        pid = 100 + d
        split_pid = 9000 + d  # ops-only satellite lane
        events.append(_thread_meta(pid, _TID_MODULES, MODULES_LANE))
        events.append(_thread_meta(pid, _TID_OPS, OPS_LANE))
        if lane_split_every:
            events.append(_thread_meta(split_pid, _TID_OPS, OPS_LANE))

        t = 1000.0  # µs into the capture
        window_start = t
        busy = 0.0

        # Compile events precede their first executions.
        compile_events.append(
            {
                "program_id": WARMUP_FINGERPRINT,
                "module_name": WARMUP_PROGRAM,
                "end_us": t - 400.0,
                "duration_ms": 180.0,
            }
        )
        compile_events.append(
            {
                "program_id": STEP_FINGERPRINT,
                "module_name": STEP_PROGRAM,
                "end_us": t - 200.0,
                "duration_ms": 950.0,
            }
        )

        # Orphan helpers at the trace head: before any step frame, no
        # compile tie (anonymous name, no fingerprint) — these MUST
        # land in unexplained.
        for _ in range(orphan_helpers):
            dur = rng.uniform(3.0, 9.0)
            events.append(
                _x(pid, _TID_MODULES, ORPHAN_HELPER_NAME, t, dur)
            )
            busy += dur
            truth["orphan_helpers"] += 1
            t += dur + rng.uniform(2.0, 6.0)

        # Anonymous warmup launches WITH ops, right after the warmup
        # compile: the compile-event tier's case.
        for _ in range(warmup_launches):
            dur = rng.uniform(400.0, 700.0)
            events.append(
                _x(
                    pid, _TID_MODULES,
                    f"{WARMUP_PROGRAM}({WARMUP_FINGERPRINT})", t, dur,
                )
            )
            cursor = t + 2.0
            for _ in range(3):
                op_dur = rng.uniform(20.0, 60.0)
                events.append(
                    _x(
                        pid, _TID_OPS, "fusion.warmup", cursor, op_dur,
                        {"hlo_category": "fusion"},
                    )
                )
                cursor += op_dur + 1.0
            busy += dur
            truth["warmups"] += 1
            t += dur + rng.uniform(20.0, 50.0)

        for step in range(steps):
            run_id = step + 1
            dur = rng.uniform(*step_dur_us)
            events.append(
                _x(
                    pid, _TID_MODULES,
                    f"{STEP_PROGRAM}({STEP_FINGERPRINT})", t, dur,
                    {"run_id": run_id},
                )
            )
            split = bool(lane_split_every) and (
                step % lane_split_every == lane_split_every - 1
            )
            ops_pid = split_pid if split else pid
            cursor = t + 4.0
            for k in range(ops_per_step):
                op_dur = rng.uniform(40.0, 160.0)
                if cursor + op_dur > t + dur - 2.0:
                    break
                events.append(
                    _x(
                        ops_pid, _TID_OPS, f"fusion.{k}", cursor, op_dur,
                        {"hlo_category": "fusion"},
                    )
                )
                cursor += op_dur + rng.uniform(1.0, 8.0)
            busy += dur
            truth["steps"] += 1
            if split:
                truth["lane_split_steps"] += 1
            t += dur

            # Dispatch-only helpers inside the step frame, named after
            # the owning compilation (compile tier catches them by
            # module-name prefix; the frame tier is the backstop).
            for _ in range(helpers_per_step):
                gap = rng.uniform(2.0, 6.0)
                t += gap
                helper_dur = rng.uniform(4.0, 14.0)
                events.append(
                    _x(pid, _TID_MODULES, HELPER_NAME, t, helper_dur)
                )
                busy += helper_dur
                truth["helpers"] += 1
                t += helper_dur

            # Host think time between steps.
            t += rng.uniform(120.0, 420.0)
            if preemption_gap_ms > 0.0 and step == steps // 2:
                t += preemption_gap_ms * 1000.0

        # Close the device window with one final tiny step so the
        # window end is a module span end (keeps the idle accounting
        # independent of the last host gap).
        dur = rng.uniform(*step_dur_us)
        events.append(
            _x(
                pid, _TID_MODULES,
                f"{STEP_PROGRAM}({STEP_FINGERPRINT})", t, dur,
                {"run_id": steps + 1},
            )
        )
        cursor = t + 4.0
        for k in range(2):
            op_dur = rng.uniform(40.0, 120.0)
            events.append(
                _x(
                    pid, _TID_OPS, f"fusion.tail{k}", cursor, op_dur,
                    {"hlo_category": "fusion"},
                )
            )
            cursor += op_dur + 2.0
        busy += dur
        truth["steps"] += 1
        t += dur

        truth["busy_us"] += busy
        truth["window_us"] += t - window_start
        truth["idle_us"] += (t - window_start) - busy

    return {"traceEvents": events}, compile_events, truth
