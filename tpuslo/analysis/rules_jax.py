"""TPL160-TPL163: trace discipline for the JAX serving plane.

tpulint's earlier families machine-check the *agent* plane; this one
checks the dispatch-layer invariants of the JAX plane the toolkit
exists to observe (``tpuslo/models/``, ``tpuslo/ops/``,
``tpuslo/parallel/`` — :data:`tpuslo.analysis.hotpaths.JAX_PLANE_PREFIXES`).
BENCH_r05 showed why these must be *checked*, not hoped for: a
perfect-acceptance speculative-decode path measured 5x SLOWER than
plain decode (``spec_measured_speedup`` 0.192) purely from eager
dispatch + host-sync churn per round.  Every static finding here has a
dynamic counterpart in :mod:`tpuslo.analysis.jitaudit`.

* **TPL160 — host-sync hazards in registered hot loops.**  Inside the
  for/while bodies of the decode/verify loops registered in
  :data:`tpuslo.analysis.hotpaths.JAX_HOT_LOOPS`: ``.item()`` /
  ``.tolist()`` on values not provably host-side,
  ``int()``/``float()``/``bool()``/``np.asarray()`` applied to values
  produced by jnp/jax calls, and ``block_until_ready``.  Each is a
  device->host round-trip per iteration; the sanctioned pattern is one
  fused ``jax.device_get`` per iteration, whose results are exempt.

* **TPL161 — retrace hazards.**  ``jax.jit`` constructed inside a
  loop, or inside a function/method without a caching decorator
  (``functools.lru_cache``/``cache``) — a fresh wrapper is a fresh
  executable cache, so identical programs recompile per call; bare
  ``@jax.jit`` defs nested in uncached functions; value-dependent
  Python branching on a traced (non-static) parameter of a jitted
  function; non-literal ``static_argnums``/``static_argnames``.

* **TPL162 — dtype-promotion drift.**  ``jnp.asarray``/``jnp.array``/
  ``jnp.zeros``/``jnp.ones``/``jnp.full``/``jnp.empty`` without an
  explicit dtype inside a loop: weak-typed results re-key the jit
  cache when promotion flips (x64 flags, int32/int64 hosts) and upload
  a fresh scalar per iteration.

* **TPL163 — donation misses.**  ``jax.jit`` over a function that
  threads a KV cache / optimizer state (parameter named in
  :data:`DONATABLE_PARAMS`) without ``donate_argnums``/
  ``donate_argnames``: un-donated decode copies the full
  (L, B, S_max, KV, HD) cache pair every step.

All four are repo-scoped with the whole JAX plane as rule anchors, so
``tpulint --changed`` runs them whenever any plane file is touched.
Suppress intentional exceptions per line with ``# tpulint:
disable=TPL16x`` plus a reason — see docs/static-analysis.md.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from tpuslo.analysis.core import FileContext, Finding, RepoContext, Rule
from tpuslo.analysis.hotpaths import JAX_HOT_LOOPS, JAX_PLANE_PREFIXES
from tpuslo.analysis.rules_hotpath import _function_index

_MANIFEST_REL = "tpuslo/analysis/hotpaths.py"

#: Parameter names that carry large mutable device state through a
#: jitted step; threading one through undonated is a per-step copy.
DONATABLE_PARAMS = frozenset(
    {"cache", "kv", "kv_cache", "cache_t", "cache_d", "state", "opt_state"}
)

_CACHING_DECORATORS = frozenset({"lru_cache", "cache"})
_SCALAR_CASTS = frozenset({"int", "float", "bool"})
_DTYPE_CTORS = {
    # name -> index of the positional arg that would carry the dtype
    "asarray": 1,
    "array": 1,
    "zeros": 1,
    "ones": 1,
    "empty": 1,
    "full": 2,
}


def _dotted(node: ast.AST) -> str | None:
    """``jax.device_get`` for Attribute chains, ``print`` for Names."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _root_name(node: ast.AST) -> str | None:
    """Base Name of a Subscript/Attribute/unary chain (``x[0].T`` -> x)."""
    while isinstance(node, (ast.Subscript, ast.Attribute, ast.Starred)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_device_call(node: ast.AST) -> bool:
    """A call whose result lives on device: jnp.*, jax.* (except the
    explicit host reads), jax.random.*, lax.*, and method chains on
    any of those (``jnp.argmax(...).astype(...)``)."""
    if not isinstance(node, ast.Call):
        return False
    dotted = _dotted(node.func)
    if not dotted:
        if isinstance(node.func, ast.Attribute):
            # Method on another call's result inherits its placement.
            return _is_device_call(node.func.value)
        return False
    root = dotted.split(".", 1)[0]
    if root == "jnp" or root == "lax":
        return True
    if root == "jax":
        return dotted not in (
            "jax.device_get",
            "jax.block_until_ready",
        )
    return False


def _is_host_call(node: ast.AST) -> bool:
    """A call whose result is host-side: device_get, np.*, scalar
    casts, list/len, and method chains on those
    (``jax.device_get(x).tolist()``)."""
    if not isinstance(node, ast.Call):
        return False
    dotted = _dotted(node.func)
    if not dotted:
        if isinstance(node.func, ast.Attribute):
            return _is_host_call(node.func.value)
        return False
    if dotted == "jax.device_get" or dotted == "device_get":
        return True
    root = dotted.split(".", 1)[0]
    if root in ("np", "numpy"):
        return True
    return dotted in ("int", "float", "bool", "list", "len", "tuple")


def _assigned_names(target: ast.AST) -> Iterator[str]:
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _assigned_names(elt)


def _classify_names(
    fn: ast.FunctionDef | ast.AsyncFunctionDef,
) -> tuple[set[str], set[str]]:
    """(device_names, host_names) assigned anywhere in ``fn``.

    A name is *device* when any assignment binds it to a jnp/jax call
    (device wins over host on conflict — flagging a sync on a
    sometimes-device value is the safe direction); *host* when bound
    from ``jax.device_get``/np/scalar casts.
    """
    device: set[str] = set()
    host: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            targets: list[ast.AST] = list(node.targets)
            value = node.value
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
            value = node.value
            if value is None:
                continue
        else:
            continue
        if _is_device_call(value):
            bucket = device
        elif _is_host_call(value):
            bucket = host
        else:
            continue
        for target in targets:
            bucket.update(_assigned_names(target))
    return device, host - device


def _provably_host(node: ast.AST, host: set[str], device: set[str]) -> bool:
    """Receiver is a host-side value: rooted at a device_get/np call or
    at a name only ever host-assigned."""
    base = node
    while isinstance(base, (ast.Subscript, ast.Attribute)):
        base = base.value
    if isinstance(base, ast.Call):
        return _is_host_call(base)
    if isinstance(base, ast.Name):
        return base.id in host and base.id not in device
    return False


def _loop_bodies(
    fn: ast.FunctionDef | ast.AsyncFunctionDef,
) -> Iterator[ast.AST]:
    """Every node inside a for/while loop of ``fn``, once — nested
    loops are walked by their enclosing loop's traversal, so yielding
    their own walk too would double-report each hazard."""
    seen: set[int] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
            for child in node.body + node.orelse:
                for sub in ast.walk(child):
                    if id(sub) not in seen:
                        seen.add(id(sub))
                        yield sub


def _jit_static_params(
    fn: ast.FunctionDef | ast.AsyncFunctionDef,
) -> tuple[bool, set[str]] | None:
    """(is_jitted, static param names) when ``fn`` is decorated with
    jax.jit (bare or via partial); None when it is not."""
    for deco in fn.decorator_list:
        if _dotted(deco) == "jax.jit":
            return True, set()
        if (
            isinstance(deco, ast.Call)
            and deco.args
            and _dotted(deco.func) in ("partial", "functools.partial")
            and _dotted(deco.args[0]) == "jax.jit"
        ):
            params = [a.arg for a in fn.args.args]
            static: set[str] = set()
            for kw in deco.keywords:
                if kw.arg == "static_argnums":
                    for idx in _literal_ints(kw.value):
                        if 0 <= idx < len(params):
                            static.add(params[idx])
                elif kw.arg == "static_argnames":
                    static.update(_literal_strs(kw.value))
            return True, static
    return None


def _literal_ints(node: ast.AST) -> list[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            out.extend(_literal_ints(elt))
        return out
    return []


def _literal_strs(node: ast.AST) -> list[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            out.extend(_literal_strs(elt))
        return out
    return []


def _is_literal_argnums(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, str))
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_literal_argnums(e) for e in node.elts)
    return False


class _Scope:
    """Ancestry walk: every node with its enclosing functions/loops."""

    def __init__(self, tree: ast.Module):
        #: node -> (enclosing defs outermost-first, inside_loop)
        self.items: list[tuple[ast.AST, tuple[ast.AST, ...], bool]] = []
        self._walk(tree, (), False)

    def _walk(
        self, node: ast.AST, defs: tuple[ast.AST, ...], in_loop: bool
    ) -> None:
        for child in ast.iter_child_nodes(node):
            # The child itself is attributed to the ENCLOSING chain (a
            # def is not nested inside itself); recursion then extends
            # the chain for the child's own body.
            self.items.append((child, defs, in_loop))
            child_defs = defs
            child_loop = in_loop
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_defs = defs + (child,)
                child_loop = False  # a nested def is a new call frame
            elif isinstance(child, (ast.For, ast.While, ast.AsyncFor)):
                child_loop = True
            self._walk(child, child_defs, child_loop)


def _has_caching_decorator(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    for deco in fn.decorator_list:
        target = deco.func if isinstance(deco, ast.Call) else deco
        dotted = _dotted(target) or ""
        if dotted.split(".")[-1] in _CACHING_DECORATORS:
            return True
    return False


class TraceDisciplineRule(Rule):
    """TPL160-163 over the JAX plane; see the module docstring."""

    code = "TPL160"
    codes = ("TPL160", "TPL161", "TPL162", "TPL163")
    name = "trace-discipline"
    rationale = (
        "the JAX serving plane must not host-sync inside registered "
        "decode/verify loops, rebuild jit wrappers per call, drift "
        "dtypes in hot loops, or thread KV caches undonated"
    )
    #: The whole plane rides along on --changed runs, so touching any
    #: models/ops/parallel file re-checks every plane contract.
    repo_anchors = JAX_PLANE_PREFIXES + (_MANIFEST_REL,)

    def __init__(
        self,
        hot_loops: tuple[tuple[str, str], ...] = JAX_HOT_LOOPS,
        plane_prefixes: tuple[str, ...] = JAX_PLANE_PREFIXES,
    ):
        self._hot_loops = hot_loops
        self._plane = plane_prefixes

    def check_repo(self, repo: RepoContext) -> Iterable[Finding]:
        if not (repo.root / _MANIFEST_REL).exists():
            # The manifest governs the repo that contains it (the
            # hotpaths discipline); fixture trees have nothing to hold.
            return ()
        findings: list[Finding] = []
        plane = [
            f
            for f in repo.files
            if f.tree is not None and f.rel.startswith(self._plane)
        ]
        findings.extend(self._check_hot_loops(repo))
        param_index = self._param_index(plane)
        for ctx in plane:
            findings.extend(self._check_file(ctx, param_index))
        return findings

    # --- TPL160: host syncs inside registered hot loops ----------------

    def _check_hot_loops(self, repo: RepoContext) -> Iterator[Finding]:
        indexes: dict[str, dict] = {}
        for rel, qualname in self._hot_loops:
            ctx = repo.by_rel.get(rel)
            if ctx is None or ctx.tree is None:
                yield Finding(
                    _MANIFEST_REL,
                    1,
                    "TPL160",
                    f"JAX_HOT_LOOPS entry {rel}:{qualname} points at a "
                    "missing or unparseable file — update the manifest "
                    "with the move",
                )
                continue
            if rel not in indexes:
                indexes[rel] = _function_index(ctx.tree)
            fn = indexes[rel].get(qualname)
            if fn is None:
                yield Finding(
                    _MANIFEST_REL,
                    1,
                    "TPL160",
                    f"JAX_HOT_LOOPS entry {rel}:{qualname} not found — "
                    "update the manifest with the rename",
                )
                continue
            device, host = _classify_names(fn)
            for node in _loop_bodies(fn):
                if not isinstance(node, ast.Call):
                    continue
                yield from self._sync_hazard(
                    ctx.rel, qualname, node, device, host
                )

    def _sync_hazard(
        self,
        rel: str,
        qualname: str,
        node: ast.Call,
        device: set[str],
        host: set[str],
    ) -> Iterator[Finding]:
        dotted = _dotted(node.func) or ""
        if dotted in ("jax.block_until_ready",) or (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "block_until_ready"
        ):
            yield Finding(
                rel,
                node.lineno,
                "TPL160",
                f"hot loop {qualname} calls block_until_ready inside "
                "the loop (a full device sync per iteration)",
            )
            return
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in ("item", "tolist")
            and not node.args
        ):
            if not _provably_host(node.func.value, host, device):
                yield Finding(
                    rel,
                    node.lineno,
                    "TPL160",
                    f"hot loop {qualname} calls .{node.func.attr}() on "
                    "a value not provably host-side (device sync per "
                    "iteration; read once via jax.device_get)",
                )
            return
        if dotted in _SCALAR_CASTS and len(node.args) == 1:
            root = _root_name(node.args[0])
            if (root and root in device) or _is_device_call(node.args[0]):
                yield Finding(
                    rel,
                    node.lineno,
                    "TPL160",
                    f"hot loop {qualname} calls {dotted}() on a device "
                    "value (blocking scalar transfer per iteration; "
                    "batch the read through jax.device_get)",
                )
            return
        if dotted in ("np.asarray", "np.array", "numpy.asarray", "numpy.array"):
            if node.args:
                root = _root_name(node.args[0])
                if (root and root in device) or _is_device_call(node.args[0]):
                    yield Finding(
                        rel,
                        node.lineno,
                        "TPL160",
                        f"hot loop {qualname} materializes a device "
                        f"array via {dotted} (host sync per iteration; "
                        "use jax.device_get)",
                    )

    # --- file-scoped TPL161/162 + call sites for TPL163 -----------------

    def _param_index(self, plane: list[FileContext]) -> dict[str, list[str]]:
        """Top-level function name -> parameter names, plane-wide (for
        resolving what a ``jax.jit(partial(f, ...))`` wraps)."""
        index: dict[str, list[str]] = {}
        for ctx in plane:
            assert ctx.tree is not None
            for node in ctx.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    index[node.name] = [a.arg for a in node.args.args]
        return index

    def _check_file(
        self, ctx: FileContext, param_index: dict[str, list[str]]
    ) -> Iterator[Finding]:
        assert ctx.tree is not None
        scope = _Scope(ctx.tree)
        local_defs = {
            node.name: [a.arg for a in node.args.args]
            for node, _defs, _loop in scope.items
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for node, defs, in_loop in scope.items:
            if isinstance(node, ast.Call) and _dotted(node.func) == "jax.jit":
                yield from self._jit_site(
                    ctx, node, defs, in_loop, local_defs, param_index
                )
            elif isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and defs:
                jitted = _jit_static_params(node)
                if jitted is not None and not any(
                    _has_caching_decorator(d)
                    for d in defs
                    if isinstance(d, (ast.FunctionDef, ast.AsyncFunctionDef))
                ):
                    yield Finding(
                        ctx.rel,
                        node.lineno,
                        "TPL161",
                        f"@jax.jit def {node.name} nested in an uncached "
                        "function retraces per enclosing call — hoist it "
                        "or cache the builder with functools.lru_cache",
                    )
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                jitted = _jit_static_params(node)
                if jitted is not None:
                    yield from self._traced_branching(ctx, node, jitted[1])
                    yield from self._decorator_jit_site(ctx, node)
            if in_loop and isinstance(node, ast.Call):
                yield from self._dtype_drift(ctx, node)

    def _jit_site(
        self,
        ctx: FileContext,
        node: ast.Call,
        defs: tuple[ast.AST, ...],
        in_loop: bool,
        local_defs: dict[str, list[str]],
        param_index: dict[str, list[str]],
    ) -> Iterator[Finding]:
        if in_loop:
            yield Finding(
                ctx.rel,
                node.lineno,
                "TPL161",
                "jax.jit constructed inside a loop — every iteration "
                "builds a fresh wrapper with an empty executable cache "
                "(guaranteed retrace); build once outside the loop",
            )
        elif defs and not any(
            _has_caching_decorator(d)
            for d in defs
            if isinstance(d, (ast.FunctionDef, ast.AsyncFunctionDef))
        ):
            fn_name = defs[-1].name if hasattr(defs[-1], "name") else "?"
            yield Finding(
                ctx.rel,
                node.lineno,
                "TPL161",
                f"jax.jit constructed per call of {fn_name} — identical "
                "programs recompile for every call; memoize the builder "
                "with functools.lru_cache (the serve.py shared-kernel "
                "discipline)",
            )
        for kw in node.keywords:
            if kw.arg in ("static_argnums", "static_argnames"):
                if not _is_literal_argnums(kw.value):
                    yield Finding(
                        ctx.rel,
                        node.lineno,
                        "TPL161",
                        f"{kw.arg} must be a literal int/str (tuple): a "
                        "computed value can vary between builds and "
                        "silently re-key the jit cache",
                    )
        # TPL163: donation misses on cache-threading targets.
        params = self._wrapped_params(node, local_defs, param_index)
        donatable = sorted(DONATABLE_PARAMS.intersection(params or ()))
        if donatable and not any(
            kw.arg in ("donate_argnums", "donate_argnames")
            for kw in node.keywords
        ):
            yield Finding(
                ctx.rel,
                node.lineno,
                "TPL163",
                "jax.jit threads large mutable state "
                f"({', '.join(donatable)}) without donate_argnums — "
                "un-donated steps copy the full buffers every call",
            )

    def _decorator_jit_site(
        self,
        ctx: FileContext,
        fn: ast.FunctionDef | ast.AsyncFunctionDef,
    ) -> Iterator[Finding]:
        """TPL161/163 on decorator-form jits (bare ``@jax.jit`` and
        ``@partial(jax.jit, ...)``) — the same contracts
        :meth:`_jit_site` enforces on call-form sites, which never see
        decorators (``@jax.jit`` is an Attribute, ``@partial(...)``'s
        call target is partial)."""
        for deco in fn.decorator_list:
            keywords: list[ast.keyword] = []
            if _dotted(deco) == "jax.jit":
                pass  # bare form: no kwargs, donation still checkable
            elif (
                isinstance(deco, ast.Call)
                and deco.args
                and _dotted(deco.func) in ("partial", "functools.partial")
                and _dotted(deco.args[0]) == "jax.jit"
            ):
                keywords = deco.keywords
            else:
                continue
            for kw in keywords:
                if kw.arg in ("static_argnums", "static_argnames"):
                    if not _is_literal_argnums(kw.value):
                        yield Finding(
                            ctx.rel,
                            fn.lineno,
                            "TPL161",
                            f"{kw.arg} must be a literal int/str (tuple)"
                            ": a computed value can vary between builds "
                            "and silently re-key the jit cache",
                        )
            donatable = sorted(
                DONATABLE_PARAMS.intersection(
                    a.arg for a in fn.args.args
                )
            )
            if donatable and not any(
                kw.arg in ("donate_argnums", "donate_argnames")
                for kw in keywords
            ):
                yield Finding(
                    ctx.rel,
                    fn.lineno,
                    "TPL163",
                    "jax.jit threads large mutable state "
                    f"({', '.join(donatable)}) without donate_argnums "
                    "— un-donated steps copy the full buffers every "
                    "call",
                )

    def _wrapped_params(
        self,
        node: ast.Call,
        local_defs: dict[str, list[str]],
        param_index: dict[str, list[str]],
    ) -> list[str] | None:
        if not node.args:
            return None
        target = node.args[0]
        if (
            isinstance(target, ast.Call)
            and _dotted(target.func) in ("partial", "functools.partial")
            and target.args
        ):
            bound = {kw.arg for kw in target.keywords if kw.arg}
            inner = self._wrapped_name_params(
                target.args[0], local_defs, param_index
            )
            if inner is None:
                return None
            return [p for p in inner if p not in bound]
        if isinstance(target, ast.Lambda):
            return [a.arg for a in target.args.args]
        return self._wrapped_name_params(target, local_defs, param_index)

    def _wrapped_name_params(
        self,
        target: ast.AST,
        local_defs: dict[str, list[str]],
        param_index: dict[str, list[str]],
    ) -> list[str] | None:
        name = None
        if isinstance(target, ast.Name):
            name = target.id
        elif isinstance(target, ast.Attribute):
            name = target.attr
        if name is None:
            return None
        return local_defs.get(name) or param_index.get(name)

    def _traced_branching(
        self,
        ctx: FileContext,
        fn: ast.FunctionDef | ast.AsyncFunctionDef,
        static: set[str],
    ) -> Iterator[Finding]:
        params = {a.arg for a in fn.args.args} - static
        for node in ast.walk(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            for name in self._bare_names_in_test(node.test):
                if name in params:
                    yield Finding(
                        ctx.rel,
                        node.lineno,
                        "TPL161",
                        f"Python branch on traced argument {name!r} "
                        "inside a jitted function — value-dependent "
                        "control flow retraces (or fails concretization)"
                        "; use lax.cond/where or make it static",
                    )

    def _bare_names_in_test(self, test: ast.AST) -> Iterator[str]:
        """Bare Name operands of a branch test — NOT attributes or
        subscripts (``x.ndim``/``x.shape[0]`` branching is static and
        legitimate), and NOT identity tests against None (``mask is
        None`` keys on pytree structure, part of the jit cache key —
        the canonical optional-argument idiom never retraces)."""
        if isinstance(test, ast.Name):
            yield test.id
        elif isinstance(test, ast.BoolOp):
            for value in test.values:
                yield from self._bare_names_in_test(value)
        elif isinstance(test, ast.UnaryOp):
            yield from self._bare_names_in_test(test.operand)
        elif isinstance(test, ast.Compare):
            if all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
            ) and any(
                isinstance(c, ast.Constant) and c.value is None
                for c in [test.left, *test.comparators]
            ):
                return
            for operand in [test.left, *test.comparators]:
                if isinstance(operand, ast.Name):
                    yield operand.id

    def _dtype_drift(self, ctx: FileContext, node: ast.Call) -> Iterator[Finding]:
        dotted = _dotted(node.func) or ""
        if not dotted.startswith(("jnp.", "jax.numpy.")):
            return
        ctor = dotted.split(".")[-1]
        dtype_pos = _DTYPE_CTORS.get(ctor)
        if dtype_pos is None:
            return
        if len(node.args) > dtype_pos:
            return
        if any(kw.arg == "dtype" for kw in node.keywords):
            return
        yield Finding(
            ctx.rel,
            node.lineno,
            "TPL162",
            f"jnp.{ctor} without an explicit dtype inside a loop — "
            "weak-typed results re-key the jit cache when promotion "
            "flips and churn per-iteration uploads; pass dtype",
        )
