"""TPL101/TPL102/TPL140/TPL150: repo-contract drift rules.

The toolkit's boundaries are JSON contracts: every emitted event
crosses a schema in ``tpuslo/schema/contracts/``, every config file is
validated against the ``v1alpha1`` toolkit-config schema, and every
metric series is supposed to be visible on a dashboard.  Each of those
contracts has two sides that can silently drift apart; these rules
re-derive both sides (dataclass AST vs schema JSON, loader AST vs
schema JSON, registry text vs dashboards/docs) on every lint run.

* **TPL101** — schema ↔ dataclass drift: every contract property must
  be a dataclass field and vice versa, with compatible types.
* **TPL102** — required-emission drift: a *required* contract property
  must be emitted unconditionally by the dataclass's ``to_dict``;
  an omit-when-falsy emission of a required key produces payloads the
  contract rejects.
* **TPL140** — config drift: every key in the toolkit-config schema
  must be read by ``toolkitcfg.py`` (dataclass field + merge-section
  read + ``to_dict`` emission) and vice versa.
* **TPL150** — metrics drift: every series registered in
  ``AgentMetrics`` must be referenced by a dashboard or a doc
  (formerly ``tools/metrics_drift_check.py``).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Any, Iterable

from tpuslo.analysis.core import Finding, RepoContext, Rule

_TYPES_REL = "tpuslo/schema/types.py"
_CFG_REL = "tpuslo/config/toolkitcfg.py"
_REGISTRY_REL = "tpuslo/metrics/registry.py"

#: dataclass name -> (schema file, JSON-pointer-ish path to its
#: (sub)schema inside that file).  Nested envelope types are checked
#: against the exact subschema their parent embeds.
SCHEMA_BINDINGS: dict[str, tuple[str, tuple[str, ...]]] = {
    "SLOEvent": ("tpuslo/schema/contracts/v1/slo-event.schema.json", ()),
    "IncidentAttribution": (
        "tpuslo/schema/contracts/v1/incident-attribution.schema.json",
        (),
    ),
    "Evidence": (
        "tpuslo/schema/contracts/v1/incident-attribution.schema.json",
        ("properties", "evidence", "items"),
    ),
    "SLOImpact": (
        "tpuslo/schema/contracts/v1/incident-attribution.schema.json",
        ("properties", "slo_impact"),
    ),
    "FaultHypothesis": (
        "tpuslo/schema/contracts/v1/incident-attribution.schema.json",
        ("properties", "fault_hypotheses", "items"),
    ),
    "ProbeEventV1": (
        "tpuslo/schema/contracts/v1alpha1/probe-event.schema.json",
        (),
    ),
    "ConnTuple": (
        "tpuslo/schema/contracts/v1alpha1/probe-event.schema.json",
        ("properties", "conn_tuple"),
    ),
    "TPURef": (
        "tpuslo/schema/contracts/v1alpha1/probe-event.schema.json",
        ("properties", "tpu"),
    ),
}

#: Python annotation (normalized) -> acceptable JSON-schema type names.
_PY_TO_JSON: dict[str, frozenset[str]] = {
    "str": frozenset({"string"}),
    "int": frozenset({"integer", "number"}),
    "float": frozenset({"number"}),
    "bool": frozenset({"boolean"}),
    "datetime": frozenset({"string"}),  # rfc3339-serialized
}


@dataclass(slots=True)
class _Field:
    name: str
    annotation: str
    has_default: bool
    lineno: int


def _dataclass_fields(node: ast.ClassDef) -> list[_Field]:
    fields: list[_Field] = []
    for stmt in node.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(
            stmt.target, ast.Name
        ):
            if isinstance(stmt.annotation, ast.Constant):
                annotation = str(stmt.annotation.value)
            else:
                annotation = ast.unparse(stmt.annotation)
            fields.append(
                _Field(
                    stmt.target.id,
                    annotation,
                    stmt.value is not None,
                    stmt.lineno,
                )
            )
    return fields


def _normalize_annotation(annotation: str) -> str:
    out = annotation.replace('"', "").replace("'", "").strip()
    for suffix in (" | None", "| None"):
        if out.endswith(suffix):
            out = out[: -len(suffix)].strip()
    return out


def _json_types_for(annotation: str) -> frozenset[str] | None:
    """Acceptable JSON types for a field annotation; None = unchecked."""
    norm = _normalize_annotation(annotation)
    if norm in _PY_TO_JSON:
        return _PY_TO_JSON[norm]
    if norm.startswith(("dict[", "Dict[")) or norm == "dict":
        return frozenset({"object"})
    if norm.startswith(("list[", "List[")) or norm == "list":
        return frozenset({"array"})
    if norm in SCHEMA_BINDINGS:  # nested envelope dataclass
        return frozenset({"object"})
    return None  # Any / unknown: no claim


def _unconditional_to_dict_keys(cls_node: ast.ClassDef) -> set[str] | None:
    """Keys ``to_dict`` emits on every call; None = cannot analyze.

    Unconditional means: a string key of a dict literal assigned or
    returned at the *top level* of ``to_dict`` (not nested under an
    ``if``), or a top-level ``out["key"] = ...`` store.  A
    ``dataclasses.asdict(self)`` body emits every field.
    """
    to_dict = next(
        (
            stmt
            for stmt in cls_node.body
            if isinstance(stmt, ast.FunctionDef) and stmt.name == "to_dict"
        ),
        None,
    )
    if to_dict is None:
        return None
    for sub in ast.walk(to_dict):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr == "asdict"
        ) or (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Name)
            and sub.func.id == "asdict"
        ):
            return {f.name for f in _dataclass_fields(cls_node)}
    keys: set[str] = set()

    def dict_keys(node: ast.expr) -> None:
        if isinstance(node, ast.Dict):
            for key in node.keys:
                if isinstance(key, ast.Constant) and isinstance(
                    key.value, str
                ):
                    keys.add(key.value)

    for stmt in to_dict.body:  # top level only: ifs are conditional
        if isinstance(stmt, ast.Assign):
            dict_keys(stmt.value)
            for target in stmt.targets:
                if isinstance(target, ast.Subscript) and isinstance(
                    target.slice, ast.Constant
                ):
                    if isinstance(target.slice.value, str):
                        keys.add(target.slice.value)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            dict_keys(stmt.value)
        elif isinstance(stmt, ast.Return) and stmt.value is not None:
            dict_keys(stmt.value)
    return keys


def _walk_pointer(schema: Any, pointer: tuple[str, ...]) -> Any | None:
    node = schema
    for part in pointer:
        if not isinstance(node, dict):
            return None
        node = node.get(part)
    return node if isinstance(node, dict) else None


class SchemaDriftRule(Rule):
    code = "TPL101"
    codes = ("TPL101", "TPL102")
    repo_anchors = (_TYPES_REL,)
    name = "schema-drift"
    rationale = (
        "the dataclasses in tpuslo/schema/types.py and the JSON "
        "contracts under tpuslo/schema/contracts/ must agree in both "
        "directions"
    )

    def check_repo(self, repo: RepoContext) -> Iterable[Finding]:
        ctx = repo.by_rel.get(_TYPES_REL)
        if ctx is None or ctx.tree is None:
            return ()
        findings: list[Finding] = []
        class_nodes = {
            node.name: node
            for node in ast.walk(ctx.tree)
            if isinstance(node, ast.ClassDef)
        }
        schema_cache: dict[str, Any] = {}
        for cls_name, (schema_rel, pointer) in SCHEMA_BINDINGS.items():
            cls_node = class_nodes.get(cls_name)
            if cls_node is None:
                findings.append(
                    Finding(
                        _TYPES_REL,
                        1,
                        "TPL101",
                        f"contract-bound dataclass {cls_name} missing "
                        f"from {_TYPES_REL} (bound to {schema_rel})",
                    )
                )
                continue
            if schema_rel not in schema_cache:
                schema_cache[schema_rel] = repo.read_json(schema_rel)
            schema = schema_cache[schema_rel]
            if schema is None:
                findings.append(
                    Finding(
                        _TYPES_REL,
                        cls_node.lineno,
                        "TPL101",
                        f"contract {schema_rel} for {cls_name} is "
                        "missing or invalid JSON",
                    )
                )
                continue
            sub = _walk_pointer(schema, pointer)
            if sub is None:
                findings.append(
                    Finding(
                        _TYPES_REL,
                        cls_node.lineno,
                        "TPL101",
                        f"subschema {'/'.join(pointer) or '<root>'} for "
                        f"{cls_name} not found in {schema_rel}",
                    )
                )
                continue
            findings.extend(self._check_class(cls_name, cls_node, sub))
        return findings

    @staticmethod
    def _check_class(
        cls_name: str, cls_node: ast.ClassDef, schema: dict
    ) -> list[Finding]:
        findings: list[Finding] = []
        properties: dict = schema.get("properties") or {}
        required = set(schema.get("required") or ())
        fields = _dataclass_fields(cls_node)
        by_name = {f.name: f for f in fields}

        for prop in sorted(properties):
            if prop not in by_name:
                findings.append(
                    Finding(
                        _TYPES_REL,
                        cls_node.lineno,
                        "TPL101",
                        f"contract property {prop!r} has no field on "
                        f"{cls_name}",
                    )
                )
        for f in fields:
            if f.name not in properties:
                findings.append(
                    Finding(
                        _TYPES_REL,
                        f.lineno,
                        "TPL101",
                        f"{cls_name}.{f.name} is not a property of its "
                        "contract (extend the schema before the field)",
                    )
                )
                continue
            expected = _json_types_for(f.annotation)
            if expected is None:
                continue
            declared = properties[f.name].get("type")
            declared_set = (
                {declared}
                if isinstance(declared, str)
                else set(declared or ())
            )
            declared_set.discard("null")
            if declared_set and not declared_set & expected:
                findings.append(
                    Finding(
                        _TYPES_REL,
                        f.lineno,
                        "TPL101",
                        f"{cls_name}.{f.name}: annotation "
                        f"{f.annotation!r} is incompatible with contract "
                        f"type {sorted(declared_set)}",
                    )
                )

        emitted = _unconditional_to_dict_keys(cls_node)
        if emitted is not None:
            for prop in sorted(required):
                if prop in by_name and prop not in emitted:
                    findings.append(
                        Finding(
                            _TYPES_REL,
                            by_name[prop].lineno,
                            "TPL102",
                            f"{cls_name}.{prop} is required by the "
                            "contract but to_dict emits it conditionally "
                            "(payload can fail validation)",
                        )
                    )
        return findings


# --- TPL103: columnar dtype drift ---------------------------------------

_COLUMNAR_REL = "tpuslo/columnar/schema.py"


def _literal_tuple_pairs(node: ast.AST) -> list[tuple[str, str]] | None:
    """Parse a ``((name, fmt), ...)`` literal; None if not that shape."""
    if not isinstance(node, ast.Tuple):
        return None
    out: list[tuple[str, str]] = []
    for elt in node.elts:
        if not (
            isinstance(elt, ast.Tuple)
            and len(elt.elts) == 2
            and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in elt.elts
            )
        ):
            return None
        out.append((elt.elts[0].value, elt.elts[1].value))
    return out


def _literal_columns_map(node: ast.AST) -> dict[str, tuple[str, ...]] | None:
    """Parse a ``{"field": ("col", ...)}`` literal; None if not that."""
    if not isinstance(node, ast.Dict):
        return None
    out: dict[str, tuple[str, ...]] = {}
    for key, value in zip(node.keys, node.values):
        if not (
            isinstance(key, ast.Constant) and isinstance(key.value, str)
        ):
            return None
        if not isinstance(value, ast.Tuple):
            return None
        cols = []
        for e in value.elts:
            if not (
                isinstance(e, ast.Constant) and isinstance(e.value, str)
            ):
                return None
            cols.append(e.value)
        out[key.value] = tuple(cols)
    return out


class ColumnarDtypeDriftRule(Rule):
    """TPL103: the columnar dtype must stay derived from ProbeEventV1.

    ``tpuslo/columnar/schema.py`` declares the batch dtype
    (``_DTYPE_FIELDS``) and the field→columns derivation map
    (``COLUMNS_FOR_FIELD``) as pure literals precisely so this rule can
    re-check, on every lint run, that

    * every ``ProbeEventV1`` dataclass field is mapped to columns,
    * every mapped field still exists on the dataclass,
    * every mapped column exists in the dtype, and
    * every dtype column is reachable from some field's mapping —

    i.e. adding/renaming/dropping a probe-event field without the
    matching columnar change (or vice versa) fails ``make lint``.
    """

    code = "TPL103"
    codes = ("TPL103",)
    repo_anchors = (_TYPES_REL, _COLUMNAR_REL)
    name = "columnar-dtype-drift"
    rationale = (
        "the columnar batch dtype in tpuslo/columnar/schema.py is "
        "derived from ProbeEventV1 and must track its fields in both "
        "directions"
    )

    def check_repo(self, repo: RepoContext) -> Iterable[Finding]:
        ctx = repo.by_rel.get(_COLUMNAR_REL)
        types_ctx = repo.by_rel.get(_TYPES_REL)
        if ctx is None or ctx.tree is None:
            return ()
        findings: list[Finding] = []
        dtype_fields: list[tuple[str, str]] | None = None
        columns_map: dict[str, tuple[str, ...]] | None = None
        dtype_line = map_line = 1
        for node in ctx.tree.body:
            targets: list[ast.expr] = []
            value: ast.expr | None = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            for target in targets:
                if not isinstance(target, ast.Name):
                    continue
                if target.id == "_DTYPE_FIELDS":
                    dtype_fields = _literal_tuple_pairs(value)
                    dtype_line = node.lineno
                elif target.id == "COLUMNS_FOR_FIELD":
                    columns_map = _literal_columns_map(value)
                    map_line = node.lineno
        if dtype_fields is None or columns_map is None:
            findings.append(
                Finding(
                    _COLUMNAR_REL,
                    1,
                    "TPL103",
                    "_DTYPE_FIELDS / COLUMNS_FOR_FIELD must be pure "
                    "literals (the dtype-sync check parses them from "
                    "the AST)",
                )
            )
            return findings

        event_fields: list[_Field] = []
        if types_ctx is not None and types_ctx.tree is not None:
            for node in ast.walk(types_ctx.tree):
                if (
                    isinstance(node, ast.ClassDef)
                    and node.name == "ProbeEventV1"
                ):
                    event_fields = _dataclass_fields(node)
        if not event_fields:
            findings.append(
                Finding(
                    _COLUMNAR_REL,
                    1,
                    "TPL103",
                    f"ProbeEventV1 not found in {_TYPES_REL}; cannot "
                    "check columnar dtype derivation",
                )
            )
            return findings

        field_names = {f.name for f in event_fields}
        dtype_names = {name for name, _ in dtype_fields}
        for f in event_fields:
            if f.name not in columns_map:
                findings.append(
                    Finding(
                        _COLUMNAR_REL,
                        map_line,
                        "TPL103",
                        f"ProbeEventV1.{f.name} has no entry in "
                        "COLUMNS_FOR_FIELD — extend the columnar dtype "
                        "with the schema change",
                    )
                )
        mapped_columns: set[str] = set()
        for field_name, cols in columns_map.items():
            if field_name not in field_names:
                findings.append(
                    Finding(
                        _COLUMNAR_REL,
                        map_line,
                        "TPL103",
                        f"COLUMNS_FOR_FIELD maps {field_name!r} which "
                        "is not a ProbeEventV1 field (stale mapping)",
                    )
                )
            for col in cols:
                mapped_columns.add(col)
                if col not in dtype_names:
                    findings.append(
                        Finding(
                            _COLUMNAR_REL,
                            map_line,
                            "TPL103",
                            f"COLUMNS_FOR_FIELD names column {col!r} "
                            "missing from _DTYPE_FIELDS",
                        )
                    )
        for name in sorted(dtype_names - mapped_columns):
            findings.append(
                Finding(
                    _COLUMNAR_REL,
                    dtype_line,
                    "TPL103",
                    f"dtype column {name!r} is not derived from any "
                    "ProbeEventV1 field (unmapped column)",
                )
            )
        return findings


# --- TPL104: fleet wire-contract drift -----------------------------------

_FLEET_WIRE_REL = "tpuslo/fleet/wire.py"


def _literal_string_tuple(node: ast.AST) -> tuple[str, ...] | None:
    """Parse a ``("a", "b", ...)`` literal; None if not that shape."""
    if not isinstance(node, ast.Tuple):
        return None
    out: list[str] = []
    for elt in node.elts:
        if not (
            isinstance(elt, ast.Constant) and isinstance(elt.value, str)
        ):
            return None
        out.append(elt.value)
    return tuple(out)


class FleetWireDriftRule(Rule):
    """TPL104: the fleet wire payload must track the columnar dtype.

    ``tpuslo/fleet/wire.py`` declares the shipment column order
    (``WIRE_EVENT_COLUMNS``) as a pure literal precisely so this rule
    can re-check, on every lint run, that the node→aggregator wire
    contract stays derivable from ``PROBE_EVENT_DTYPE`` — and, through
    ``COLUMNS_FOR_FIELD``, from ``ProbeEventV1`` — in both directions:

    * every wire column must exist in the columnar dtype,
    * every dtype column must be on the wire (an aggregator
      reconstructs FULL batches; a silently dropped column would
      corrupt fleet attribution, not fail loudly),
    * every ``ProbeEventV1`` field's derived columns must all ship,
    * duplicate wire columns are findings —

    the same drift-proofing shape as TPL103 one layer down.
    """

    code = "TPL104"
    codes = ("TPL104",)
    repo_anchors = (_TYPES_REL, _COLUMNAR_REL, _FLEET_WIRE_REL)
    name = "fleet-wire-drift"
    rationale = (
        "the aggregator wire payload in tpuslo/fleet/wire.py is "
        "derived from PROBE_EVENT_DTYPE / ProbeEventV1 and must track "
        "them in both directions"
    )

    def check_repo(self, repo: RepoContext) -> Iterable[Finding]:
        wire_ctx = repo.by_rel.get(_FLEET_WIRE_REL)
        if wire_ctx is None or wire_ctx.tree is None:
            return ()
        findings: list[Finding] = []
        wire_columns: tuple[str, ...] | None = None
        wire_line = 1
        for node in wire_ctx.tree.body:
            targets: list[ast.expr] = []
            value: ast.expr | None = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            for target in targets:
                if (
                    isinstance(target, ast.Name)
                    and target.id == "WIRE_EVENT_COLUMNS"
                ):
                    wire_columns = _literal_string_tuple(value)
                    wire_line = node.lineno
        if wire_columns is None:
            findings.append(
                Finding(
                    _FLEET_WIRE_REL,
                    wire_line,
                    "TPL104",
                    "WIRE_EVENT_COLUMNS must be a pure string-tuple "
                    "literal (the wire-contract check parses it from "
                    "the AST)",
                )
            )
            return findings

        seen: set[str] = set()
        for name in wire_columns:
            if name in seen:
                findings.append(
                    Finding(
                        _FLEET_WIRE_REL,
                        wire_line,
                        "TPL104",
                        f"wire column {name!r} listed twice (decode "
                        "would silently overwrite the first buffer)",
                    )
                )
            seen.add(name)

        # Dtype side (TPL103's literals, re-read here so TPL104 stays
        # meaningful even when TPL103 is suppressed).
        schema_ctx = repo.by_rel.get(_COLUMNAR_REL)
        dtype_fields: list[tuple[str, str]] | None = None
        columns_map: dict[str, tuple[str, ...]] | None = None
        if schema_ctx is not None and schema_ctx.tree is not None:
            for node in schema_ctx.tree.body:
                targets = []
                value = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif (
                    isinstance(node, ast.AnnAssign)
                    and node.value is not None
                ):
                    targets, value = [node.target], node.value
                for target in targets:
                    if not isinstance(target, ast.Name):
                        continue
                    if target.id == "_DTYPE_FIELDS":
                        dtype_fields = _literal_tuple_pairs(value)
                    elif target.id == "COLUMNS_FOR_FIELD":
                        columns_map = _literal_columns_map(value)
        if dtype_fields is None or columns_map is None:
            findings.append(
                Finding(
                    _FLEET_WIRE_REL,
                    wire_line,
                    "TPL104",
                    "cannot resolve _DTYPE_FIELDS / COLUMNS_FOR_FIELD "
                    f"literals in {_COLUMNAR_REL}; the wire contract "
                    "cannot be checked",
                )
            )
            return findings

        dtype_names = {name for name, _ in dtype_fields}
        wire_set = set(wire_columns)
        for name in wire_columns:
            if name not in dtype_names:
                findings.append(
                    Finding(
                        _FLEET_WIRE_REL,
                        wire_line,
                        "TPL104",
                        f"wire column {name!r} is not a "
                        "PROBE_EVENT_DTYPE column (not derivable from "
                        "ProbeEventV1)",
                    )
                )
        for name, _ in dtype_fields:
            if name not in wire_set:
                findings.append(
                    Finding(
                        _FLEET_WIRE_REL,
                        wire_line,
                        "TPL104",
                        f"dtype column {name!r} missing from "
                        "WIRE_EVENT_COLUMNS — aggregators would "
                        "reconstruct batches without it",
                    )
                )

        # ProbeEventV1 direction: every field's derived columns ship.
        types_ctx = repo.by_rel.get(_TYPES_REL)
        event_fields: list[_Field] = []
        if types_ctx is not None and types_ctx.tree is not None:
            for node in ast.walk(types_ctx.tree):
                if (
                    isinstance(node, ast.ClassDef)
                    and node.name == "ProbeEventV1"
                ):
                    event_fields = _dataclass_fields(node)
        for f in event_fields:
            for col in columns_map.get(f.name, ()):
                if col not in wire_set:
                    findings.append(
                        Finding(
                            _FLEET_WIRE_REL,
                            wire_line,
                            "TPL104",
                            f"ProbeEventV1.{f.name} derives column "
                            f"{col!r} which the wire contract does "
                            "not ship",
                        )
                    )
        return findings


# --- TPL140: config drift ------------------------------------------------

_SPECIAL_TOP_LEVEL = {"apiVersion", "kind", "signal_set"}


class ConfigDriftRule(Rule):
    code = "TPL140"
    codes = ("TPL140",)
    repo_anchors = (_CFG_REL,)
    name = "config-drift"
    rationale = (
        "every key in the v1alpha1 toolkit-config schema must be read "
        "by toolkitcfg.py and vice versa"
    )

    def check_repo(self, repo: RepoContext) -> Iterable[Finding]:
        ctx = repo.by_rel.get(_CFG_REL)
        if ctx is None or ctx.tree is None:
            return ()
        schema = repo.read_json(
            "tpuslo/schema/contracts/v1alpha1/toolkit-config.schema.json"
        )
        if schema is None:
            return (
                Finding(
                    _CFG_REL,
                    1,
                    "TPL140",
                    "toolkit-config schema missing or invalid JSON",
                ),
            )
        findings: list[Finding] = []
        top_props: dict = schema.get("properties") or {}

        class_nodes = {
            n.name: n
            for n in ast.walk(ctx.tree)
            if isinstance(n, ast.ClassDef)
        }
        toolkit = class_nodes.get("ToolkitConfig")
        if toolkit is None:
            return (
                Finding(_CFG_REL, 1, "TPL140", "ToolkitConfig not found"),
            )
        #: section name -> section dataclass fields
        section_fields: dict[str, dict[str, _Field]] = {}
        section_lines: dict[str, int] = {}
        for f in _dataclass_fields(toolkit):
            norm = _normalize_annotation(f.annotation)
            section_cls = class_nodes.get(norm)
            if section_cls is not None and norm.endswith("Config"):
                section_fields[f.name] = {
                    sf.name: sf for sf in _dataclass_fields(section_cls)
                }
                section_lines[f.name] = section_cls.lineno

        merge_keys = self._merge_section_keys(ctx.tree)
        to_dict_keys = self._to_dict_section_keys(toolkit)

        # Schema sections <-> loader sections.
        for section, prop in sorted(top_props.items()):
            if section in _SPECIAL_TOP_LEVEL:
                continue
            keys = set((prop.get("properties") or {}))
            fields = section_fields.get(section)
            if fields is None:
                findings.append(
                    Finding(
                        _CFG_REL,
                        toolkit.lineno,
                        "TPL140",
                        f"schema section {section!r} has no dataclass "
                        "field on ToolkitConfig",
                    )
                )
                continue
            line = section_lines.get(section, toolkit.lineno)
            for key in sorted(keys - set(fields)):
                findings.append(
                    Finding(
                        _CFG_REL,
                        line,
                        "TPL140",
                        f"schema key {section}.{key} is not a field of "
                        "its config dataclass (never loaded)",
                    )
                )
            for key in sorted(set(fields) - keys):
                findings.append(
                    Finding(
                        _CFG_REL,
                        fields[key].lineno,
                        "TPL140",
                        f"config field {section}.{key} is absent from "
                        "the toolkit-config schema (never validated)",
                    )
                )
            read = merge_keys.get(section)
            if read is not None:
                for key in sorted(keys - read):
                    findings.append(
                        Finding(
                            _CFG_REL,
                            line,
                            "TPL140",
                            f"schema key {section}.{key} is not read by "
                            "load_config's merge for that section",
                        )
                    )
            emitted = to_dict_keys.get(section)
            if emitted is not None:
                for key in sorted(keys - emitted):
                    findings.append(
                        Finding(
                            _CFG_REL,
                            line,
                            "TPL140",
                            f"schema key {section}.{key} is not emitted "
                            "by ToolkitConfig.to_dict",
                        )
                    )
        for section in sorted(set(section_fields) - set(top_props)):
            findings.append(
                Finding(
                    _CFG_REL,
                    section_lines.get(section, toolkit.lineno),
                    "TPL140",
                    f"config section {section!r} is absent from the "
                    "toolkit-config schema",
                )
            )
        return findings

    @staticmethod
    def _merge_section_keys(tree: ast.Module) -> dict[str, set[str]]:
        """Section -> keys passed to ``_merge_section(cfg.<s>, .., {..})``."""
        out: dict[str, set[str]] = {}
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "_merge_section"
                and len(node.args) >= 3
            ):
                continue
            target = node.args[0]
            if not (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "cfg"
            ):
                continue
            keys_arg = node.args[2]
            if isinstance(keys_arg, ast.Dict):
                out.setdefault(target.attr, set()).update(
                    k.value
                    for k in keys_arg.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                )
        return out

    @staticmethod
    def _to_dict_section_keys(
        toolkit: ast.ClassDef,
    ) -> dict[str, set[str]]:
        to_dict = next(
            (
                stmt
                for stmt in toolkit.body
                if isinstance(stmt, ast.FunctionDef)
                and stmt.name == "to_dict"
            ),
            None,
        )
        if to_dict is None:
            return {}
        out: dict[str, set[str]] = {}
        for node in ast.walk(to_dict):
            if not isinstance(node, ast.Dict):
                continue
            for key, value in zip(node.keys, node.values):
                if (
                    isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                    and isinstance(value, ast.Dict)
                ):
                    out[key.value] = {
                        k.value
                        for k in value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                    }
        return out


# --- TPL150: metrics drift -----------------------------------------------

_METRIC_NAME_RE = re.compile(r'"(llm_(?:slo|tpu)_[a-z0-9_]+)"')


class MetricsDriftRule(Rule):
    code = "TPL150"
    codes = ("TPL150",)
    repo_anchors = (_REGISTRY_REL,)
    name = "metrics-drift"
    rationale = (
        "every AgentMetrics series must be referenced by a dashboard "
        "or a doc — an unobservable series is a silent gap"
    )

    def check_repo(self, repo: RepoContext) -> Iterable[Finding]:
        registry = repo.read_text(_REGISTRY_REL)
        if registry is None:
            return ()
        series: dict[str, int] = {}
        for lineno, line in enumerate(registry.splitlines(), start=1):
            for name in _METRIC_NAME_RE.findall(line):
                series.setdefault(name, lineno)
        if not series:
            return (
                Finding(
                    _REGISTRY_REL,
                    1,
                    "TPL150",
                    "no metric names found — did the registry move?",
                ),
            )
        chunks: list[str] = []
        for _, text in repo.glob_text("dashboards/*.json"):
            chunks.append(text)
        # generate.py is the dashboards' source of truth; a panel
        # defined there counts even before the JSON is regenerated.
        gen = repo.read_text("dashboards/generate.py")
        if gen is not None:
            chunks.append(gen)
        for _, text in repo.glob_text("docs/**/*.md"):
            chunks.append(text)
        corpus = "\n".join(chunks)
        return [
            Finding(
                _REGISTRY_REL,
                lineno,
                "TPL150",
                f"series {name} is referenced by no dashboard or doc "
                "(add a panel in dashboards/generate.py, a runbook "
                "reference, or delete the series)",
            )
            for name, lineno in sorted(series.items())
            if name not in corpus
        ]
