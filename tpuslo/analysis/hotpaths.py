"""Hot-path manifest: the functions the TPL12x purity rules guard.

These are the per-event / per-cycle code paths that PR 1 and PR 5
measured and optimized (fast-path validation, batched generation,
indexed correlation, tracer stage records); a stray ``json.dumps`` or
``time.time`` here silently undoes that work.  Registering a function
makes two invariants machine-checked:

* **TPL120** — its body (including nested defs) must not call the
  known hot-path poisons: ``logging``/logger calls, ``print``,
  ``json.dumps``/``json.dump``, ``copy.deepcopy``, ``time.time`` /
  ``time.time_ns`` (use ``perf_counter_ns``; wall-clock anchoring
  belongs on the cold side), or ``os.urandom`` (~10 µs/call — use the
  seeded ``random`` instance the tracer keeps).
* **TPL121** — the dataclasses it allocates per event (listed in
  ``HOT_DATACLASSES``) must declare ``slots`` — a per-event ``__dict__``
  costs both allocation time and cache locality.

When a new function joins the hot path (columnar spine, fleet
aggregator ingest), add it here in the same PR that optimizes it —
the manifest is the contract that the optimization stays real.
"""

from __future__ import annotations

#: (repo-relative module path, dotted qualname within the module).
HOT_FUNCTIONS: tuple[tuple[str, str], ...] = (
    # Structural fast-path validation (PR 1): runs once per probe event.
    ("tpuslo/schema/fastpath.py", "fast_probe_event_valid"),
    ("tpuslo/schema/fastpath.py", "fast_probe_payload_valid"),
    ("tpuslo/schema/fastpath.py", "validate_probe_event"),
    ("tpuslo/schema/fastpath.py", "validate_probe_payload"),
    # Batched probe-event generation (PR 1): per sample x signal.
    ("tpuslo/signals/generator.py", "Generator.generate_batch"),
    # Indexed correlation (PR 1): per span x tier.
    ("tpuslo/correlation/matcher.py", "match_batch"),
    # Self-tracer stage records (PR 5): 8+ per agent cycle; these CMs
    # were hand-rolled specifically to stay under the overhead gate.
    ("tpuslo/obs/tracer.py", "_StageCM.__init__"),
    ("tpuslo/obs/tracer.py", "_StageCM.__exit__"),
    ("tpuslo/obs/tracer.py", "CycleTrace.stage"),
    # Burn-engine SLI fold (ISSUE 7): once per request outcome; ring
    # arithmetic only — time arrives with the outcome, never from the
    # wall clock, and windows roll forward in O(1) amortized.
    ("tpuslo/sloengine/stream.py", "TenantWindows.record"),
    ("tpuslo/sloengine/stream.py", "TenantWindows.roll_to"),
    ("tpuslo/sloengine/engine.py", "BurnEngine.record"),
    # Columnar spine (ISSUE 8): the batch kernels behind the 1M-events/s
    # gate.  serialize_jsonl is registered precisely because its row
    # twin's cost IS json.dumps — strings escape once per pool entry
    # via StringPool.escaped(), never per event.
    ("tpuslo/columnar/generate.py", "columns_from_samples"),
    ("tpuslo/columnar/gate.py", "dedup_hashes"),
    ("tpuslo/columnar/gate.py", "ColumnarGate.admit_batch"),
    ("tpuslo/columnar/gate.py", "ColumnarGate._dedup_batch"),
    ("tpuslo/columnar/match.py", "signal_columns_from_batch"),
    ("tpuslo/columnar/match.py", "match_columns"),
    ("tpuslo/columnar/match.py", "_tier_probe"),
    ("tpuslo/columnar/posterior.py", "log_posterior_batch"),
    ("tpuslo/columnar/serialize.py", "serialize_jsonl"),
    # Fleet aggregator ingest (ISSUE 9): the shard path behind the
    # 5M-events/s aggregate gate.  decode_shipment stays frombuffer-
    # only; the fold's Python cost is per distinct group, not per
    # event — a stray per-event call here erases the sharding win.
    ("tpuslo/fleet/wire.py", "decode_shipment"),
    ("tpuslo/fleet/aggregator.py", "AggregatorShard.ingest"),
    ("tpuslo/fleet/aggregator.py", "AggregatorShard._drain"),
    ("tpuslo/fleet/aggregator.py", "AggregatorShard._fold"),
    # Federation plane (ISSUE 15): the cluster/region ingest paths run
    # per shipment / per envelope at 10k-node scale, and the adaptive
    # sampler runs per decoded batch under saturation — exactly when
    # the plane can least afford per-event Python or a stray
    # json.dumps.  Pressure observation runs every pump.
    ("tpuslo/federation/backpressure.py", "AdaptiveSampler.sample_batch"),
    ("tpuslo/federation/backpressure.py", "PressureController.observe"),
    ("tpuslo/federation/cluster.py", "ClusterAggregator.ingest"),
    ("tpuslo/federation/region.py", "RegionAggregator.ingest"),
    # Live deployment plane (ISSUE 17): the socket listener's frame
    # decoder runs per recv() chunk on every live hop; a per-frame
    # print or json.dumps here would stall the accept loop under the
    # same load the chaos lane partitions.  encode_frame is the
    # sender-side slow path (one json.dumps per shipment flush, not
    # per event) and is deliberately NOT registered.
    ("tpuslo/livenet/framing.py", "FrameDecoder.feed"),
    # Remediation evaluate path (ISSUE 11): the decision + verify fold
    # runs once per attributed incident / per in-flight action per
    # evaluation window, inside the agent cycle the tracer budgets —
    # time arrives as a parameter (never from the wall clock) and the
    # bodies stay dict/deque arithmetic; provenance serialization lives
    # on the cold side.
    ("tpuslo/remediation/policy.py", "RemediationPolicy.decide"),
    ("tpuslo/remediation/engine.py", "RemediationEngine.consider"),
    ("tpuslo/remediation/engine.py", "RemediationEngine.tick"),
    ("tpuslo/remediation/verifier.py", "observe_window"),
    # Serving decode/verify kernels (ISSUE 10): the traced bodies the
    # spec-decode and decode paths run per token/round.  They execute
    # under jax tracing, where a stray print/json.dumps lands in every
    # compile AND betrays a retrace; per-trace Python cost here is a
    # compile-storm amplifier.
    ("tpuslo/models/llama.py", "decode_step"),
    ("tpuslo/models/llama.py", "verify_chunk"),
    ("tpuslo/models/llama.py", "decode_chunk"),
    ("tpuslo/models/speculative.py", "_spec_round_core"),
    # Serving front door (ISSUE 12): the per-round-boundary scheduler
    # paths.  step() runs once per fused multi-round dispatch and its
    # emission loop touches every slot; the admission paths run per
    # admitted request inside the serving loop — wall-clock reads are
    # perf_counter-only (outcome timestamps derive from an init-time
    # anchor), and a stray json.dumps/print here stalls every slot.
    ("tpuslo/models/frontdoor.py", "FrontDoorEngine.step"),
    ("tpuslo/models/frontdoor.py", "FrontDoorEngine._step"),
    ("tpuslo/models/frontdoor.py", "FrontDoorEngine._fill_slots"),
    ("tpuslo/models/frontdoor.py", "FrontDoorEngine._admit"),
    ("tpuslo/models/frontdoor.py", "FrontDoorEngine._admit_batch"),
    # Serving scale-out router (ISSUE 16): placement runs once per
    # request at fleet arrival rate — the scored policy reads queue
    # depths and the warm mirror, never device state, and a stray
    # logging call here delays every admission behind it.
    ("tpuslo/models/router.py", "SLORouter.route"),
    ("tpuslo/models/router.py", "SLORouter._score_engine"),
    ("tpuslo/models/router.py", "SLORouter._pick_engine"),
    # Device-plane ledger (ISSUE 14): the fold runs over every span of
    # a capture (thousands per trace) and inside gates/benches; the
    # per-dispatch ledger note runs once per serving dispatch inside
    # FrontDoorEngine._step — pure arithmetic, timestamps arrive as
    # perf_counter deltas, serialization stays in to_dict on the cold
    # side.
    ("tpuslo/deviceplane/ledger.py", "build_ledger"),
    ("tpuslo/deviceplane/ledger.py", "_contained_ops"),
    ("tpuslo/deviceplane/dispatch.py", "DispatchLedger.note"),
    # Continuous profiler (ISSUE 20): the tick runs every columnar
    # cycle; capture-window fold + governor + payload emission run once
    # per stride inside the live loop's cycle budget — the measured
    # cost of exactly these functions is what the 3% gate holds, so a
    # logging/serialization call here inflates the number it governs.
    # Wall-clock/perf-counter reads go through the module-bound
    # _CLOCK_NS/_PERF_NS references.
    ("tpuslo/deviceplane/profiler.py", "ContinuousProfiler.tick"),
    ("tpuslo/deviceplane/profiler.py", "ContinuousProfiler._capture_window"),
    ("tpuslo/deviceplane/profiler.py", "ContinuousProfiler._note_overhead"),
    ("tpuslo/deviceplane/profiler.py", "ContinuousProfiler.probe_payloads"),
    # Global peer mesh (ISSUE 19): the gossip fold runs once per
    # received envelope at mesh fan-in rate, the election tick and
    # envelope build run every round for every remote — all three read
    # only the in-memory peer views and the event clock passed in; a
    # wall-clock read or serialization call here skews the liveness
    # horizon for every peer behind it.
    ("tpuslo/federation/global_tier.py", "GlobalPeer.gossip_in"),
    ("tpuslo/federation/global_tier.py", "GlobalPeer.gossip_out"),
    ("tpuslo/federation/global_tier.py", "GlobalPeer.election_tick"),
)

#: (repo-relative module path, dataclass name) pairs that are allocated
#: on the paths above and must declare ``slots``.
HOT_DATACLASSES: tuple[tuple[str, str], ...] = (
    ("tpuslo/schema/types.py", "ProbeEventV1"),
    ("tpuslo/schema/types.py", "ConnTuple"),
    ("tpuslo/schema/types.py", "TPURef"),
    ("tpuslo/obs/tracer.py", "Span"),
    ("tpuslo/correlation/matcher.py", "SpanRef"),
    ("tpuslo/correlation/matcher.py", "SignalRef"),
    ("tpuslo/correlation/matcher.py", "Decision"),
    ("tpuslo/correlation/matcher.py", "BatchMatch"),
    ("tpuslo/sloengine/stream.py", "RequestOutcome"),
    # Columnar spine containers (ISSUE 8).
    ("tpuslo/columnar/schema.py", "StringPool"),
    ("tpuslo/columnar/schema.py", "ColumnarBatch"),
    ("tpuslo/columnar/gate.py", "ColumnarGateBatch"),
    ("tpuslo/columnar/match.py", "MatchColumns"),
    ("tpuslo/columnar/match.py", "ColumnarMatches"),
    ("tpuslo/columnar/posterior.py", "PosteriorMatrices"),
    # Fleet plane containers (ISSUE 9).
    ("tpuslo/fleet/wire.py", "Shipment"),
    ("tpuslo/fleet/aggregator.py", "_NodeState"),
    # Federation-plane containers (ISSUE 15).
    ("tpuslo/federation/wire.py", "RegionEnvelope"),
    ("tpuslo/federation/backpressure.py", "PressureSignal"),
    ("tpuslo/federation/backpressure.py", "SampleResult"),
    ("tpuslo/federation/region.py", "_ClusterState"),
    # Remediation evaluate-path containers (ISSUE 11).
    ("tpuslo/remediation/policy.py", "AttributionContext"),
    ("tpuslo/remediation/policy.py", "RemediationRule"),
    ("tpuslo/remediation/policy.py", "PolicyDecision"),
    ("tpuslo/remediation/engine.py", "ActionRecord"),
    ("tpuslo/remediation/verifier.py", "VerifyState"),
    # Front-door slot/queue records (ISSUE 12): allocated per request,
    # scanned per round boundary by the scheduler.
    ("tpuslo/models/frontdoor.py", "FrontDoorRequest"),
    # Paged park record (ISSUE 16): one per preemption in paged mode;
    # router placement record: one per request at arrival rate.
    ("tpuslo/models/frontdoor.py", "_PagedParked"),
    ("tpuslo/models/router.py", "RouterDecision"),
    # Device-plane ledger records (ISSUE 14): one per module launch.
    ("tpuslo/deviceplane/ledger.py", "LaunchRecord"),
    ("tpuslo/deviceplane/ledger.py", "DeviceWindow"),
    ("tpuslo/deviceplane/ledger.py", "CompileEvent"),
    # Profiler window record (ISSUE 20): one per capture window,
    # allocated inside the governed fold.
    ("tpuslo/deviceplane/profiler.py", "ProfilerWindow"),
    # Peer-mesh containers (ISSUE 19): one envelope per remote per
    # gossip round; one view per peer folded on every receive; the
    # gap-tolerant cursor advances per envelope.
    ("tpuslo/federation/wire.py", "PeerEnvelope"),
    ("tpuslo/federation/global_tier.py", "_PeerView"),
    ("tpuslo/federation/global_tier.py", "GapTolerantCursor"),
)

#: The JAX plane the TPL16x trace-discipline rules govern: every file
#: under these prefixes is scanned for retrace hazards (TPL161), dtype
#: drift (TPL162) and donation misses (TPL163).
JAX_PLANE_PREFIXES: tuple[str, ...] = (
    "tpuslo/models/",
    "tpuslo/ops/",
    "tpuslo/parallel/",
)

#: Registered decode/verify hot loops: (repo-relative module path,
#: dotted qualname).  Inside these functions' for/while bodies a host
#: sync is a per-token (or per-round) cost — through a remote-chip
#: tunnel, a full network round-trip — so **TPL160** flags the known
#: sync constructs there: ``.item()``/``.tolist()`` on device arrays,
#: ``int()``/``float()``/``bool()``/``np.asarray()`` on values produced
#: by jnp/jax calls, and ``block_until_ready``.  The sanctioned read is
#: ONE fused ``jax.device_get`` per loop iteration; results of
#: ``device_get`` (and other host values) are exempt.  When a new
#: serving loop is optimized, register it here in the same PR — the
#: manifest is the contract that the dispatch discipline stays real.
JAX_HOT_LOOPS: tuple[tuple[str, str], ...] = (
    ("tpuslo/models/serve.py", "ServeEngine.generate"),
    ("tpuslo/models/serve.py", "ServeEngine.generate_batch"),
    ("tpuslo/models/serve.py", "ServeEngine._prefill_rows"),
    ("tpuslo/models/serve.py", "ServeEngine._append_ids"),
    ("tpuslo/models/speculative.py", "SpeculativeEngine.stream"),
    ("tpuslo/models/speculative.py", "SpeculativeEngine.generate_batch"),
    ("tpuslo/models/frontdoor.py", "FrontDoorEngine._step"),
    ("tpuslo/models/frontdoor.py", "FrontDoorEngine._admit"),
    # Paged park/resume (ISSUE 16): run per preemption / per resumed
    # admission inside the serving loop — one dispatch each, with the
    # block bookkeeping (free list, bucket choice) pure host ints.
    ("tpuslo/models/frontdoor.py", "FrontDoorEngine._park_paged"),
    ("tpuslo/models/frontdoor.py", "FrontDoorEngine._resume_paged"),
)
