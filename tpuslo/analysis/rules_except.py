"""TPL130: exception discipline inside the agent plane.

PRs 2–5 built the agent's error accounting on one contract: every
failure is *counted somewhere* — a metrics counter, a dead-letter
file, a quarantine dir, a log line — or it propagates.  A broad
``except Exception`` whose body does nothing silently erases a failure
class from every dashboard and every chaos sweep; the crash harness
can then no longer distinguish "handled" from "lost".

The rule flags ``except Exception`` / ``except BaseException`` / bare
``except`` handlers in agent-plane modules whose body performs no
action at all (only ``pass``/``...``/``continue``/``break``/bare or
constant ``return``).  Any call, assignment, or raise counts as
routing the failure somewhere.  Narrowing the exception type
(``except OSError``) also satisfies the rule — an anticipated, typed
miss is a decision; a swallowed ``Exception`` is a blind spot.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tpuslo.analysis.core import FileContext, Finding, Rule

#: The agent plane: the modules whose failures feed the loss-accounting
#: contract.  Research/serving code (models/, benchmark/, ops/,
#: parallel/) is exempt — best-effort probing of optional backends is
#: its normal mode.
AGENT_PLANE_PREFIXES = (
    "tpuslo/cli/",
    "tpuslo/delivery/",
    "tpuslo/ingest/",
    "tpuslo/obs/",
    "tpuslo/runtime/",
    "tpuslo/collector/",
    "tpuslo/safety/",
    "tpuslo/metrics/",
    "tpuslo/signals/",
    "tpuslo/correlation/",
    "tpuslo/attribution/",
    "tpuslo/webhook/",
    "tpuslo/chaos/",
    "tpuslo/schema/",
    "tpuslo/config/",
    "tpuslo/utils/",
    "tpuslo/otel/",
    "tpuslo/slo/",
    "tpuslo/releasegate/",
    "tpuslo/cdgate/",
    "tpuslo/faultreplay/",
    "tpuslo/prereq/",
)

_BROAD = frozenset({"Exception", "BaseException"})


def _is_broad(handler: ast.ExceptHandler) -> bool:
    node = handler.type
    if node is None:
        return True
    if isinstance(node, ast.Name):
        return node.id in _BROAD
    if isinstance(node, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in _BROAD for e in node.elts
        )
    return False


def _is_silent(body: list[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / ellipsis
        if isinstance(stmt, (ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Return) and (
            stmt.value is None or isinstance(stmt.value, ast.Constant)
        ):
            continue
        return False
    return True


class ExceptionDisciplineRule(Rule):
    code = "TPL130"
    codes = ("TPL130",)
    name = "exception-discipline"
    rationale = (
        "agent-plane failures must be counted, routed, or re-raised — "
        "never silently swallowed"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None or not ctx.rel.startswith(
            AGENT_PLANE_PREFIXES
        ):
            return ()
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _is_broad(node) and _is_silent(node.body):
                findings.append(
                    Finding(
                        ctx.rel,
                        node.lineno,
                        "TPL130",
                        "broad except silently swallows the failure: "
                        "re-raise, count it, or route it to a "
                        "dead-letter/quarantine path (or narrow the type)",
                    )
                )
        return findings
