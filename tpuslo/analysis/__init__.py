"""tpulint v2: contract-aware static analysis + dynamic race checking.

Static half (``python -m tpuslo.analysis`` / ``make lint``): a rule
framework with stable TPL codes over the repo's real invariants —
schema/dataclass drift, lock discipline, hot-path purity, exception
accounting, config and metrics drift — plus the generic TPL00x style
tier ported from tpulint v1.  Dynamic half
(:mod:`tpuslo.analysis.racecheck`, ``TPUSLO_RACECHECK=1``): a
lock-order race detector that wraps ``threading.Lock``/``RLock`` and
fails CI on cross-thread acquisition-order inversions.
"""

from tpuslo.analysis.core import (
    BASELINE_FILENAME,
    DEFAULT_PATHS,
    AnalysisResult,
    Baseline,
    FileContext,
    Finding,
    RepoContext,
    Rule,
    changed_py_files,
    run_analysis,
)
from tpuslo.analysis.rules import ALL_RULES, rule_catalog

__all__ = [
    "ALL_RULES",
    "AnalysisResult",
    "BASELINE_FILENAME",
    "Baseline",
    "DEFAULT_PATHS",
    "FileContext",
    "Finding",
    "RepoContext",
    "Rule",
    "changed_py_files",
    "rule_catalog",
    "run_analysis",
]
