"""Dynamic lock-order race detector (``TPUSLO_RACECHECK=1``).

The static TPL111 rule sees the acquisition orders the AST admits;
this module checks the orders that actually *execute*.  When installed
it replaces ``threading.Lock``/``RLock`` with tracked wrappers that
record, per thread, the stack of held locks at every acquisition.
Two failure patterns are detected:

* **Order inversion (AB/BA).**  Acquiring B while holding A adds edge
  A→B to a global acquisition-order graph.  If the edge closes a cycle
  (some thread ever acquired A while holding B, directly or
  transitively), both acquisition stacks are recorded as a violation —
  the classic latent deadlock that only fires under the right
  scheduler interleaving.

* **Lock held across a blocking call.**  ``time.sleep`` is patched to
  flag sleeping while holding any tracked lock — the pattern that
  turns a slow sink into a stalled agent loop (the delivery layer's
  contract is that backoff sleeps and network sends happen outside
  every lock).

Violations are recorded, not raised: raising inside an arbitrary
worker thread would vanish into daemon-thread teardown.  The pytest
wiring (``tests/conftest.py``) fails the session if any violation was
recorded; ``make racecheck-smoke`` runs the delivery/runtime/obs
suites this way.

The wrappers are Condition-compatible: ``threading.Condition(lock)``
binds the wrapper's ``acquire``/``release``, so waits release and
re-acquire through the tracking.  (A Condition over a tracked *RLock*
delegates ``_release_save``/``_acquire_restore`` to the raw lock and
bypasses hold tracking during the wait window — acceptable: the repo
builds conditions over plain Locks.)

Unit tests drive :class:`RaceCheckRegistry` directly with explicitly
wrapped locks, so provoked inversions never pollute the global
install's registry.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from dataclasses import dataclass

import _thread

ENV_FLAG = "TPUSLO_RACECHECK"

#: The threaded suites `make racecheck-smoke` / `m5gate --racecheck-smoke`
#: run under the detector, plus its own seeded-inversion tests.
SMOKE_SUITES = (
    "tests/test_delivery.py",
    "tests/test_runtime_drain.py",
    "tests/test_runtime_state.py",
    "tests/test_runtime_supervisor.py",
    "tests/test_obs_tracer.py",
    "tests/test_racecheck.py",
)

#: Raw lock factory immune to the monkeypatch (the registry's own
#: synchronization must not recurse into the tracker).
_raw_lock = _thread.allocate_lock

_real_lock_factory = threading.Lock
_real_rlock_factory = threading.RLock
_real_sleep = time.sleep


@dataclass(slots=True)
class Violation:
    kind: str  # "order_inversion" | "blocked_while_locked"
    detail: str
    stack: str
    other_stack: str = ""

    def render(self) -> str:
        out = f"racecheck: {self.kind}: {self.detail}\n--- stack:\n{self.stack}"
        if self.other_stack:
            out += f"--- conflicting acquisition stack:\n{self.other_stack}"
        return out


@dataclass(slots=True)
class _Edge:
    stack: str
    thread: str


class RaceCheckRegistry:
    """Global acquisition-order graph + per-thread held-lock stacks."""

    def __init__(self, max_violations: int = 64):
        self._mu = _raw_lock()
        #: src lock id -> dst lock id -> first-seen edge info
        self._graph: dict[int, dict[int, _Edge]] = {}
        self._names: dict[int, str] = {}
        #: Strong refs to every lock whose id entered the order graph:
        #: CPython recycles ids after GC, so an unpinned graph would
        #: conflate a dead test's locks with fresh allocations and fail
        #: the session gate with spurious inversions.  Bounded by the
        #: number of distinct locks that ever nested — not by total
        #: lock churn.
        self._refs: dict[int, object] = {}
        self._tls = threading.local()
        self.violations: list[Violation] = []
        self._max_violations = max_violations

    # --- held-stack bookkeeping ----------------------------------------

    def _held(self) -> list:
        """Per-thread stack of HELD LOCK OBJECTS (strong refs while
        held, so their ids cannot be recycled mid-hold)."""
        held = getattr(self._tls, "held", None)
        if held is None:
            held = []
            self._tls.held = held
        return held

    def name_of(self, lock_id: int) -> str:
        return self._names.get(lock_id, f"lock-{lock_id:#x}")

    def on_acquired(self, lock, name: str) -> None:
        lock_id = id(lock)
        held = self._held()
        stack = None
        with self._mu:
            if lock_id not in self._refs:
                # Not pinned: id may belong to a new lock — (re)name it.
                self._names[lock_id] = name
            for src_lock in held:
                src = id(src_lock)
                if src == lock_id:
                    continue
                edges = self._graph.setdefault(src, {})
                if lock_id not in edges:
                    if stack is None:
                        stack = "".join(traceback.format_stack(limit=12))
                    edges[lock_id] = _Edge(
                        stack, threading.current_thread().name
                    )
                    self._refs[src] = src_lock
                    self._refs[lock_id] = lock
                    self._check_cycle_locked(src, lock_id)
        held.append(lock)

    def on_released(self, lock) -> None:
        held = self._held()
        # Out-of-order release (lock A released while B still held) is
        # legal Python; remove the newest matching entry.
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    def held_any(self) -> list:
        return list(self._held())

    # --- detection ------------------------------------------------------

    def _check_cycle_locked(self, src: int, dst: int) -> None:
        """After adding src→dst: a dst→…→src path means an inversion."""
        seen = {dst}
        stack = [dst]
        while stack:
            node = stack.pop()
            for nxt in self._graph.get(node, ()):
                if nxt == src:
                    edge = self._graph[src][dst]
                    back = self._graph[node][src]
                    self._record_locked(
                        Violation(
                            "order_inversion",
                            f"{self.name_of(src)} -> {self.name_of(dst)} "
                            f"inverts an existing "
                            f"{self.name_of(dst)} ~> {self.name_of(src)} "
                            f"order (thread {edge.thread} vs "
                            f"{back.thread})",
                            edge.stack,
                            back.stack,
                        )
                    )
                    return
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)

    def note_blocking(self, what: str) -> None:
        held = self._held()
        if not held:
            return
        names = ", ".join(self.name_of(id(h)) for h in held)
        with self._mu:
            self._record_locked(
                Violation(
                    "blocked_while_locked",
                    f"{what} while holding [{names}]",
                    "".join(traceback.format_stack(limit=12)),
                )
            )

    def _record_locked(self, violation: Violation) -> None:
        if len(self.violations) < self._max_violations:
            self.violations.append(violation)

    def reset(self) -> None:
        with self._mu:
            self._graph.clear()
            self._refs.clear()
            self.violations.clear()

    def report(self) -> str:
        return "\n\n".join(v.render() for v in self.violations)


class TrackedLock:
    """Order-tracking wrapper around a raw ``threading.Lock``."""

    _reentrant = False

    def __init__(
        self,
        registry: RaceCheckRegistry,
        name: str = "",
        _factory=None,
    ):
        self._inner = (_factory or _real_lock_factory)()
        self._registry = registry
        self._name = name or f"Lock@{id(self._inner):#x}"
        self._depth = 0  # only the RLock subclass ever exceeds 1

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            if self._depth == 0:
                self._registry.on_acquired(self, self._name)
            if self._reentrant:
                self._depth += 1
            else:
                self._depth = 1
        return got

    def release(self) -> None:
        if self._depth > 0:
            self._depth -= 1
            if self._depth == 0:
                self._registry.on_released(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.release()

    def __getattr__(self, item):
        # Condition() introspects _is_owned/_release_save/_acquire_restore
        # on RLocks; delegate anything we don't track.
        return getattr(self._inner, item)


class TrackedRLock(TrackedLock):
    _reentrant = True

    def __init__(self, registry: RaceCheckRegistry, name: str = ""):
        super().__init__(registry, name, _factory=_real_rlock_factory)
        self._name = name or f"RLock@{id(self._inner):#x}"


# --- global install -------------------------------------------------------

_GLOBAL = RaceCheckRegistry()
_installed = False


def registry() -> RaceCheckRegistry:
    return _GLOBAL


def enabled_by_env() -> bool:
    return os.environ.get(ENV_FLAG, "") == "1"


def _caller_name() -> str:
    """Identify a lock by its allocation site — the stable name the
    inversion report needs (ids recycle, source lines do not)."""
    for frame in reversed(traceback.extract_stack(limit=8)[:-2]):
        if "racecheck" not in (frame.filename or ""):
            return f"{os.path.basename(frame.filename)}:{frame.lineno}"
    return "unknown"


def _tracked_lock_factory() -> TrackedLock:
    return TrackedLock(_GLOBAL, f"Lock({_caller_name()})")


def _tracked_rlock_factory() -> TrackedRLock:
    return TrackedRLock(_GLOBAL, f"RLock({_caller_name()})")


def _tracked_sleep(seconds: float) -> None:
    # Sub-millisecond sleeps are scheduler yields, not blocking waits.
    if seconds >= 0.001:
        _GLOBAL.note_blocking(f"time.sleep({seconds!r})")
    _real_sleep(seconds)


def install() -> None:
    """Patch ``threading.Lock``/``RLock`` and ``time.sleep``.

    Locks created *after* install are tracked; pre-existing locks
    (interpreter internals, already-imported libraries binding
    ``from threading import Lock``) keep working untracked.
    """
    global _installed
    if _installed:
        return
    threading.Lock = _tracked_lock_factory  # type: ignore[misc]
    threading.RLock = _tracked_rlock_factory  # type: ignore[misc]
    time.sleep = _tracked_sleep
    _installed = True


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    threading.Lock = _real_lock_factory  # type: ignore[misc]
    threading.RLock = _real_rlock_factory  # type: ignore[misc]
    time.sleep = _real_sleep
    _installed = False


def installed() -> bool:
    return _installed
