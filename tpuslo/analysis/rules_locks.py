"""TPL110/TPL111: lock discipline for the agent plane's threaded classes.

The delivery channel, breaker, spool, metrics registry, and generator
all share mutable state between the agent loop and worker threads
behind ad-hoc ``threading.Lock``/``RLock`` instances.  Two invariants
are machine-checked here:

* **TPL110 — unguarded write.**  For every class that creates a lock,
  an attribute that is *ever* written under ``with self._lock`` (or
  inside a ``*_locked``-suffixed method, the repo's held-by-contract
  naming convention) is considered lock-protected; any write to it
  outside a lock context is a data race waiting for a scheduler to
  find it.  ``__init__`` is exempt — construction happens-before
  publication of ``self``.

* **TPL111 — lock-order cycle.**  A static acquisition graph is built
  across methods and classes: holding lock A while (transitively,
  through self-calls and calls on members whose class is known to own
  locks) acquiring lock B adds edge A→B.  A cycle in the graph is a
  potential AB/BA deadlock; a self-edge on a non-reentrant ``Lock``
  is a guaranteed one.  The dynamic counterpart is
  ``tpuslo.analysis.racecheck``, which checks the orders that actually
  execute.

``threading.Condition(self._lock)`` aliases the condition attribute to
the wrapped lock, so ``with self._cond`` counts as holding
``self._lock`` (they are the same underlying lock).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterable

from tpuslo.analysis.core import FileContext, Finding, RepoContext, Rule

#: Only toolkit code is in scope — tests construct ad-hoc lock fixtures
#: that would drown the signal.
_SCOPE_PREFIX = "tpuslo/"

_LOCK_FACTORIES = {"Lock", "RLock"}


@dataclass(slots=True)
class _AttrWrite:
    attr: str
    lineno: int
    held: tuple[str, ...]  # canonical lock attrs held at the write


@dataclass(slots=True)
class _Acquire:
    lock: str  # canonical own-lock attr
    lineno: int


@dataclass(slots=True)
class _HeldCall:
    held_lock: str  # canonical own-lock attr held at the call site
    lineno: int
    #: ("self", method) or ("member", attr, method)
    target: tuple[str, ...]


@dataclass(slots=True)
class _MethodInfo:
    name: str
    direct_acquires: list[_Acquire] = field(default_factory=list)
    held_calls: list[_HeldCall] = field(default_factory=list)
    #: plain self-calls made while holding nothing (for transitive
    #: acquisition resolution)
    plain_self_calls: list[str] = field(default_factory=list)


@dataclass
class _ClassInfo:
    rel: str
    name: str
    lineno: int
    #: canonical lock attr -> "Lock" | "RLock"
    locks: dict[str, str] = field(default_factory=dict)
    #: alias attr (Condition wrapper) -> canonical lock attr
    aliases: dict[str, str] = field(default_factory=dict)
    writes: list[_AttrWrite] = field(default_factory=list)
    methods: dict[str, _MethodInfo] = field(default_factory=dict)
    #: member attr -> class name it is constructed from (``self._spool =
    #: DiskSpool(...)``) for cross-class edges
    member_classes: dict[str, str] = field(default_factory=dict)

    def canonical(self, attr: str) -> str | None:
        if attr in self.locks:
            return attr
        return self.aliases.get(attr)


def _self_attr(node: ast.expr) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _written_attr(target: ast.expr) -> str | None:
    """Attribute name written by an assignment target.

    ``self.x = ...`` and ``self.x[...] = ...`` / ``self.x[...] += ...``
    both count as writes to ``x`` — mutating a lock-protected dict's
    slots races exactly like rebinding the attribute.
    """
    attr = _self_attr(target)
    if attr is not None:
        return attr
    if isinstance(target, ast.Subscript):
        return _self_attr(target.value)
    return None


def _is_lock_ctor(node: ast.expr) -> str | None:
    """'Lock'/'RLock' when node is ``threading.Lock()``-style call."""
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    if (
        isinstance(func, ast.Attribute)
        and isinstance(func.value, ast.Name)
        and func.value.id == "threading"
        and func.attr in _LOCK_FACTORIES
    ):
        return func.attr
    return None


def _is_condition_ctor(node: ast.expr) -> ast.Call | None:
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    if (
        isinstance(func, ast.Attribute)
        and isinstance(func.value, ast.Name)
        and func.value.id == "threading"
        and func.attr == "Condition"
    ):
        return node
    return None


class _MethodScanner(ast.NodeVisitor):
    """One pass over a method body tracking the held-lock stack."""

    def __init__(self, cls: _ClassInfo, method: _MethodInfo, in_init: bool):
        self.cls = cls
        self.method = method
        self.in_init = in_init
        self.held: list[str] = []
        if not in_init and method.name.endswith("_locked"):
            # Held-by-contract: *_locked methods run with the class's
            # (single) lock held; multi-lock classes are left alone —
            # the convention cannot name which lock is meant.
            if len(cls.locks) == 1:
                self.held.append(next(iter(cls.locks)))

    # --- lock/alias discovery ------------------------------------------

    def _scan_assign_value(self, attr: str, value: ast.expr) -> None:
        kind = _is_lock_ctor(value)
        if kind is not None:
            self.cls.locks[attr] = kind
            return
        cond = _is_condition_ctor(value)
        if cond is not None:
            if cond.args:
                inner = _self_attr(cond.args[0])
                if inner is not None:
                    self.cls.aliases[attr] = inner
                    return
            # Bare Condition() owns a private RLock.
            self.cls.locks[attr] = "RLock"
            return
        if self.in_init and isinstance(value, ast.Call):
            func = value.func
            if isinstance(func, ast.Name):
                self.cls.member_classes[attr] = func.id
            elif isinstance(func, ast.Attribute):
                self.cls.member_classes[attr] = func.attr

    # --- traversal ------------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            attr = _self_attr(target)
            if attr is not None:
                self._scan_assign_value(attr, node.value)
            self._note_write(target, node.lineno)
        self.generic_visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            attr = _self_attr(node.target)
            if attr is not None:
                self._scan_assign_value(attr, node.value)
            self._note_write(node.target, node.lineno)
            self.generic_visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._note_write(node.target, node.lineno)
        self.generic_visit(node.value)

    def _note_write(self, target: ast.expr, lineno: int) -> None:
        if self.in_init:
            return
        attr = _written_attr(target)
        if attr is None or self.cls.canonical(attr) is not None:
            return
        self.cls.writes.append(_AttrWrite(attr, lineno, tuple(self.held)))

    def visit_With(self, node: ast.With) -> None:
        acquired: list[str] = []
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr is None:
                continue
            lock = self.cls.canonical(attr)
            if lock is None:
                continue
            if not self.in_init:
                self.method.direct_acquires.append(
                    _Acquire(lock, node.lineno)
                )
                if self.held:
                    # Explicit nested acquisition: edge via a pseudo
                    # self-call so the graph builder sees it uniformly.
                    self.method.held_calls.append(
                        _HeldCall(
                            self.held[-1],
                            node.lineno,
                            ("lock", lock),
                        )
                    )
            acquired.append(lock)
        self.held.extend(acquired)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    def visit_Call(self, node: ast.Call) -> None:
        if not self.in_init:
            func = node.func
            callee = None
            if isinstance(func, ast.Attribute):
                owner = func.value
                if isinstance(owner, ast.Name) and owner.id == "self":
                    callee = ("self", func.attr)
                else:
                    member = _self_attr(owner)
                    if member is not None:
                        callee = ("member", member, func.attr)
            if callee is not None:
                if self.held:
                    self.method.held_calls.append(
                        _HeldCall(self.held[-1], node.lineno, callee)
                    )
                elif callee[0] == "self":
                    self.method.plain_self_calls.append(callee[1])
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # Nested defs (closures, callbacks) may run long after the
        # lock is released: analyze their bodies as unguarded.
        saved = self.held
        self.held = []
        for stmt in node.body:
            self.visit(stmt)
        self.held = saved

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Lambda(self, node: ast.Lambda) -> None:
        saved = self.held
        self.held = []
        self.visit(node.body)
        self.held = saved


def _collect_classes(files: Iterable[FileContext]) -> list[_ClassInfo]:
    classes: list[_ClassInfo] = []
    for ctx in files:
        if ctx.tree is None or not ctx.rel.startswith(_SCOPE_PREFIX):
            continue
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            cls = _ClassInfo(ctx.rel, node.name, node.lineno)
            methods = [
                stmt
                for stmt in node.body
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
            ]
            # Two passes: lock attrs may be created in __init__ after
            # other methods are defined textually, and canonical-name
            # resolution needs the full lock/alias map first.
            for meth in methods:
                if meth.name != "__init__":
                    continue
                info = cls.methods.setdefault(
                    meth.name, _MethodInfo(meth.name)
                )
                scanner = _MethodScanner(cls, info, in_init=True)
                for stmt in meth.body:
                    scanner.visit(stmt)
            if not cls.locks:
                # Locks assigned outside __init__ (rare) still count.
                for meth in methods:
                    for sub in ast.walk(meth):
                        if isinstance(sub, ast.Assign):
                            for target in sub.targets:
                                attr = _self_attr(target)
                                if attr is None:
                                    continue
                                kind = _is_lock_ctor(sub.value)
                                if kind is not None:
                                    cls.locks[attr] = kind
            if not cls.locks:
                continue
            for meth in methods:
                if meth.name == "__init__":
                    continue
                info = cls.methods.setdefault(
                    meth.name, _MethodInfo(meth.name)
                )
                scanner = _MethodScanner(cls, info, in_init=False)
                for stmt in meth.body:
                    scanner.visit(stmt)
            classes.append(cls)
    return classes


class LockDisciplineRule(Rule):
    code = "TPL110"
    codes = ("TPL110", "TPL111")
    #: Cross-class lock graphs need the whole toolkit tree even on
    #: git-scoped runs (an AB edge and its BA inversion can live in
    #: files the diff never touched).
    repo_anchors = (_SCOPE_PREFIX,)
    name = "lock-discipline"
    rationale = (
        "attributes written under a lock anywhere must always be "
        "written under it; lock-acquisition cycles deadlock"
    )

    def check_repo(self, repo: RepoContext) -> Iterable[Finding]:
        classes = _collect_classes(repo.files)
        findings: list[Finding] = []
        findings.extend(self._check_unguarded_writes(classes))
        findings.extend(self._check_lock_graph(classes))
        return findings

    # --- TPL110 ---------------------------------------------------------

    @staticmethod
    def _check_unguarded_writes(
        classes: list[_ClassInfo],
    ) -> list[Finding]:
        findings: list[Finding] = []
        for cls in classes:
            # *_locked-method writes count as protected via the held
            # tuple (the scanner seeds held for single-lock classes).
            protected: set[str] = {
                w.attr for w in cls.writes if w.held
            }
            for write in cls.writes:
                if write.attr in protected and not write.held:
                    findings.append(
                        Finding(
                            cls.rel,
                            write.lineno,
                            "TPL110",
                            f"{cls.name}.{write.attr} is written under "
                            f"a lock elsewhere but written here without "
                            f"one (data race)",
                        )
                    )
        return findings

    # --- TPL111 ---------------------------------------------------------

    @staticmethod
    def _check_lock_graph(classes: list[_ClassInfo]) -> list[Finding]:
        by_name: dict[str, _ClassInfo] = {}
        for cls in classes:
            by_name.setdefault(cls.name, cls)

        def node_id(cls: _ClassInfo, lock: str) -> str:
            return f"{cls.name}.{lock}"

        # Transitive lock acquisitions per (class, method).
        memo: dict[tuple[str, str], set[str]] = {}

        def acquires(cls: _ClassInfo, method: str, depth: int = 0) -> set[str]:
            key = (cls.name, method)
            if key in memo:
                return memo[key]
            memo[key] = set()  # cycle guard
            info = cls.methods.get(method)
            if info is None or depth > 6:
                return set()
            out = {node_id(cls, a.lock) for a in info.direct_acquires}
            for callee in info.plain_self_calls:
                out |= acquires(cls, callee, depth + 1)
            for call in info.held_calls:
                # Locks acquired under a held lock are still part of
                # this method's transitive acquisition set.
                out |= _callee_acquires(cls, call, depth + 1)
            memo[key] = out
            return out

        def _callee_acquires(
            cls: _ClassInfo, call: _HeldCall, depth: int
        ) -> set[str]:
            target = call.target
            if target[0] == "lock":
                return {node_id(cls, target[1])}
            if target[0] == "self":
                return acquires(cls, target[1], depth)
            if target[0] == "member":
                member_cls = by_name.get(
                    cls.member_classes.get(target[1], "")
                )
                if member_cls is not None:
                    return acquires(member_cls, target[2], depth)
            return set()

        edges: dict[tuple[str, str], tuple[str, int]] = {}
        for cls in classes:
            for info in cls.methods.values():
                for call in info.held_calls:
                    src = node_id(cls, call.held_lock)
                    for dst in _callee_acquires(cls, call, 0):
                        edges.setdefault(
                            (src, dst), (cls.rel, call.lineno)
                        )

        findings: list[Finding] = []
        # Self-edge on a non-reentrant Lock: guaranteed deadlock.
        for (src, dst), (rel, lineno) in sorted(edges.items()):
            if src == dst:
                cls_name, lock = src.rsplit(".", 1)
                owner = by_name.get(cls_name)
                if owner is not None and owner.locks.get(lock) == "Lock":
                    findings.append(
                        Finding(
                            rel,
                            lineno,
                            "TPL111",
                            f"non-reentrant lock {src} re-acquired while "
                            f"already held (guaranteed deadlock)",
                        )
                    )

        # Cross-lock cycles: DFS over the digraph.
        graph: dict[str, set[str]] = {}
        for src, dst in edges:
            if src != dst:
                graph.setdefault(src, set()).add(dst)
        reported: set[tuple[str, ...]] = set()
        for start in sorted(graph):
            stack = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in sorted(graph.get(node, ())):
                    if nxt == start and len(path) > 1:
                        cycle = tuple(sorted(path))
                        if cycle in reported:
                            continue
                        reported.add(cycle)
                        rel, lineno = edges[(path[-1], start)]
                        findings.append(
                            Finding(
                                rel,
                                lineno,
                                "TPL111",
                                "lock-order cycle (potential deadlock): "
                                + " -> ".join(path + [start]),
                            )
                        )
                    elif nxt not in path and len(path) < 8:
                        stack.append((nxt, path + [nxt]))
        return findings
