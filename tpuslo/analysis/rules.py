"""Rule registry: the analyzer's active rule set, in emission order.

New rules register here; see CONTRIBUTING.md "Adding a lint rule" and
docs/static-analysis.md for the catalog.
"""

from __future__ import annotations

from tpuslo.analysis.core import Rule
from tpuslo.analysis.rules_contracts import (
    ColumnarDtypeDriftRule,
    ConfigDriftRule,
    FleetWireDriftRule,
    MetricsDriftRule,
    SchemaDriftRule,
)
from tpuslo.analysis.rules_except import ExceptionDisciplineRule
from tpuslo.analysis.rules_hotpath import HotPathPurityRule
from tpuslo.analysis.rules_jax import TraceDisciplineRule
from tpuslo.analysis.rules_locks import LockDisciplineRule
from tpuslo.analysis.rules_style import StyleRules

ALL_RULES: tuple[Rule, ...] = (
    StyleRules(),
    SchemaDriftRule(),
    ColumnarDtypeDriftRule(),
    FleetWireDriftRule(),
    ConfigDriftRule(),
    MetricsDriftRule(),
    LockDisciplineRule(),
    HotPathPurityRule(),
    TraceDisciplineRule(),
    ExceptionDisciplineRule(),
)


def rule_catalog() -> list[dict[str, str]]:
    """(code, name, rationale) rows for --list-rules and the docs."""
    rows = []
    for rule in ALL_RULES:
        for code in rule.codes:
            rows.append(
                {"code": code, "name": rule.name, "rationale": rule.rationale}
            )
    return rows
