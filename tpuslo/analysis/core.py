"""tpulint v2 core: rule framework, suppressions, baseline, engine.

The toolkit's static analyzer grew out of ``tools/lint.py`` (a generic
AST style pass) into a contract-aware subsystem: rules know the repo's
real invariants — JSON-schema ↔ dataclass parity, lock discipline for
the agent's threaded classes, hot-path purity, exception accounting,
config drift.  The framework provides what every rule shares:

* stable codes (``TPL0xx`` style ports, ``TPL1xx`` semantic rules);
* per-finding suppression via ``# tpulint: disable=TPL110[,TPL111]``
  on the finding line or the line directly above, and file-level
  ``# tpulint: disable-file=TPL130`` directives;
* a committed baseline file (``.tpulint-baseline.json``) for
  grandfathered findings — the gate is zero-delta against it, and
  every entry must carry a ``reason``;
* human (``path:line: CODE message``) and ``--json`` output.

No external dependencies: the CI image has no ruff/flake8, so the
analyzer is stdlib-AST only (the reference repo pins golangci-lint for
the same role).
"""

from __future__ import annotations

import ast
import json
import re
import subprocess
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Iterable, Iterator

DEFAULT_PATHS = (
    "tpuslo",
    "demo",
    "tests",
    "tools",
    "bench.py",
    "__graft_entry__.py",
)

BASELINE_FILENAME = ".tpulint-baseline.json"

_DISABLE_RE = re.compile(r"#\s*tpulint:\s*disable=([A-Z0-9,\s]+)")
_DISABLE_FILE_RE = re.compile(r"#\s*tpulint:\s*disable-file=([A-Z0-9,\s]+)")


@dataclass(slots=True)
class Finding:
    """One analyzer finding, stable across reruns.

    ``path`` is repo-relative POSIX so baselines survive checkouts in
    different directories; ``message`` must avoid volatile content
    (absolute paths, timestamps) for the same reason.
    """

    path: str
    line: int
    code: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"

    def fingerprint(self) -> tuple[str, str, str]:
        """Line-number-free identity used by baseline matching."""
        return (self.path, self.code, self.message)

    def to_dict(self) -> dict[str, Any]:
        return {
            "path": self.path,
            "line": self.line,
            "code": self.code,
            "message": self.message,
        }


class FileContext:
    """One parsed source file shared by every file-scoped rule.

    Parsing once per file (instead of once per rule) is what keeps the
    full-repo run inside the bench.py < 30 s gate on the 1-CPU box.
    """

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree: ast.Module | None = None
        self.parse_error: SyntaxError | None = None
        try:
            self.tree = ast.parse(source, filename=rel)
        except SyntaxError as exc:
            self.parse_error = exc
        self._file_disabled: set[str] | None = None
        self._line_disabled: dict[int, set[str]] | None = None

    # --- suppression ----------------------------------------------------

    def _scan_directives(self) -> None:
        file_disabled: set[str] = set()
        line_disabled: dict[int, set[str]] = {}
        for lineno, line in enumerate(self.lines, start=1):
            if "tpulint" not in line:
                continue
            m = _DISABLE_FILE_RE.search(line)
            if m:
                file_disabled.update(_parse_codes(m.group(1)))
            m = _DISABLE_RE.search(line)
            if m:
                codes = _parse_codes(m.group(1))
                # A trailing directive governs its own line; a
                # standalone comment line governs the line below it.
                targets = (
                    (lineno, lineno + 1)
                    if line.lstrip().startswith("#")
                    else (lineno,)
                )
                for target in targets:
                    line_disabled.setdefault(target, set()).update(codes)
        self._file_disabled = file_disabled
        self._line_disabled = line_disabled

    def suppressed(self, finding: Finding) -> bool:
        if self._file_disabled is None:
            self._scan_directives()
        assert self._file_disabled is not None
        assert self._line_disabled is not None
        if finding.code in self._file_disabled or "ALL" in self._file_disabled:
            return True
        codes = self._line_disabled.get(finding.line)
        return bool(codes and (finding.code in codes or "ALL" in codes))


def _parse_codes(raw: str) -> set[str]:
    return {c.strip().upper() for c in raw.split(",") if c.strip()}


class RepoContext:
    """The full analyzed tree plus lazily-loaded repo artifacts.

    Repo-scoped rules (schema drift, config drift, metrics drift,
    cross-class lock graphs) need more than one file; they read the
    contracts and registries through here so the engine stays the only
    component that touches the filesystem layout.
    """

    def __init__(self, root: Path, files: list[FileContext]):
        self.root = root
        self.files = files
        self.by_rel = {f.rel: f for f in files}

    def read_json(self, rel: str) -> Any | None:
        path = self.root / rel
        try:
            return json.loads(path.read_text(encoding="utf-8"))
        except (OSError, json.JSONDecodeError):
            return None

    def read_text(self, rel: str) -> str | None:
        try:
            return (self.root / rel).read_text(encoding="utf-8")
        except OSError:
            return None

    def glob_text(self, pattern: str) -> Iterator[tuple[str, str]]:
        for path in sorted(self.root.glob(pattern)):
            try:
                yield (
                    path.relative_to(self.root).as_posix(),
                    path.read_text(encoding="utf-8"),
                )
            except OSError:
                continue


class Rule:
    """Base class: a rule owns one or more stable TPL codes.

    ``check_file`` runs once per analyzed file; ``check_repo`` once per
    run (for contract rules that compare artifacts across files).
    Override whichever applies — the defaults are empty.
    """

    #: Primary code; ``codes`` lists every code the rule can emit.
    code: str = ""
    codes: tuple[str, ...] = ()
    name: str = ""
    rationale: str = ""
    #: Repo-relative files (or ``dir/`` prefixes) a repo-scoped rule
    #: needs in context even when the scanned set is git-scoped
    #: (``--changed``): the engine loads missing anchors from disk so
    #: contract rules genuinely always run, and suppressions inside
    #: anchor files are honored on every run.
    repo_anchors: tuple[str, ...] = ()

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def check_repo(self, repo: RepoContext) -> Iterable[Finding]:
        return ()


# --- baseline ------------------------------------------------------------


@dataclass(slots=True)
class Baseline:
    """Committed grandfathered findings; the gate is zero-delta.

    Matching is by (path, code, message) fingerprint, not line number —
    unrelated edits above a finding must not invalidate the baseline.
    Every entry carries a ``reason`` explaining why it is allowed to
    stay; ``stale`` entries (no longer matched by any finding) are
    reported so the file shrinks over time instead of fossilizing.
    """

    entries: list[dict[str, str]] = field(default_factory=list)

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        try:
            raw = json.loads(path.read_text(encoding="utf-8"))
        except (OSError, json.JSONDecodeError):
            return cls()
        entries = raw.get("entries") if isinstance(raw, dict) else None
        return cls(entries=list(entries or []))

    def save(self, path: Path) -> None:
        payload = {
            "version": 1,
            "comment": (
                "tpulint baseline: grandfathered findings. The lint gate "
                "is zero-delta against this file; every entry needs a "
                "reason and should be burned down, not added to."
            ),
            "entries": self.entries,
        }
        path.write_text(json.dumps(payload, indent=2) + "\n", encoding="utf-8")

    def _keys(self) -> set[tuple[str, str, str]]:
        return {
            (e.get("path", ""), e.get("code", ""), e.get("message", ""))
            for e in self.entries
        }

    def split(
        self, findings: list[Finding]
    ) -> tuple[list[Finding], list[Finding], list[dict[str, str]]]:
        """(new, baselined, stale-entries) partition of a run's output."""
        keys = self._keys()
        new: list[Finding] = []
        matched: list[Finding] = []
        seen: set[tuple[str, str, str]] = set()
        for finding in findings:
            fp = finding.fingerprint()
            if fp in keys:
                matched.append(finding)
                seen.add(fp)
            else:
                new.append(finding)
        stale = [
            e
            for e in self.entries
            if (e.get("path", ""), e.get("code", ""), e.get("message", ""))
            not in seen
        ]
        return new, matched, stale

    @classmethod
    def from_findings(cls, findings: list[Finding]) -> "Baseline":
        return cls(
            entries=[
                {
                    "path": f.path,
                    "code": f.code,
                    "message": f.message,
                    "reason": "TODO: justify or fix",
                }
                for f in findings
            ]
        )


# --- engine --------------------------------------------------------------

_SKIP_DIR_PARTS = frozenset({"__pycache__", ".git", "node_modules"})


def iter_py_files(root: Path, paths: Iterable[str]) -> list[Path]:
    out: list[Path] = []
    for raw in paths:
        p = Path(raw)
        if not p.is_absolute():
            p = root / p
        if p.is_dir():
            out.extend(
                f
                for f in sorted(p.rglob("*.py"))
                if not _SKIP_DIR_PARTS.intersection(f.parts)
            )
        elif p.suffix == ".py" and p.exists():
            out.append(p)
    return out


def changed_py_files(root: Path) -> list[Path]:
    """Python files touched vs HEAD (staged, unstaged, untracked) —
    the ``make lint-changed`` scope."""
    cmds = (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    )
    names: set[str] = set()
    for cmd in cmds:
        try:
            proc = subprocess.run(
                cmd, cwd=root, capture_output=True, text=True, timeout=30
            )
        except (OSError, subprocess.SubprocessError):
            continue
        if proc.returncode == 0:
            names.update(
                line.strip()
                for line in proc.stdout.splitlines()
                if line.strip().endswith(".py")
            )
    return [root / n for n in sorted(names) if (root / n).exists()]


@dataclass(slots=True)
class AnalysisResult:
    findings: list[Finding]
    suppressed: int
    files_scanned: int

    def to_dict(self) -> dict[str, Any]:
        return {
            "files_scanned": self.files_scanned,
            "suppressed": self.suppressed,
            "findings": [f.to_dict() for f in self.findings],
        }


def run_analysis(
    root: Path,
    paths: Iterable[str] | None = None,
    rules: Iterable[Rule] | None = None,
    files: list[Path] | None = None,
) -> AnalysisResult:
    """Parse once, run every rule, apply suppressions, sort stably.

    ``files`` overrides path discovery (the ``--changed`` scope);
    repo-scoped rules still see the full context they need because
    each declares ``repo_anchors`` — the engine loads any anchor file
    missing from the scanned set, file-scoped rules run only over the
    requested files.
    """
    from tpuslo.analysis.rules import ALL_RULES

    root = root.resolve()
    active_rules = list(rules) if rules is not None else list(ALL_RULES)
    file_paths = (
        list(files)
        if files is not None
        else iter_py_files(root, paths or DEFAULT_PATHS)
    )

    contexts: list[FileContext] = []
    findings: list[Finding] = []

    def load(path: Path, report_errors: bool) -> FileContext | None:
        try:
            source = path.read_text(encoding="utf-8")
        except OSError as exc:
            if report_errors:
                findings.append(
                    Finding(
                        _rel(root, path),
                        1,
                        "TPL000",
                        f"unreadable: {exc.strerror}",
                    )
                )
            return None
        ctx = FileContext(path, _rel(root, path), source)
        if ctx.parse_error is not None:
            if report_errors:
                findings.append(
                    Finding(
                        ctx.rel,
                        ctx.parse_error.lineno or 1,
                        "TPL000",
                        f"syntax error: {ctx.parse_error.msg}",
                    )
                )
            return None
        return ctx

    for path in file_paths:
        ctx = load(path, report_errors=True)
        if ctx is not None:
            contexts.append(ctx)

    # Anchor files repo rules need beyond the scanned set (the
    # git-scoped mode): loaded for RepoContext only — file-scoped
    # rules still run over exactly the requested files.
    anchors: list[FileContext] = []
    have = {c.rel for c in contexts}
    for rule in active_rules:
        for anchor in rule.repo_anchors:
            if anchor.endswith("/"):
                anchor_files = iter_py_files(root, [anchor.rstrip("/")])
            else:
                anchor_files = [root / anchor]
            for path in anchor_files:
                rel = _rel(root, path)
                if rel in have or not path.exists():
                    continue
                have.add(rel)
                ctx = load(path, report_errors=False)
                if ctx is not None:
                    anchors.append(ctx)

    repo = RepoContext(root, contexts + anchors)
    for rule in active_rules:
        for ctx in contexts:
            findings.extend(rule.check_file(ctx))
        findings.extend(rule.check_repo(repo))

    kept: list[Finding] = []
    suppressed = 0
    for finding in findings:
        ctx = repo.by_rel.get(finding.path)
        if ctx is not None and ctx.suppressed(finding):
            suppressed += 1
            continue
        kept.append(finding)
    kept.sort(key=lambda f: (f.path, f.line, f.code, f.message))
    return AnalysisResult(
        findings=kept, suppressed=suppressed, files_scanned=len(file_paths)
    )


def _rel(root: Path, path: Path) -> str:
    try:
        return path.resolve().relative_to(root).as_posix()
    except ValueError:
        return path.as_posix()
