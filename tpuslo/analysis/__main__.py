"""CLI for the analyzer: ``python -m tpuslo.analysis [paths...]``.

Exit codes: 0 clean (or fully baselined), 1 non-baselined findings,
2 usage/configuration error.  ``make lint`` runs this over the repo's
default trees with the committed baseline; ``make lint-changed`` scopes
the file-level rules to git-changed files (repo-contract rules always
run — they are cheap and cross-file by nature).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from tpuslo.analysis.core import (
    BASELINE_FILENAME,
    Baseline,
    changed_py_files,
    run_analysis,
)
from tpuslo.analysis.rules import rule_catalog


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpuslo.analysis", description=__doc__
    )
    p.add_argument("paths", nargs="*", help="files/dirs (default: repo trees)")
    p.add_argument("--root", default=".", help="repo root (default: cwd)")
    p.add_argument("--json", action="store_true", help="machine output")
    p.add_argument(
        "--baseline",
        default="",
        help=f"baseline file (default: <root>/{BASELINE_FILENAME})",
    )
    p.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, ignoring the baseline",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="write current findings as the new baseline and exit 0 "
        "(each entry still needs a human-written reason)",
    )
    p.add_argument(
        "--changed",
        action="store_true",
        help="lint only git-changed .py files (plus repo-contract rules)",
    )
    p.add_argument("--list-rules", action="store_true")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for row in rule_catalog():
            print(f"{row['code']:7s} {row['name']:20s} {row['rationale']}")
        return 0

    root = Path(args.root).resolve()
    files = None
    if args.changed:
        files = changed_py_files(root)
        if not files:
            print("tpulint: no changed python files", file=sys.stderr)

    result = run_analysis(
        root, paths=args.paths or None, files=files
    )
    if result.files_scanned == 0 and not args.changed:
        # Fail closed: a gate that scanned nothing (wrong --root, cwd
        # outside the repo) must not report a green lint run.
        print(
            f"tpulint: no python files found under {root} — wrong "
            "--root or cwd? refusing to pass an empty gate",
            file=sys.stderr,
        )
        return 2

    baseline_path = Path(args.baseline) if args.baseline else (
        root / BASELINE_FILENAME
    )
    if args.write_baseline:
        regenerated = Baseline.from_findings(result.findings)
        # Preserve human-written justifications for entries that are
        # still live — regeneration must not reset them to TODO.
        existing = {
            (e.get("path", ""), e.get("code", ""), e.get("message", "")):
                e.get("reason", "")
            for e in Baseline.load(baseline_path).entries
        }
        for entry in regenerated.entries:
            kept = existing.get(
                (entry["path"], entry["code"], entry["message"])
            )
            if kept and not kept.startswith("TODO"):
                entry["reason"] = kept
        regenerated.save(baseline_path)
        print(
            f"tpulint: wrote {len(regenerated.entries)} entries to "
            f"{baseline_path}",
            file=sys.stderr,
        )
        return 0

    baseline = (
        Baseline()
        if args.no_baseline
        else Baseline.load(baseline_path)
    )
    new, baselined, stale = baseline.split(result.findings)

    if args.json:
        print(
            json.dumps(
                {
                    "files_scanned": result.files_scanned,
                    "suppressed": result.suppressed,
                    "baselined": len(baselined),
                    "stale_baseline_entries": stale,
                    "findings": [f.to_dict() for f in new],
                },
                indent=2,
            )
        )
    else:
        for finding in new:
            print(finding.render())
        for entry in stale:
            print(
                f"tpulint: stale baseline entry ({entry.get('code')} "
                f"{entry.get('path')}): remove it from {baseline_path.name}",
                file=sys.stderr,
            )
    print(
        f"tpulint: {result.files_scanned} files, {len(new)} findings "
        f"({len(baselined)} baselined, {result.suppressed} suppressed)",
        file=sys.stderr,
    )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
