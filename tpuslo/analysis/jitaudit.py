"""Dynamic retrace/host-sync auditor (``TPUSLO_JITAUDIT=1``).

The static TPL160-163 rules (:mod:`tpuslo.analysis.rules_jax`) see the
dispatch hazards the AST admits; this module counts the ones that
actually *execute*.  When installed it hooks three layers:

* **XLA compiles** via :mod:`jax.monitoring` duration events
  (``/jax/core/compile/jaxpr_trace_duration`` and
  ``backend_compile_duration``) — every trace and every backend
  compile is recorded against the audit section active at that moment.
* **Per-function compile counts** by wrapping ``jax.jit``: every
  wrapper constructed after install reports its executable-cache
  growth per call, so a retrace storm names the function that churns
  (the BENCH_r05 spec-decode defect was a fresh ``jax.jit`` per chunk
  — invisible in aggregate counters, obvious per function).
* **Host-device traffic** by wrapping ``jax.device_get`` (fused
  device→host reads) and ``jnp.asarray``/``jnp.array`` applied to
  non-device values (host→device uploads — the per-round scalar churn
  TPL160/162 flag statically).  Implicit syncs (``int(arr)``,
  ``np.asarray(arr)``) bypass Python and cannot be intercepted; the
  serving plane's contract is that every host read routes through ONE
  fused ``device_get``, so the explicit counters are the meaningful
  ones (and the static TPL160 pass rejects the implicit forms).

**Steady-state sections** are the gate.  Code that has finished
warmup declares it (:meth:`JitAuditRegistry.steady`, or conditional
per-iteration ``push_section``/``pop_section`` as the serving loops
do); any backend compile recorded inside a steady section
is a violation.  :class:`tpuslo.models.speculative.SpeculativeEngine`
and :meth:`tpuslo.models.serve.ServeEngine.generate` self-declare
their post-warmup decode loops when the auditor is installed, so
``make jitcheck-smoke`` (``TPUSLO_JITAUDIT=1`` over the serving
suites — :data:`SMOKE_SUITES`, gated in ``tests/conftest.py``) fails
the session if a steady-state decode loop ever recompiles — the
dynamic counterpart of every TPL161 finding.  ``bench.py``'s measured
speculation lane reads ``spec_retrace_count`` and
``decode_host_syncs_per_token`` from the same registry as gated
release counters.

Violations are recorded, not raised (raising inside a monitoring
callback would corrupt the compile in flight); the pytest wiring
fails the session at teardown, mirroring ``racecheck``.
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from dataclasses import dataclass

ENV_FLAG = "TPUSLO_JITAUDIT"

#: The serving suites ``make jitcheck-smoke`` / ``m5gate
#: --jitcheck-smoke`` run under the auditor: the speculative-decode
#: exactness suite (whose engines self-declare steady sections) plus
#: the auditor's own deterministic planted-churn tests.
SMOKE_SUITES = (
    "tests/test_speculative.py",
    "tests/test_jitaudit.py",
)

_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


@dataclass(slots=True)
class CompileEvent:
    section: str  # active section label ("" outside any section)
    steady: bool
    kind: str  # "trace" | "backend_compile"
    duration_ms: float


@dataclass(slots=True)
class Violation:
    section: str
    detail: str

    def render(self) -> str:
        return f"jitaudit: steady-state recompile in [{self.section}]: {self.detail}"


class JitAuditRegistry:
    """Compile/transfer counters bucketed by audit section.

    Sections nest (a stack); counters attribute to the innermost
    label.  The registry is process-global when installed via
    :func:`install`; unit tests construct their own and drive the
    ``on_*`` hooks directly so provoked churn never pollutes the
    session gate.
    """

    def __init__(self, max_violations: int = 64):
        self._mu = threading.Lock()
        # Sections are per-thread: jax compiles run on the calling
        # thread, so a steady section opened by one serving loop must
        # not claim (and fail on) another thread's legitimate
        # first-hit compile.
        self._tls = threading.local()
        self.events: list[CompileEvent] = []
        self.violations: list[Violation] = []
        #: function name -> executable-cache entries compiled (from
        #: wrapped ``jax.jit`` functions; aggregate events catch the
        #: rest).
        self.fn_compiles: dict[str, int] = {}
        #: section label -> fused device->host reads / host->device
        #: uploads observed while that section was innermost.
        self.host_reads: dict[str, int] = {}
        self.uploads: dict[str, int] = {}
        self._max_violations = max_violations

    # --- sections -------------------------------------------------------

    def _stack(self) -> list[tuple[str, bool]]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _current(self) -> tuple[str, bool]:
        stack = self._stack()
        return stack[-1] if stack else ("", False)

    def push_section(self, label: str, steady: bool = False) -> None:
        self._stack().append((label, steady))

    def pop_section(self) -> None:
        stack = self._stack()
        if stack:
            stack.pop()

    @contextmanager
    def section(self, label: str, steady: bool = False):
        self.push_section(label, steady)
        try:
            yield self
        finally:
            self.pop_section()

    def steady(self, label: str):
        """A post-warmup region: any backend compile inside is a
        violation (the loop's shapes are fixed; a recompile means
        retrace churn — the BENCH_r05 5x-slowdown class)."""
        return self.section(label, steady=True)

    # --- hooks (called by the installed patches) -----------------------

    def on_compile(self, kind: str, duration_ms: float) -> None:
        with self._mu:
            section, steady = self._current()
            self.events.append(
                CompileEvent(section, steady, kind, duration_ms)
            )
            if steady and kind == "backend_compile":
                if len(self.violations) < self._max_violations:
                    self.violations.append(
                        Violation(
                            section,
                            f"XLA backend compile ({duration_ms:.1f} ms) "
                            "after the loop declared steady state",
                        )
                    )

    def on_fn_compiles(self, name: str, n: int) -> None:
        with self._mu:
            self.fn_compiles[name] = self.fn_compiles.get(name, 0) + n

    def on_host_read(self) -> None:
        with self._mu:
            label = self._current()[0]
            self.host_reads[label] = self.host_reads.get(label, 0) + 1

    def on_upload(self) -> None:
        with self._mu:
            label = self._current()[0]
            self.uploads[label] = self.uploads.get(label, 0) + 1

    # --- reads ----------------------------------------------------------

    def compile_count(self, kind: str = "backend_compile") -> int:
        with self._mu:
            return sum(1 for e in self.events if e.kind == kind)

    def steady_compile_count(self) -> int:
        """Backend compiles recorded inside steady sections — the
        retrace count every serving gate floors at zero."""
        with self._mu:
            return sum(
                1
                for e in self.events
                if e.steady and e.kind == "backend_compile"
            )

    def host_sync_count(self) -> int:
        """Explicit host<->device round-trips: fused reads + uploads."""
        with self._mu:
            return sum(self.host_reads.values()) + sum(
                self.uploads.values()
            )

    def reset(self) -> None:
        with self._mu:
            self.events.clear()
            self.violations.clear()
            self.fn_compiles.clear()
            self.host_reads.clear()
            self.uploads.clear()

    def report(self) -> str:
        lines = [v.render() for v in self.violations]
        if self.fn_compiles:
            top = sorted(
                self.fn_compiles.items(), key=lambda kv: -kv[1]
            )[:8]
            lines.append(
                "per-function compiles: "
                + ", ".join(f"{name}={n}" for name, n in top)
            )
        return "\n".join(lines)


# --- global install -------------------------------------------------------

_GLOBAL = JitAuditRegistry()
_installed = False
_real_jit = None
_real_device_get = None
_real_asarray = None
_real_array = None


def registry() -> JitAuditRegistry:
    return _GLOBAL


def enabled_by_env() -> bool:
    return os.environ.get(ENV_FLAG, "") == "1"


def installed() -> bool:
    return _installed


class TrackedJitFunction:
    """Call-through proxy over a real jit wrapper that reports
    executable-cache growth per call (attributing compiles to the
    function the static rules would name)."""

    __slots__ = ("_fn", "_name", "_registry", "_last_size")

    def __init__(self, fn, name: str, reg: JitAuditRegistry):
        self._fn = fn
        self._name = name
        self._registry = reg
        self._last_size = 0

    def __call__(self, *args, **kwargs):
        out = self._fn(*args, **kwargs)
        try:
            size = self._fn._cache_size()
        except Exception:  # noqa: BLE001 - older jax: no cache probe
            return out
        if size > self._last_size:
            self._registry.on_fn_compiles(
                self._name, size - self._last_size
            )
            self._last_size = size
        return out

    def __getattr__(self, item):
        return getattr(self._fn, item)


def _on_duration(name: str, duration: float, **kwargs) -> None:
    if name == _COMPILE_EVENT:
        _GLOBAL.on_compile("backend_compile", duration * 1000.0)
    elif name == _TRACE_EVENT:
        _GLOBAL.on_compile("trace", duration * 1000.0)


def _fn_label(fun) -> str:
    qual = getattr(fun, "__qualname__", None) or getattr(
        fun, "__name__", None
    )
    if qual:
        return qual
    inner = getattr(fun, "func", None)  # functools.partial
    if inner is not None:
        return f"partial({_fn_label(inner)})"
    return type(fun).__name__


def _tracked_jit(fun=None, **kwargs):
    if fun is None:
        # jax.jit(static_argnums=...) decorator-factory form.
        return lambda f: _tracked_jit(f, **kwargs)
    assert _real_jit is not None
    return TrackedJitFunction(
        _real_jit(fun, **kwargs), _fn_label(fun), _GLOBAL
    )


def _tracked_device_get(x):
    _GLOBAL.on_host_read()
    assert _real_device_get is not None
    return _real_device_get(x)


def _is_host_value(x) -> bool:
    import jax

    return not isinstance(x, (jax.Array, jax.core.Tracer))


def _tracked_asarray(a, *args, **kwargs):
    if _is_host_value(a):
        _GLOBAL.on_upload()
    assert _real_asarray is not None
    return _real_asarray(a, *args, **kwargs)


def _tracked_array(a, *args, **kwargs):
    if _is_host_value(a):
        _GLOBAL.on_upload()
    assert _real_array is not None
    return _real_array(a, *args, **kwargs)


def install() -> None:
    """Hook jax.monitoring + patch jit/device_get/asarray/array.

    jit wrappers created *before* install keep working untracked (the
    aggregate monitoring events still count their compiles); the
    lru-cached serving kernels are tracked whenever the auditor is
    installed before engine construction — which the smoke suites and
    the bench lane guarantee by installing first.
    """
    global _installed, _real_jit, _real_device_get
    global _real_asarray, _real_array
    if _installed:
        return
    import jax
    import jax.monitoring
    import jax.numpy as jnp

    jax.monitoring.register_event_duration_secs_listener(_on_duration)
    _real_jit = jax.jit
    _real_device_get = jax.device_get
    _real_asarray = jnp.asarray
    _real_array = jnp.array
    jax.jit = _tracked_jit
    jax.device_get = _tracked_device_get
    jnp.asarray = _tracked_asarray
    jnp.array = _tracked_array
    _installed = True


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    import jax
    import jax.numpy as jnp

    try:
        from jax._src import monitoring as _mon

        _mon._unregister_event_duration_listener_by_callback(_on_duration)
    except Exception:  # noqa: BLE001 - private API moved: listener stays,
        pass  # but it only appends to this registry, which is inert.
    jax.jit = _real_jit
    jax.device_get = _real_device_get
    jnp.asarray = _real_asarray
    jnp.array = _real_array
    _installed = False
