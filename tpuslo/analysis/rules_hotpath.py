"""TPL120/TPL121: hot-path purity, driven by the hotpaths manifest.

See :mod:`tpuslo.analysis.hotpaths` for what is registered and why.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tpuslo.analysis.core import Finding, RepoContext, Rule
from tpuslo.analysis.hotpaths import HOT_DATACLASSES, HOT_FUNCTIONS

_LOGGER_NAMES = frozenset({"logger", "log", "LOGGER", "LOG", "_LOG", "_LOGGER"})
_LOGGER_METHODS = frozenset(
    {"debug", "info", "warning", "warn", "error", "exception", "critical"}
)


def _forbidden_call(node: ast.Call) -> str | None:
    """Human-readable name of a banned hot-path call, or None."""
    func = node.func
    if isinstance(func, ast.Name):
        if func.id == "print":
            return "print"
        if func.id == "deepcopy":
            return "deepcopy"
        if func.id == "urandom":
            return "urandom"
        return None
    if not isinstance(func, ast.Attribute):
        return None
    owner = func.value
    if not isinstance(owner, ast.Name):
        return None
    base, attr = owner.id, func.attr
    if base == "json" and attr in ("dumps", "dump"):
        return f"json.{attr}"
    if base == "copy" and attr == "deepcopy":
        return "copy.deepcopy"
    if base == "time" and attr in ("time", "time_ns"):
        return f"time.{attr}"
    if base == "os" and attr == "urandom":
        return "os.urandom"
    if base == "logging":
        return f"logging.{attr}"
    if base in _LOGGER_NAMES and attr in _LOGGER_METHODS:
        return f"{base}.{attr}"
    return None


def _function_index(
    tree: ast.Module,
) -> dict[str, ast.FunctionDef | ast.AsyncFunctionDef]:
    """Map ``qualname`` (``func`` or ``Class.method``) -> def node."""
    index: dict[str, ast.FunctionDef | ast.AsyncFunctionDef] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            index[node.name] = node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    index[f"{node.name}.{sub.name}"] = sub
    return index


def _dataclass_has_slots(node: ast.ClassDef) -> bool:
    for deco in node.decorator_list:
        if isinstance(deco, ast.Call):
            for kw in deco.keywords:
                if (
                    kw.arg == "slots"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True
                ):
                    return True
    # __slots__ declared in the class body also satisfies the contract
    # (plain classes on the hot path use it directly).
    for stmt in node.body:
        if isinstance(stmt, ast.Assign):
            for target in stmt.targets:
                if isinstance(target, ast.Name) and target.id == "__slots__":
                    return True
    return False


_MANIFEST_REL = "tpuslo/analysis/hotpaths.py"


class HotPathPurityRule(Rule):
    code = "TPL120"
    codes = ("TPL120", "TPL121")
    #: Manifest files are loaded on every run (incl. git-scoped), so a
    #: deleted or renamed hot-path module is a finding, never a skip.
    repo_anchors = tuple(
        sorted(
            {rel for rel, _ in HOT_FUNCTIONS}
            | {rel for rel, _ in HOT_DATACLASSES}
        )
    )
    name = "hot-path-purity"
    rationale = (
        "manifest-registered hot functions must stay free of known "
        "per-event poisons and allocate only slotted dataclasses"
    )

    def check_repo(self, repo: RepoContext) -> Iterable[Finding]:
        if not (repo.root / _MANIFEST_REL).exists():
            # The manifest governs the repo that contains it; on a
            # foreign root (fixture trees) there is nothing to enforce.
            return ()
        findings: list[Finding] = []
        for rel, qualname in HOT_FUNCTIONS:
            ctx = repo.by_rel.get(rel)
            if ctx is None or ctx.tree is None:
                findings.append(
                    Finding(
                        _MANIFEST_REL,
                        1,
                        "TPL120",
                        f"manifest entry {rel}:{qualname} points at a "
                        "missing or unparseable file — update the "
                        "hotpaths manifest with the move",
                    )
                )
                continue
            node = _function_index(ctx.tree).get(qualname)
            if node is None:
                findings.append(
                    Finding(
                        _MANIFEST_REL,
                        1,
                        "TPL120",
                        f"manifest entry {rel}:{qualname} not found — "
                        "update the hotpaths manifest with the rename",
                    )
                )
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    banned = _forbidden_call(sub)
                    if banned is not None:
                        findings.append(
                            Finding(
                                rel,
                                sub.lineno,
                                "TPL120",
                                f"hot path {qualname} calls {banned} "
                                "(per-event cost; see docs/hot-path.md)",
                            )
                        )
        for rel, clsname in HOT_DATACLASSES:
            ctx = repo.by_rel.get(rel)
            if ctx is None or ctx.tree is None:
                findings.append(
                    Finding(
                        _MANIFEST_REL,
                        1,
                        "TPL121",
                        f"manifest dataclass {rel}:{clsname} points at "
                        "a missing or unparseable file — update the "
                        "hotpaths manifest with the move",
                    )
                )
                continue
            cls_node = next(
                (
                    n
                    for n in ast.walk(ctx.tree)
                    if isinstance(n, ast.ClassDef) and n.name == clsname
                ),
                None,
            )
            if cls_node is None:
                findings.append(
                    Finding(
                        _MANIFEST_REL,
                        1,
                        "TPL121",
                        f"manifest dataclass {rel}:{clsname} not found — "
                        "update the hotpaths manifest with the rename",
                    )
                )
                continue
            if not _dataclass_has_slots(cls_node):
                findings.append(
                    Finding(
                        rel,
                        cls_node.lineno,
                        "TPL121",
                        f"hot-path dataclass {clsname} must declare "
                        "slots (per-event __dict__ allocation)",
                    )
                )
        return findings
