"""TPL001–TPL009: generic AST style/defect rules.

Ported from the original ``tools/lint.py`` (tpulint v1) into the rule
framework; the codes and semantics are unchanged so existing inline
``# noqa: unused (name)`` annotations and developer muscle memory keep
working.  These are the non-semantic tier — the TPL1xx rules carry the
repo-contract knowledge.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tpuslo.analysis.core import FileContext, Finding, Rule

_DUNDER_ALL = "__all__"


class _StyleVisitor(ast.NodeVisitor):
    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.findings: list[Finding] = []
        self.imports: dict[str, int] = {}
        self.used_names: set[str] = set()
        self.exported: set[str] = set()

    def report(self, lineno: int, code: str, message: str) -> None:
        self.findings.append(Finding(self.ctx.rel, lineno, code, message))

    # --- collection -----------------------------------------------------

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            self.imports.setdefault(name, node.lineno)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            return  # compiler directives, not bindings
        for alias in node.names:
            if alias.name == "*":
                continue
            name = alias.asname or alias.name
            self.imports.setdefault(name, node.lineno)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.used_names.add(node.id)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            if isinstance(target, ast.Name) and target.id == _DUNDER_ALL:
                value = node.value
                if isinstance(value, (ast.List, ast.Tuple)):
                    for elt in value.elts:
                        if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str
                        ):
                            self.exported.add(elt.value)
        self.generic_visit(node)

    # --- per-node checks ------------------------------------------------

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self.report(node.lineno, "TPL003", "bare except:")
        if node.name:
            used = False
            reraised = False
            for child in ast.walk(ast.Module(body=node.body, type_ignores=[])):
                if isinstance(child, ast.Name) and child.id == node.name:
                    used = True
                if isinstance(child, ast.Raise) and child.exc is None:
                    reraised = True
            if not used and not reraised:
                self.report(
                    node.lineno,
                    "TPL009",
                    f"exception bound as {node.name!r} but never used",
                )
        self.generic_visit(node)

    def _check_defaults(
        self, node: ast.FunctionDef | ast.AsyncFunctionDef
    ) -> None:
        for default in [*node.args.defaults, *node.args.kw_defaults]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                self.report(
                    default.lineno,
                    "TPL004",
                    f"mutable default argument in {node.name}()",
                )

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._check_defaults(node)
        self._check_param_shadowing(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._check_defaults(node)
        self._check_param_shadowing(node)
        self.generic_visit(node)

    def _check_param_shadowing(
        self, node: ast.FunctionDef | ast.AsyncFunctionDef
    ) -> None:
        params = {
            a.arg
            for a in [
                *node.args.posonlyargs,
                *node.args.args,
                *node.args.kwonlyargs,
                *([node.args.vararg] if node.args.vararg else []),
                *([node.args.kwarg] if node.args.kwarg else []),
            ]
        }
        for child in node.body:
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ) and child.name in params:
                self.report(
                    child.lineno,
                    "TPL008",
                    f"inner {child.name!r} shadows parameter of {node.name}()",
                )

    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        if not any(isinstance(v, ast.FormattedValue) for v in node.values):
            self.report(node.lineno, "TPL005", "f-string without placeholders")
        self.generic_visit(node)

    def visit_FormattedValue(self, node: ast.FormattedValue) -> None:
        # Visit only the value: a format spec is itself a JoinedStr
        # (f"{x:.2f}") and must not trip the placeholder check.
        self.visit(node.value)

    def visit_Compare(self, node: ast.Compare) -> None:
        for op, comparator in zip(node.ops, node.comparators):
            if (
                isinstance(op, (ast.Eq, ast.NotEq))
                and isinstance(comparator, ast.Constant)
                and comparator.value is None
            ):
                self.report(
                    node.lineno,
                    "TPL006",
                    "comparison to None with ==/!= (use is/is not)",
                )
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        if isinstance(node.test, ast.Tuple) and node.test.elts:
            self.report(
                node.lineno, "TPL007", "assert on a tuple is always true"
            )
        self.generic_visit(node)

    # --- module-level checks --------------------------------------------

    def check_duplicate_defs(self, tree: ast.Module) -> None:
        scopes: list[tuple[str, list[ast.stmt]]] = [("module", tree.body)]
        for scope_name, body in scopes:
            seen: dict[str, int] = {}
            for stmt in body:
                if isinstance(stmt, ast.ClassDef):
                    scopes.append((stmt.name, stmt.body))
                if isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    # Decorated re-bindings (@overload, @property+setter,
                    # @functools.singledispatch registrations) are
                    # legitimate double bindings.
                    if stmt.decorator_list:
                        continue
                    if stmt.name in seen:
                        self.report(
                            stmt.lineno,
                            "TPL002",
                            f"{stmt.name!r} already defined at line "
                            f"{seen[stmt.name]} in {scope_name}",
                        )
                    seen[stmt.name] = stmt.lineno

    def check_unused_imports(self) -> None:
        is_init = self.ctx.rel.endswith("__init__.py")
        for name, lineno in sorted(self.imports.items(), key=lambda kv: kv[1]):
            if name.startswith("_"):
                continue
            if name in self.used_names or name in self.exported:
                continue
            if is_init:
                # Package __init__ re-exports are the module's API even
                # without __all__; only flag when __all__ exists and
                # omits the name (then it is truly dead).
                if not self.exported:
                    continue
            # Conftest-style side-effect imports are annotated inline.
            if f"# noqa: unused ({name})" in self.ctx.source:
                continue
            self.report(lineno, "TPL001", f"unused import {name!r}")


class StyleRules(Rule):
    code = "TPL001"
    codes = (
        "TPL001",
        "TPL002",
        "TPL003",
        "TPL004",
        "TPL005",
        "TPL006",
        "TPL007",
        "TPL008",
        "TPL009",
    )
    name = "style"
    rationale = "generic defect classes ported from tpulint v1"

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None:
            return ()
        visitor = _StyleVisitor(ctx)
        visitor.visit(ctx.tree)
        visitor.check_duplicate_defs(ctx.tree)
        visitor.check_unused_imports()
        return visitor.findings
