"""Device-mesh construction and GSPMD sharding rules for the demo models.

TPU-first scaling design (vs the reference, which has no parallelism —
SURVEY.md §2.5): a ``jax.sharding.Mesh`` with axes

* ``dp``   — data parallel (batch), gradients all-reduced over ICI;
* ``fsdp`` — parameter/optimizer sharding along the feature axis
             (ZeRO-style), all-gathered per layer by XLA;
* ``tp``   — tensor parallel: attention heads and MLP hidden are
             column-sharded, output projections row-sharded, so each
             layer needs one ``psum`` on the row-parallel matmuls;
* ``sp``   — sequence/context parallel for long sequences (ring
             attention over ``ppermute``, see
             :mod:`tpuslo.ops.ring_attention`).

Shardings are declared with ``NamedSharding`` + ``PartitionSpec`` and
handed to ``jax.jit`` — XLA GSPMD inserts the collectives; nothing here
hand-schedules communication.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "fsdp", "tp")


@dataclass(frozen=True)
class MeshPlan:
    """Device factorization.  ``dcn > 1`` adds an OUTER multi-slice
    axis: pure data parallelism across TPU slices connected by DCN
    (data-center network, ~10-100x slower than ICI).  The axis order
    makes the bandwidth economics structural: fsdp all-gathers and tp
    psums ride the inner (ICI) axes because slices replicate the model;
    the ONLY collective that crosses DCN is the once-per-step gradient
    all-reduce — the canonical multi-slice layout (each slice trains a
    full model replica; scale slices for global batch)."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    dcn: int = 1

    @property
    def n_devices(self) -> int:
        return self.dcn * self.dp * self.fsdp * self.tp


def plan_for_devices(n: int, slices: int = 1) -> MeshPlan:
    """Reasonable default factorization: tp innermost (fastest ICI hops),
    then fsdp, then dp; ``slices > 1`` factors a dcn axis out first
    (each slice gets the single-slice plan for its own chips)."""
    if slices > 1:
        if n % slices:
            raise ValueError(f"{n} devices not divisible by {slices} slices")
        inner = plan_for_devices(n // slices)
        return MeshPlan(
            dp=inner.dp, fsdp=inner.fsdp, tp=inner.tp, dcn=slices
        )
    tp = 1
    for candidate in (8, 4, 2):
        if n % candidate == 0:
            tp = candidate
            break
    rest = n // tp
    fsdp = 1
    for candidate in (4, 2):
        if rest % candidate == 0:
            fsdp = candidate
            break
    dp = rest // fsdp
    return MeshPlan(dp=dp, fsdp=fsdp, tp=tp)


def make_mesh(plan: MeshPlan, devices: list | None = None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if len(devices) < plan.n_devices:
        raise ValueError(
            f"plan needs {plan.n_devices} devices, have {len(devices)}"
        )
    # Multi-slice: the dcn axis is OUTERMOST so a contiguous run of
    # device ids (one slice's chips) forms each inner submesh —
    # inner-axis collectives never leave the slice.
    shape, names = (
        ((plan.dcn, plan.dp, plan.fsdp, plan.tp), ("dcn", *AXES))
        if plan.dcn > 1
        else ((plan.dp, plan.fsdp, plan.tp), AXES)
    )
    return Mesh(np.asarray(devices[: plan.n_devices]).reshape(shape), names)


def param_shardings(mesh: Mesh) -> dict:
    """PartitionSpec tree matching ``tpuslo.models.llama.init_params``.

    Column-parallel projections shard their output dim on ``tp`` and
    input dim on ``fsdp``; row-parallel projections are transposed.
    Layer-stacked leaves keep the leading layer axis unsharded so the
    ``lax.scan`` body stays uniform.
    """
    ns = lambda spec: NamedSharding(mesh, spec)  # noqa: E731
    return {
        "embed": ns(P("tp", "fsdp")),
        "layers": {
            "attn_norm": ns(P(None, None)),
            "wq": ns(P(None, "fsdp", "tp")),
            "wk": ns(P(None, "fsdp", "tp")),
            "wv": ns(P(None, "fsdp", "tp")),
            "wo": ns(P(None, "tp", "fsdp")),
            "mlp_norm": ns(P(None, None)),
            "w1": ns(P(None, "fsdp", "tp")),
            "w3": ns(P(None, "fsdp", "tp")),
            "w2": ns(P(None, "tp", "fsdp")),
        },
        "final_norm": ns(P(None)),
        "output": ns(P("fsdp", "tp")),
    }


def batch_sharding(mesh: Mesh, seq_axis: str | None = None) -> NamedSharding:
    """Tokens/targets: batch over every data axis the mesh carries
    (dcn slices, dp, fsdp); optionally sequence over sp.  Params never
    shard on dcn, so splitting the batch over it is what makes the
    cross-slice gradient psum the only DCN collective."""
    data_axes = tuple(
        a for a in ("dcn", "dp", "fsdp") if a in mesh.axis_names
    )
    return NamedSharding(mesh, P(data_axes, seq_axis))


def optimizer_state_shardings(opt_abstract, p_shard, mesh: Mesh):
    """Sharding tree for an optax state mirroring a sharded param tree.

    Optimizer moments (mu/nu) replicate the param tree structurally, so
    each state leaf whose tree-path *suffix* matches a param path gets
    that param's sharding (e.g. ``(0, 'mu', 'layers', 'w1')`` matches
    param path ``('layers', 'w1')``); scalars and other state leaves
    replicate.  Path-based matching is collision-proof where
    shape-keyed lookup is not: two same-shaped params with different
    shardings resolve by name, not by first-registered shape.
    """
    from jax.tree_util import keystr, tree_flatten_with_path, tree_map_with_path

    def norm(path):
        return tuple(keystr((k,)) for k in path)

    by_path = {
        norm(path): shard
        for path, shard in tree_flatten_with_path(
            p_shard, is_leaf=lambda v: isinstance(v, NamedSharding)
        )[0]
    }
    replicated = NamedSharding(mesh, P())

    def lookup(path, _leaf):
        keys = norm(path)
        for i in range(len(keys)):
            if keys[i:] in by_path:
                return by_path[keys[i:]]
        return replicated

    return tree_map_with_path(lookup, opt_abstract)
