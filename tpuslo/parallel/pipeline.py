"""Pipeline parallelism (the ``pp`` axis): GPipe microbatch schedule.

Layers are stacked along a leading axis (see
``tpuslo.models.llama.init_params``), so pipeline-stage assignment is
just sharding that axis over the ``pp`` mesh dimension — stage *i*
holds layers ``[i*L/pp, (i+1)*L/pp)``.  The schedule is a single
``lax.scan`` over ``n_microbatches + pp - 1`` ticks; each tick every
stage runs its local layer stack on its current microbatch and hands
the activations to the next stage with ``lax.ppermute`` (one
neighbour ICI hop).  The whole schedule is reverse-differentiable —
``scan``/``ppermute``/``psum`` all carry transpose rules, so
``jax.grad`` through :func:`pipelined_loss` yields the standard GPipe
backward pipeline without hand-written bubbles.

TPU-first notes:

* static trip count and static microbatch shapes — one compile, no
  bubbles beyond the algorithmic ``pp - 1``;
* embedding/final-norm/head are computed replicated (they are tiny
  next to the layer stack and keeping them replicated avoids two
  extra boundary collectives);
* activations cross stages in the model dtype (bf16 on TPU), so each
  hop moves ``mb x S x D x 2`` bytes.

The reference has no parallelism at all (SURVEY.md §2.5); together
with dp/fsdp/tp (``tpuslo.parallel.mesh``), sp
(``tpuslo.ops.ring_attention``) and ep (``tpuslo.ops.moe``) this
completes the strategy set for the observed workload.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuslo.models.llama import LlamaConfig, _layer_body, rms_norm, rope_frequencies, _matmul

try:  # moved out of jax.experimental in newer releases
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

PyTree = Any


def pipeline_param_specs(axis_name: str = "pp") -> PyTree:
    """PartitionSpec tree for ``init_params``: layer axis over ``pp``."""
    layer = P(axis_name, None, None)
    return {
        "embed": P(None, None),
        "layers": {
            "attn_norm": P(axis_name, None),
            "wq": layer,
            "wk": layer,
            "wv": layer,
            "wo": layer,
            "mlp_norm": P(axis_name, None),
            "w1": layer,
            "w3": layer,
            "w2": layer,
        },
        "final_norm": P(None),
        "output": P(None, None),
    }


def place_pipeline_params(params: PyTree, mesh: Mesh, axis_name: str = "pp") -> PyTree:
    specs = pipeline_param_specs(axis_name)
    return jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        params,
        specs,
    )


def _stage_stack(cfg: LlamaConfig, h, local_layers, cos, sin, mask, remat):
    """Run this stage's layer shard on one microbatch."""
    body = partial(_layer_body, cfg, causal=True)
    if remat:
        body = jax.checkpoint(body, static_argnums=())

    def scan_step(carry, layer):
        carry, _kv = body(carry, layer, cos, sin, mask)
        return carry, None

    h, _ = lax.scan(scan_step, h, local_layers)
    return h


def _pipeline_body(
    params: PyTree,
    tokens: jax.Array,
    cfg: LlamaConfig,
    axis_name: str,
    n_microbatches: int,
    remat: bool,
) -> jax.Array:
    """shard_map body → logits (B, S, vocab), replicated."""
    pp = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    M = n_microbatches
    B, S = tokens.shape
    mb = B // M

    h = params["embed"][tokens].astype(cfg.dtype)  # replicated compute
    h_mb = h.reshape(M, mb, S, -1)
    positions = jnp.broadcast_to(jnp.arange(S), (mb, S))
    cos, sin = rope_frequencies(cfg, positions)
    mask = jnp.tril(jnp.ones((S, S), jnp.bool_))

    fwd = partial(
        _stage_stack, cfg, local_layers=params["layers"], cos=cos, sin=sin,
        mask=mask, remat=remat,
    )

    def tick(carry, t):
        buf, outputs = carry
        # Stage 0 injects microbatch t (clamped: late ticks re-inject the
        # last microbatch, whose output is never collected).
        inject = lax.dynamic_index_in_dim(
            h_mb, jnp.minimum(t, M - 1), 0, keepdims=False
        )
        buf = jnp.where(stage == 0, inject, buf)
        processed = fwd(buf)
        # Last stage collects finished microbatch t - (pp - 1).
        out_idx = t - (pp - 1)
        collected = lax.dynamic_update_index_in_dim(
            outputs, processed.astype(jnp.float32), jnp.clip(out_idx, 0, M - 1), 0
        )
        take = jnp.logical_and(stage == pp - 1, out_idx >= 0)
        outputs = jnp.where(take, collected, outputs)
        # Hand activations to the next stage (ring hop; the wraparound
        # pp-1 -> 0 link carries data stage 0 overwrites via inject).
        perm = [(i, (i + 1) % pp) for i in range(pp)]
        buf = lax.ppermute(processed, axis_name, perm)
        return (buf, outputs), None

    # The carry becomes stage-varying after the first tick (axis_index /
    # ppermute); the initial zeros must carry the same varying-over-pp
    # type or scan rejects the carry (shard_map vma rule).
    buf0 = lax.pcast(
        jnp.zeros((mb, S, h.shape[-1]), cfg.dtype), (axis_name,), to="varying"
    )
    out0 = lax.pcast(
        jnp.zeros((M, mb, S, h.shape[-1]), jnp.float32), (axis_name,), to="varying"
    )
    (_, outputs), _ = lax.scan(
        tick, (buf0, out0), jnp.arange(M + pp - 1)
    )

    # Only the last stage holds real outputs; psum replicates them so
    # the (replicated) head below sees identical values everywhere.
    outputs = lax.psum(
        jnp.where(stage == pp - 1, outputs, jnp.zeros_like(outputs)),
        axis_name,
    )
    h = outputs.reshape(B, S, -1).astype(cfg.dtype)
    h = rms_norm(h, params["final_norm"], cfg.norm_eps)
    return _matmul(h, params["output"]).astype(jnp.float32)


def pipelined_forward(
    params: PyTree,
    tokens: jax.Array,
    cfg: LlamaConfig,
    mesh: Mesh,
    n_microbatches: int = 4,
    axis_name: str = "pp",
    remat: bool = False,
) -> jax.Array:
    """Full-sequence forward through the pipeline → logits (B, S, V).

    Requires ``cfg.n_layers % mesh.shape[axis_name] == 0`` and
    ``tokens.shape[0] % n_microbatches == 0``.
    """
    pp = mesh.shape[axis_name]
    if cfg.n_layers % pp:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by pp={pp}")
    if tokens.shape[0] % n_microbatches:
        raise ValueError(
            f"batch={tokens.shape[0]} not divisible by "
            f"n_microbatches={n_microbatches}"
        )
    fn = shard_map(
        partial(
            _pipeline_body,
            cfg=cfg,
            axis_name=axis_name,
            n_microbatches=n_microbatches,
            remat=remat,
        ),
        mesh=mesh,
        in_specs=(pipeline_param_specs(axis_name), P(None, None)),
        out_specs=P(None, None, None),
    )
    return fn(params, tokens)


def pipelined_loss(
    params: PyTree,
    tokens: jax.Array,
    targets: jax.Array,
    cfg: LlamaConfig,
    mesh: Mesh,
    n_microbatches: int = 4,
    axis_name: str = "pp",
    remat: bool = True,
) -> jax.Array:
    """Mean next-token cross-entropy through the pipeline (grad-able)."""
    logits = pipelined_forward(
        params, tokens, cfg, mesh, n_microbatches, axis_name, remat
    )
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)
