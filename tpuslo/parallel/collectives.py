"""Active ICI collective prober: measured XLA collectives over a mesh.

The toolkit's passive sources (libtpu uprobes, xprof device lanes)
observe the *workload's* collectives; this is the active counterpart —
a blackbox prober that launches small psum / all_gather /
reduce_scatter / ppermute rounds over the device mesh and reports their
wall latency as real ``ici_collective_latency_ms`` probe events.  Role
parity: the reference's agent actively creates a BPF map as its
privilege probe (``pkg/collector/kernel.go:18-39``); here the active
check exercises the interconnect itself, so a degrading ICI link shows
up even when the serving workload is idle.

TPU-first mechanics: each op is one ``shard_map``-wrapped collective
jitted over a 1-D mesh axis, compiled once per (op, shape) and timed
over committed sharded inputs — what's measured is the collective
dispatch + ICI transfer, not host padding or transfer-in.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from datetime import datetime, timezone

import numpy as np

from tpuslo.schema import ProbeEventV1, TPURef
from tpuslo.signals.constants import SIGNAL_ICI_COLLECTIVE_MS
from tpuslo.signals.generator import signal_status

DEFAULT_OPS = ("psum", "all_gather", "reduce_scatter", "ppermute")


@dataclass(frozen=True)
class CollectiveProbe:
    """One measured collective: latency quantiles over ``reps`` rounds."""

    op: str
    n_devices: int
    payload_bytes_per_device: int
    reps: int
    mean_ms: float
    p50_ms: float
    p95_ms: float
    min_ms: float

    def to_dict(self) -> dict:
        return {
            "op": self.op,
            "n_devices": self.n_devices,
            "payload_bytes_per_device": self.payload_bytes_per_device,
            "reps": self.reps,
            "mean_ms": round(self.mean_ms, 4),
            "p50_ms": round(self.p50_ms, 4),
            "p95_ms": round(self.p95_ms, 4),
            "min_ms": round(self.min_ms, 4),
        }


def _collective_fn(op: str, mesh, axis: str):
    """shard_map-wrapped collective over the 1-D probe axis."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    n = mesh.shape[axis]
    if op == "psum":
        body = lambda x: lax.psum(x, axis)  # noqa: E731
        out_spec = P(axis, None)
    elif op == "all_gather":
        body = lambda x: lax.all_gather(x, axis, tiled=True)  # noqa: E731
        out_spec = P(axis, None)
    elif op == "reduce_scatter":
        body = lambda x: lax.psum_scatter(x, axis, tiled=True)  # noqa: E731
        out_spec = P(axis, None)
    elif op == "ppermute":
        perm = [(i, (i + 1) % n) for i in range(n)]
        body = lambda x: lax.ppermute(x, axis, perm)  # noqa: E731
        out_spec = P(axis, None)
    else:
        raise ValueError(f"unknown collective op {op!r}")
    # built once per CollectiveSuite (the constructor compiles;
    # measure() only replays), not per probe.
    # tpulint: disable=TPL161
    return jax.jit(
        shard_map(body, mesh=mesh, in_specs=P(axis, None), out_specs=out_spec)
    )


class CollectiveSuite:
    """Compiled collective fns + committed sharded input, built once.

    Compilation and the host→device put happen in the constructor;
    :meth:`measure` only replays the compiled programs, so periodic
    probing (the agent's ``ActiveICIProber``) pays jit/transfer cost a
    single time, not per interval.
    """

    def __init__(
        self,
        mesh=None,
        payload_bytes: int = 1 << 20,
        ops: tuple[str, ...] = DEFAULT_OPS,
    ):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        if mesh is None:
            mesh = Mesh(np.array(jax.devices()), ("probe",))
        axis = mesh.axis_names[0]
        self.n_devices = mesh.shape[axis]
        n = self.n_devices

        cols = 256
        # Per-device rows rounded to a multiple of n: tiled psum_scatter
        # splits the shard's leading dim across the axis again.
        rows_per_dev = max(n, (payload_bytes // (4 * cols) // n) * n)
        self.payload_bytes_per_device = rows_per_dev * cols * 4
        x_host = np.ones((n * rows_per_dev, cols), np.float32)
        self._x = jax.device_put(x_host, NamedSharding(mesh, P(axis, None)))
        self._fns = {op: _collective_fn(op, mesh, axis) for op in ops}
        for fn in self._fns.values():
            jax.block_until_ready(fn(self._x))  # compile round

    def measure(self, reps: int = 20) -> list[CollectiveProbe]:
        import jax

        out: list[CollectiveProbe] = []
        for op, fn in self._fns.items():
            samples_ms: list[float] = []
            for _ in range(reps):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(self._x))
                samples_ms.append((time.perf_counter() - t0) * 1000.0)
            arr = np.asarray(samples_ms)
            out.append(
                CollectiveProbe(
                    op=op,
                    n_devices=self.n_devices,
                    payload_bytes_per_device=self.payload_bytes_per_device,
                    reps=reps,
                    mean_ms=float(arr.mean()),
                    p50_ms=float(np.percentile(arr, 50)),
                    p95_ms=float(np.percentile(arr, 95)),
                    min_ms=float(arr.min()),
                )
            )
        return out


def bench_collectives(
    mesh=None,
    payload_bytes: int = 1 << 20,
    reps: int = 20,
    ops: tuple[str, ...] = DEFAULT_OPS,
) -> list[CollectiveProbe]:
    """One-shot convenience: build a :class:`CollectiveSuite`, measure.

    ``payload_bytes`` is the per-device shard size.  The compile round
    is excluded; quantiles come from the ``reps`` timed rounds, each
    synced with ``block_until_ready``.
    """
    return CollectiveSuite(mesh=mesh, payload_bytes=payload_bytes, ops=ops).measure(
        reps
    )


class ActiveICIProber:
    """Periodic in-agent collective prober.

    The agent calls :meth:`maybe_probe` once per emit cycle; the probe
    actually runs only when ``interval_s`` has elapsed, and a failing
    backend (chip held exclusively by the serving workload, tunnel
    down) disables the prober after one loud log line instead of
    failing every cycle.  Default payload/reps are sized so a probe
    round stays well under the agent's 3% overhead budget.
    """

    def __init__(
        self,
        interval_s: float,
        node: str = "tpu-vm-0",
        namespace: str = "llm",
        slice_id: str = "",
        host_index: int = -1,
        payload_kb: int = 256,
        reps: int = 5,
        log=None,
        timeout_s: float = 120.0,
    ):
        self.interval_s = interval_s
        self.node = node
        self.namespace = namespace
        self.slice_id = slice_id
        self.host_index = host_index
        self.payload_kb = payload_kb
        self.reps = reps
        self.timeout_s = timeout_s
        self._next_due = 0.0  # first cycle probes immediately
        self._disabled = False
        self._suite: CollectiveSuite | None = None
        self._log = log or (lambda msg: None)

    def _probe_once(self) -> tuple["CollectiveSuite", list[CollectiveProbe]]:
        """Build-or-reuse the suite and measure; returns both WITHOUT
        publishing to ``self._suite`` — the caller publishes only after
        a successful timed join, so a worker that outlives its timeout
        cannot re-attach a handle the timeout path already dropped."""
        suite = self._suite
        if suite is None:
            # One-time compile + device_put; later intervals only
            # replay the compiled programs (OverheadGuard would
            # otherwise see a recompile burst every interval and
            # shed unrelated passive probes).
            suite = CollectiveSuite(payload_bytes=self.payload_kb * 1024)
        return suite, suite.measure(self.reps)

    def maybe_probe(self, now_monotonic: float) -> list[ProbeEventV1]:
        if self._disabled or now_monotonic < self._next_due:
            return []
        self._next_due = now_monotonic + self.interval_s
        # The documented failure mode of an unreachable device backend
        # is a HANG in backend init (the axon plugin retries forever —
        # no exception for try/except to catch), so the build+measure
        # runs in a worker thread with a join timeout: a wedged tunnel
        # disables the prober instead of stalling the whole agent emit
        # loop (passive probes, heartbeat, metrics).  The leaked daemon
        # thread parks forever inside the backend; the suite handle is
        # dropped so no later cycle touches it.
        import threading

        box: dict[str, object] = {}

        def worker():
            try:
                result = self._probe_once()
                if result is not None:
                    box["suite"], box["probes"] = result
            except Exception as exc:  # noqa: BLE001 - device unavailable
                box["error"] = exc

        thread = threading.Thread(target=worker, daemon=True)
        thread.start()
        thread.join(timeout=self.timeout_s)
        if thread.is_alive():
            self._disabled = True
            self._suite = None
            self._log(
                f"ici prober disabled: probe exceeded {self.timeout_s}s "
                "(backend hang — tunnel down?)"
            )
            return []
        if "error" in box or "probes" not in box:
            self._disabled = True
            self._log(f"ici prober disabled: {box.get('error', 'no result')}")
            return []
        self._suite = box["suite"]  # type: ignore[assignment]
        return probes_to_events(
            box["probes"],  # type: ignore[arg-type]
            node=self.node,
            namespace=self.namespace,
            slice_id=self.slice_id,
            host_index=self.host_index,
        )


def probes_to_events(
    probes: list[CollectiveProbe],
    node: str = "tpu-vm-0",
    namespace: str = "llm",
    pod: str = "icibench",
    container: str = "icibench",
    slice_id: str = "",
    host_index: int = -1,
    chip: str = "accel0",
    now: datetime | None = None,
) -> list[ProbeEventV1]:
    """One ``ici_collective_latency_ms`` probe event per measured op.

    The op rides ``tpu.module_name`` (it names the probe's compiled HLO
    module) so the correlation/attribution layers can split by
    collective kind without schema changes.
    """
    import os

    now = now or datetime.now(timezone.utc)
    ts = int(now.timestamp() * 1e9)
    events = []
    for probe in probes:
        value = probe.p95_ms
        events.append(
            ProbeEventV1(
                ts_unix_nano=ts,
                signal=SIGNAL_ICI_COLLECTIVE_MS,
                node=node,
                namespace=namespace,
                pod=pod,
                container=container,
                pid=os.getpid(),
                tid=os.getpid(),
                value=value,
                unit="ms",
                status=signal_status(SIGNAL_ICI_COLLECTIVE_MS, value),
                tpu=TPURef(
                    chip=chip,
                    slice_id=slice_id,
                    host_index=host_index,
                    program_id="icibench",
                    module_name=f"collective:{probe.op}",
                ),
            )
        )
    return events
