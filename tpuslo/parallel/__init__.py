from tpuslo.parallel.mesh import (
    MeshPlan,
    batch_sharding,
    make_mesh,
    param_shardings,
    plan_for_devices,
)

__all__ = [
    "MeshPlan",
    "batch_sharding",
    "make_mesh",
    "param_shardings",
    "plan_for_devices",
]
