"""Multi-process distributed collective prober (the DCN analog).

The virtual single-process mesh (``tpuslo/parallel/collectives.py``)
exercises XLA's collective *lowering*; this module exercises the
actual multi-host shape: N OS processes join one
``jax.distributed`` runtime (coordinator + gloo CPU collectives — the
same topology a v5e pod's hosts form over ICI/DCN, minus the silicon),
run measured cross-process ``psum`` launches over the global mesh, and
emit per-host ``ici_collective_latency_ms`` probe events carrying
(slice, host, program, launch) identity.

The straggler physics is REAL here, not simulated: a cross-process
collective blocks every participant until the last one arrives, so
when one host is delayed the punctual hosts' measured latency inflates
by the delay while the straggler itself sails through — exactly the
signature :class:`tpuslo.correlation.multihost.SliceJoiner` attributes
(the fastest host is the one everybody waited for).

``tpuslo icibench --multiprocess N`` fronts this; tests drive it with
2–3 processes on CI.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
from typing import Any

PROGRAM_ID = "dist_psum"


def _free_port() -> int:
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


def worker_main(argv: list[str] | None = None) -> int:
    """One distributed host: join the runtime, measure collectives.

    Prints one ProbeEventV1 JSON per launch on stdout.  Must run in its
    own process (jax.distributed.initialize is once-per-process).
    """
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--process-id", type=int, required=True)
    p.add_argument("--num-processes", type=int, required=True)
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--launches", type=int, default=5)
    p.add_argument("--payload-kb", type=int, default=256)
    p.add_argument("--delay-ms", type=float, default=0.0)
    p.add_argument("--delayed-host", type=int, default=-1)
    p.add_argument("--slice-id", default="dist-slice")
    p.add_argument(
        "--n-slices", type=int, default=1,
        help="partition the hosts into this many slices: each launch "
        "then measures an intra-slice round AND a global round, and "
        "the difference is emitted as dcn_transfer_latency_ms — the "
        "cross-slice component, measured, not simulated",
    )
    p.add_argument(
        "--ring-path", default="",
        help="also write each measured event into this userspace ring "
        "(the host's agent consumes it — the DaemonSet fan-out shape)",
    )
    p.add_argument(
        "--hold-before-init-s", type=float, default=0.0,
        help="pause between ring creation and jax.distributed init so "
        "an orchestrator can attach per-host consumers first",
    )
    args = p.parse_args(argv)

    ring = None
    if args.ring_path:
        # Create the ring BEFORE the (slow) jax.distributed init and
        # announce it: the consumer (this host's agent) attaches at the
        # writer's HEAD, so it must be attached before the first
        # measured launch — which cannot happen until every worker has
        # joined the runtime and compiled, seconds from now.
        from tpuslo.collector.ringbuf import RingWriter

        ring = RingWriter(args.ring_path)
        print(f"RING_READY:{args.ring_path}", flush=True)
    if args.hold_before_init_s > 0:
        time.sleep(args.hold_before_init_s)

    import jax

    # Force the CPU platform BEFORE any backend touch (the pinned axon
    # tunnel would hang), then the cross-process gloo collectives.
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 - newer jax versions default this
        pass
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{args.port}",
        num_processes=args.num_processes,
        process_id=args.process_id,
    )

    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from tpuslo.schema import ProbeEventV1, TPURef

    n = jax.device_count()
    n_slices = max(1, args.n_slices)
    if args.num_processes % n_slices:
        raise SystemExit(
            f"--n-slices {n_slices} must divide --num-processes "
            f"{args.num_processes}: slices are process groups"
        )
    per_proc = n // args.num_processes
    if n % n_slices or (n // n_slices) % max(per_proc, 1):
        raise SystemExit(
            f"--n-slices {n_slices} does not align to process "
            f"boundaries ({n} devices, {per_proc} per process): a "
            "host's devices must not straddle two slices"
        )
    cols = 256
    rows = max(n, (args.payload_kb * 1024 // (4 * cols) // n) * n)
    x_local = np.ones((rows // n * jax.local_device_count(), cols), np.float32)
    from jax.experimental import multihost_utils
    from jax.experimental.shard_map import shard_map

    if n_slices > 1:
        # Two-level mesh: contiguous process-id runs form each slice
        # (the same layout MeshPlan's dcn axis uses).  The intra round
        # psums over the slice-local axis only; the global round
        # crosses slices — its excess over intra IS the cross-slice
        # transfer component.
        mesh = Mesh(
            np.array(jax.devices()).reshape(n_slices, n // n_slices),
            ("slice", "host"),
        )
        spec = P(("slice", "host"), None)
        x = multihost_utils.host_local_array_to_global_array(
            x_local, mesh, spec
        )

        @jax.jit
        # dryrun harness: compiled once per process run, explicitly
        # warmed before the timed launches.
        # tpulint: disable=TPL161
        def intra_reduce(v):
            return shard_map(
                lambda s: jax.lax.psum(s, "host"),
                mesh=mesh, in_specs=spec, out_specs=P("slice", None),
            )(v)

        @jax.jit
        # dryrun harness: compiled once per process run, explicitly
        # warmed before the timed launches.
        # tpulint: disable=TPL161
        def allreduce(v):
            return shard_map(
                lambda s: jax.lax.psum(s, ("slice", "host")),
                mesh=mesh, in_specs=spec, out_specs=P(None, None),
            )(v)

        jax.block_until_ready(intra_reduce(x))  # compile round
    else:
        mesh = Mesh(np.array(jax.devices()), ("hosts",))
        spec = P("hosts", None)
        x = multihost_utils.host_local_array_to_global_array(
            x_local, mesh, spec
        )
        intra_reduce = None

        @jax.jit
        # dryrun harness: compiled once per process run, explicitly
        # warmed before the timed launches.
        # tpulint: disable=TPL161
        def allreduce(v):
            return shard_map(
                lambda s: jax.lax.psum(s, "hosts"),
                mesh=mesh, in_specs=spec, out_specs=P(None, None),
            )(v)

    jax.block_until_ready(allreduce(x))  # compile round

    me = args.process_id
    my_slice = me * n_slices // args.num_processes
    slice_id = (
        f"{args.slice_id}-{my_slice}" if n_slices > 1 else args.slice_id
    )
    for launch in range(args.launches):
        if me == args.delayed_host and args.delay_ms > 0:
            time.sleep(args.delay_ms / 1000.0)
        intra_ms = 0.0
        if intra_reduce is not None:
            # Intra round first: slice-local psum (a delayed host only
            # stalls its own slice's peers here).
            t0 = time.perf_counter()
            jax.block_until_ready(intra_reduce(x))
            intra_ms = (time.perf_counter() - t0) * 1000.0
        t0 = time.perf_counter()
        jax.block_until_ready(allreduce(x))
        wait_ms = (time.perf_counter() - t0) * 1000.0
        def emit(signal_name: str, value_ms: float, native_sig: int) -> None:
            """One measured reading: ProbeEventV1 on stdout + ring.

            Ring wire format: ns value for _ms signals (native decode
            divides back), launch identity in aux, F_TPU so the
            consumer lifts it into a TPURef.
            """
            event = ProbeEventV1(
                ts_unix_nano=time.time_ns(),
                signal=signal_name,
                node=f"dist-host-{me}",
                namespace="llm",
                pod=f"agent-{me}",
                container="agent",
                pid=os.getpid(),
                tid=me,
                value=value_ms,
                unit="ms",
                status="ok",
                tpu=TPURef(
                    chip="accel0",
                    slice_id=slice_id,
                    host_index=me,
                    ici_link=-1,
                    program_id=PROGRAM_ID,
                    launch_id=launch,
                ),
            )
            print(json.dumps(event.to_dict()), flush=True)
            if ring is not None:
                from tpuslo.collector import native

                ring.write_event(
                    signal=native_sig,
                    value=int(value_ms * 1e6),
                    ts_ns=event.ts_unix_nano,
                    aux=launch,
                    pid=os.getpid(),
                    tid=me,
                    flags=native.F_TPU,
                )

        from tpuslo.collector import native as _native

        if intra_reduce is not None:
            # The global round's excess over the slice-local round is
            # the measured cross-slice (DCN-path) component; the intra
            # round is the slice-local collective reading.
            emit(
                "dcn_transfer_latency_ms",
                max(0.0, wait_ms - intra_ms),
                _native.SIG_DCN_TRANSFER,
            )
            wait_ms = intra_ms
        emit(
            "ici_collective_latency_ms", wait_ms, _native.SIG_ICI_COLLECTIVE
        )
    if ring is not None:
        ring.close()
    return 0


def run_distributed_probe(
    n_processes: int = 2,
    launches: int = 5,
    payload_kb: int = 256,
    delay_ms: float = 0.0,
    delayed_host: int = -1,
    timeout_s: float = 420.0,
    n_slices: int = 1,
) -> dict[str, Any]:
    """Spawn the workers, collect per-host events, join stragglers.

    Returns a report with every measured event, the SliceJoiner
    incidents, and (when a host was delayed) whether the join named it.
    """
    port = _free_port()
    procs = []
    for pid in range(n_processes):
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-m", "tpuslo.parallel.distributed",
                    "--process-id", str(pid),
                    "--num-processes", str(n_processes),
                    "--port", str(port),
                    "--launches", str(launches),
                    "--payload-kb", str(payload_kb),
                    "--delay-ms", str(delay_ms),
                    "--delayed-host", str(delayed_host),
                    "--n-slices", str(n_slices),
                ],
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    events: list[dict] = []
    errors: list[str] = []
    # One SHARED deadline across every worker.  If a worker crashes, the
    # survivors block forever inside the cross-process psum; sequential
    # per-proc communicate(timeout) calls would stack to N*timeout_s of
    # wall clock before reporting.  A drain thread per worker keeps the
    # PIPEs flowing (a chatty worker would wedge on a full 64 KB pipe if
    # the parent only polled); the main loop watches exit codes and the
    # moment any worker exits nonzero kills the rest — they can never
    # complete once a collective participant is gone.
    import threading

    outputs: list[tuple[str, str]] = [("", "")] * len(procs)

    def _drain(i: int) -> None:
        outputs[i] = procs[i].communicate()

    drains = [
        threading.Thread(target=_drain, args=(i,), daemon=True)
        for i in range(len(procs))
    ]
    for t in drains:
        t.start()
    deadline = time.monotonic() + timeout_s
    pending = set(range(len(procs)))
    peer_failed = False
    while pending and not peer_failed and time.monotonic() < deadline:
        for i in list(pending):
            if procs[i].poll() is None:
                continue
            pending.discard(i)
            if procs[i].returncode != 0:
                peer_failed = True
        if pending and not peer_failed:
            time.sleep(0.05)
    for i in list(pending):
        procs[i].kill()
        errors.append(
            "worker killed (peer exited nonzero)" if peer_failed
            else "worker timeout"
        )
    for t in drains:
        t.join(timeout=30.0)
    for proc, (out, err) in zip(procs, outputs):
        if proc.returncode is not None and proc.returncode != 0:
            errors.append((err or "")[-300:])
        for line in (out or "").splitlines():
            if line.strip().startswith("{"):
                events.append(json.loads(line))

    from tpuslo.correlation.multihost import SliceJoiner

    joiner = SliceJoiner(expected_hosts=n_processes)
    joiner.add_all(events)
    # With slicing, the intra-slice ICI groups can only ever hold
    # n_processes/n_slices hosts — size the completeness guard to the
    # smallest legitimate group so they are not silently suppressed.
    min_hosts = max(2, n_processes // n_slices)
    incidents = [i.to_dict() for i in joiner.incidents(min_hosts=min_hosts)]
    report: dict[str, Any] = {
        "mechanism": "jax_distributed_gloo",
        "real": True,
        "n_processes": n_processes,
        "n_slices": n_slices,
        "launches": launches,
        "events_measured": len(events),
        "events": events,
        "errors": errors,
        "incidents": incidents,
    }
    if n_slices > 1:
        dcn = [
            e["value"] for e in events
            if e.get("signal") == "dcn_transfer_latency_ms"
        ]
        if dcn:
            report["dcn_transfer_ms_max"] = round(max(dcn), 2)
            report["dcn_events"] = len(dcn)
    if delayed_host >= 0:
        correct = [
            i for i in incidents if i["straggler_host"] == delayed_host
        ]
        report["delayed_host"] = delayed_host
        report["correct_attributions"] = len(correct)
        report["top_confidence"] = max(
            (i["confidence"] for i in correct), default=0.0
        )
    return report


if __name__ == "__main__":
    sys.exit(worker_main())
