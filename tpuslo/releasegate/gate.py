"""M5 GA statistical release gates: baseline provenance, B5 overhead,
D3 rerun variance, E3 significance.

Reference: ``pkg/releasegate/gate.go:140-946``.  Artifact layout::

    <candidate_root>/<scenario>/<run-*>/raw_samples.jsonl
    <candidate_root>/<scenario>/<run-*>/collector_overhead.csv
    <baseline_root>/manifest.json  (+ same per-scenario layout)

Gate semantics:
  baseline — manifest provenance; candidate==baseline source downgrades
             E3 comparisons to informational (same-source skip).
  B5       — per-node p95 CPU overhead ≤ threshold AND mean ≤ threshold.
  D3       — CV% of TTFT-p95 / tokens-p50 / error-mean across ≥3 runs
             ≤ threshold.
  E3       — TTFT-p95 regression fails only if pct > limit AND
             Mann-Whitney p < α AND bootstrap CI95 low > 0 AND
             |Cliff's δ| ≥ practical threshold, with n ≥ 30/scenario.
"""

from __future__ import annotations

import csv
import json
import os
from dataclasses import dataclass, field
from datetime import datetime, timezone
from pathlib import Path
from typing import Any

from tpuslo.collector.synthetic import RawSample
from tpuslo.releasegate.stats import (
    bootstrap_delta_ci,
    cliffs_delta,
    coefficient_of_variance_pct,
    mann_whitney_p_value,
    mean,
    stddev,
)
from tpuslo.slo.calculator import quantile

DEFAULT_SCENARIOS = [
    "dns_latency",
    "cpu_throttle",
    "provider_throttle",
    "memory_pressure",
    "network_partition",
    "ici_drop",
    "hbm_pressure",
    "xla_recompile_storm",
    "host_offload_stall",
    "mixed",
    "mixed_multi",
    "tpu_mixed",
]


@dataclass
class Config:
    candidate_root: str = "artifacts/weekly-benchmark"
    baseline_root: str = ""
    baseline_manifest_path: str = ""
    candidate_ref: str = ""
    candidate_commit: str = ""
    require_baseline_manifest: bool = False
    scenarios: list[str] = field(default_factory=list)
    max_overhead_pct: float = 3.0
    max_variance_pct: float = 10.0
    min_runs_per_scenario: int = 3
    regression_pct_limit: float = 5.0
    significance_alpha: float = 0.05
    bootstrap_iterations: int = 1000
    bootstrap_seed: int = 42
    min_samples_per_scenario: int = 30
    min_cliffs_delta_for_failure: float = 0.147

    def normalized(self) -> "Config":
        cfg = Config(**self.__dict__)
        if not cfg.baseline_root:
            cfg.baseline_root = os.path.join(cfg.candidate_root, "baseline")
        if not cfg.baseline_manifest_path:
            cfg.baseline_manifest_path = os.path.join(cfg.baseline_root, "manifest.json")
        if not cfg.scenarios:
            cfg.scenarios = list(DEFAULT_SCENARIOS)
        if cfg.max_overhead_pct <= 0:
            cfg.max_overhead_pct = 3.0
        if cfg.max_variance_pct <= 0:
            cfg.max_variance_pct = 10.0
        if cfg.min_runs_per_scenario <= 0:
            cfg.min_runs_per_scenario = 3
        if cfg.regression_pct_limit <= 0:
            cfg.regression_pct_limit = 5.0
        if not 0 < cfg.significance_alpha < 1:
            cfg.significance_alpha = 0.05
        if cfg.bootstrap_iterations <= 0:
            cfg.bootstrap_iterations = 1000
        if cfg.bootstrap_seed == 0:
            cfg.bootstrap_seed = 42
        if cfg.min_samples_per_scenario <= 0:
            cfg.min_samples_per_scenario = 30
        if cfg.min_cliffs_delta_for_failure <= 0:
            cfg.min_cliffs_delta_for_failure = 0.147
        return cfg


@dataclass
class BaselineGate:
    passed: bool = True
    manifest_required: bool = False
    manifest_path: str = ""
    source_ref: str = ""
    source_commit: str = ""
    candidate_ref: str = ""
    candidate_commit: str = ""
    same_source: bool = False
    failure_reason: str = ""


@dataclass
class OverheadGate:
    passed: bool = True
    threshold_pct: float = 3.0
    max_observed_pct: float = 0.0
    mean_observed_pct: float = 0.0
    sample_count: int = 0
    files_checked: int = 0
    node_p95_observed: dict[str, float] = field(default_factory=dict)
    max_node_p95_pct: float = 0.0
    max_node_p95_node: str = ""
    failure_reason: str = ""


@dataclass
class ScenarioVariance:
    scenario: str
    run_count: int = 0
    ttft_p95_values: list[float] = field(default_factory=list)
    mean_ttft_p95: float = 0.0
    stddev_ttft_p95: float = 0.0
    variance_pct: float = 0.0
    tokens_p50_values: list[float] = field(default_factory=list)
    tokens_variance_pct: float = 0.0
    error_rate_mean_values: list[float] = field(default_factory=list)
    error_rate_variance_pct: float = 0.0
    passed: bool = True
    failure_reason: str = ""


@dataclass
class VarianceGate:
    passed: bool = True
    threshold_pct: float = 10.0
    min_runs: int = 3
    scenarios: list[ScenarioVariance] = field(default_factory=list)


@dataclass
class ScenarioSignificance:
    scenario: str
    candidate_n: int = 0
    baseline_n: int = 0
    candidate_ttft_p95: float = 0.0
    baseline_ttft_p95: float = 0.0
    ttft_regression_pct: float = 0.0
    mann_whitney_p_value: float = 1.0
    bootstrap_delta_ci95: tuple[float, float] = (0.0, 0.0)
    cliffs_delta: float = 0.0
    practical_effect_pass: bool = False
    minimum_samples_reached: bool = False
    informational_only: bool = False
    passed: bool = True
    failure_reason: str = ""


@dataclass
class SignificanceGate:
    passed: bool = True
    regression_pct_limit: float = 5.0
    alpha: float = 0.05
    bootstrap_iterations: int = 1000
    min_samples_per_scenario: int = 30
    min_cliffs_delta_for_failure: float = 0.147
    scenarios: list[ScenarioSignificance] = field(default_factory=list)


@dataclass
class Summary:
    generated_at: str = ""
    candidate_root: str = ""
    baseline_root: str = ""
    scenarios: list[str] = field(default_factory=list)
    baseline: BaselineGate = field(default_factory=BaselineGate)
    overhead: OverheadGate = field(default_factory=OverheadGate)
    variance: VarianceGate = field(default_factory=VarianceGate)
    significance: SignificanceGate = field(default_factory=SignificanceGate)
    passed: bool = False
    failures: list[str] = field(default_factory=list)

    def to_dict(self) -> dict[str, Any]:
        def plain(obj):
            if hasattr(obj, "__dataclass_fields__"):
                return {k: plain(v) for k, v in obj.__dict__.items()}
            if isinstance(obj, (list, tuple)):
                return [plain(v) for v in obj]
            if isinstance(obj, dict):
                return {k: plain(v) for k, v in obj.items()}
            return obj

        return plain(self)


def discover_runs(scenario_root: str | Path) -> list[str]:
    root = Path(scenario_root)
    if not root.is_dir():
        return []
    return sorted(str(p) for p in root.iterdir() if p.is_dir())


def load_raw_samples(path: str | Path) -> list[RawSample]:
    samples = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                samples.append(RawSample.from_dict(json.loads(line)))
    return samples


def load_overhead_csv(path: str | Path) -> list[tuple[str, float]]:
    """Rows of (node, cpu_pct) from a collector_overhead.csv file."""
    out = []
    with open(path, newline="", encoding="utf-8") as f:
        for row in csv.DictReader(f):
            node = row.get("node", "")
            cpu = row.get("cpu_pct", row.get("cpu", ""))
            if node and cpu:
                out.append((node, float(cpu)))
    return out


def evaluate(cfg: Config) -> Summary:
    cfg = cfg.normalized()
    summary = Summary(
        generated_at=datetime.now(timezone.utc).isoformat(),
        candidate_root=cfg.candidate_root,
        baseline_root=cfg.baseline_root,
        scenarios=list(cfg.scenarios),
    )
    summary.baseline = _evaluate_baseline(cfg)
    summary.overhead = _evaluate_overhead(cfg)
    summary.variance = _evaluate_variance(cfg)
    summary.significance = _evaluate_significance(cfg, summary.baseline.same_source)
    summary.passed = (
        summary.baseline.passed
        and summary.overhead.passed
        and summary.variance.passed
        and summary.significance.passed
    )
    if not summary.baseline.passed:
        summary.failures.append(
            "baseline gate failed: "
            + (summary.baseline.failure_reason or "provenance validation failed")
        )
    if not summary.overhead.passed:
        summary.failures.append(
            "B5 overhead gate failed: " + summary.overhead.failure_reason
        )
    if not summary.variance.passed:
        summary.failures.append("D3 rerun variance gate failed")
    if not summary.significance.passed:
        summary.failures.append("E3 significance gate failed")
    return summary


def _evaluate_baseline(cfg: Config) -> BaselineGate:
    gate = BaselineGate(
        manifest_required=cfg.require_baseline_manifest,
        manifest_path=cfg.baseline_manifest_path,
        candidate_ref=cfg.candidate_ref,
        candidate_commit=cfg.candidate_commit,
    )
    manifest_path = Path(cfg.baseline_manifest_path)
    if not manifest_path.exists():
        if cfg.require_baseline_manifest:
            gate.passed = False
            gate.failure_reason = f"baseline manifest missing at {manifest_path}"
        return gate
    try:
        manifest = json.loads(manifest_path.read_text())
    except json.JSONDecodeError as exc:
        gate.passed = False
        gate.failure_reason = f"baseline manifest unreadable: {exc}"
        return gate
    gate.source_ref = manifest.get("source_ref", "")
    gate.source_commit = manifest.get("source_commit", "")
    gate.same_source = bool(
        gate.source_commit
        and cfg.candidate_commit
        and gate.source_commit == cfg.candidate_commit
    )
    return gate


def _evaluate_overhead(cfg: Config) -> OverheadGate:
    gate = OverheadGate(threshold_pct=cfg.max_overhead_pct)
    values: list[float] = []
    by_node: dict[str, list[float]] = {}
    for scenario in cfg.scenarios:
        runs = discover_runs(Path(cfg.candidate_root) / scenario)
        if not runs:
            gate.passed = False
            gate.failure_reason = f"no run directories found for scenario {scenario}"
            return gate
        for run_dir in runs:
            path = Path(run_dir) / "collector_overhead.csv"
            if not path.exists():
                gate.passed = False
                gate.failure_reason = f"missing {path}"
                return gate
            gate.files_checked += 1
            for node, cpu in load_overhead_csv(path):
                values.append(cpu)
                by_node.setdefault(node, []).append(cpu)
    if not values:
        gate.passed = False
        gate.failure_reason = f"no overhead values found in {cfg.candidate_root}"
        return gate
    gate.sample_count = len(values)
    gate.max_observed_pct = max(values)
    gate.mean_observed_pct = mean(values)
    for node, node_values in by_node.items():
        p95 = quantile(node_values, 0.95)
        gate.node_p95_observed[node] = p95
        if p95 > gate.max_node_p95_pct or not gate.max_node_p95_node:
            gate.max_node_p95_pct = p95
            gate.max_node_p95_node = node
    gate.passed = (
        gate.max_node_p95_pct <= gate.threshold_pct
        and gate.mean_observed_pct <= gate.threshold_pct
    )
    if not gate.passed:
        if gate.max_node_p95_pct > gate.threshold_pct:
            gate.failure_reason = (
                f"node {gate.max_node_p95_node} p95 overhead "
                f"{gate.max_node_p95_pct:.4f} exceeds {gate.threshold_pct:.4f}"
            )
        else:
            gate.failure_reason = (
                f"mean overhead {gate.mean_observed_pct:.4f} exceeds "
                f"{gate.threshold_pct:.4f}"
            )
    return gate


def _scenario_metrics(run_dirs: list[str]) -> tuple[list[float], list[float], list[float], list[list[float]]]:
    ttft_p95, tokens_p50, err_mean = [], [], []
    pooled_ttft: list[list[float]] = []
    for run_dir in run_dirs:
        samples = load_raw_samples(Path(run_dir) / "raw_samples.jsonl")
        ttft = [s.ttft_ms for s in samples]
        tokens = [s.token_throughput_tps for s in samples]
        errors = [s.error_rate for s in samples]
        if not ttft or not tokens or not errors:
            raise ValueError(f"empty metric series in {run_dir}")
        ttft_p95.append(quantile(ttft, 0.95))
        tokens_p50.append(quantile(tokens, 0.50))
        err_mean.append(mean(errors))
        pooled_ttft.append(ttft)
    return ttft_p95, tokens_p50, err_mean, pooled_ttft


def _evaluate_variance(cfg: Config) -> VarianceGate:
    gate = VarianceGate(
        threshold_pct=cfg.max_variance_pct, min_runs=cfg.min_runs_per_scenario
    )
    for scenario in cfg.scenarios:
        runs = discover_runs(Path(cfg.candidate_root) / scenario)
        row = ScenarioVariance(scenario=scenario, run_count=len(runs))
        if len(runs) < cfg.min_runs_per_scenario:
            row.passed = False
            row.failure_reason = f"requires at least {cfg.min_runs_per_scenario} runs"
            gate.passed = False
            gate.scenarios.append(row)
            continue
        try:
            ttft_p95, tokens_p50, err_mean, _ = _scenario_metrics(runs)
        except (OSError, ValueError, json.JSONDecodeError) as exc:
            row.passed = False
            row.failure_reason = f"unreadable run artifacts: {exc}"
            gate.passed = False
            gate.scenarios.append(row)
            continue
        row.ttft_p95_values = ttft_p95
        row.mean_ttft_p95 = mean(ttft_p95)
        row.stddev_ttft_p95 = stddev(ttft_p95)
        row.variance_pct = coefficient_of_variance_pct(ttft_p95)
        row.tokens_p50_values = tokens_p50
        row.tokens_variance_pct = coefficient_of_variance_pct(tokens_p50)
        row.error_rate_mean_values = err_mean
        row.error_rate_variance_pct = coefficient_of_variance_pct(err_mean)
        row.passed = (
            row.variance_pct <= cfg.max_variance_pct
            and row.tokens_variance_pct <= cfg.max_variance_pct
            and row.error_rate_variance_pct <= cfg.max_variance_pct
        )
        if not row.passed:
            parts = []
            if row.variance_pct > cfg.max_variance_pct:
                parts.append(f"ttft variance {row.variance_pct:.4f}% exceeds limit")
            if row.tokens_variance_pct > cfg.max_variance_pct:
                parts.append(f"tokens variance {row.tokens_variance_pct:.4f}% exceeds limit")
            if row.error_rate_variance_pct > cfg.max_variance_pct:
                parts.append(
                    f"error-rate variance {row.error_rate_variance_pct:.4f}% exceeds limit"
                )
            row.failure_reason = "; ".join(parts)
            gate.passed = False
        gate.scenarios.append(row)
    return gate


def _evaluate_significance(cfg: Config, same_source: bool) -> SignificanceGate:
    gate = SignificanceGate(
        regression_pct_limit=cfg.regression_pct_limit,
        alpha=cfg.significance_alpha,
        bootstrap_iterations=cfg.bootstrap_iterations,
        min_samples_per_scenario=cfg.min_samples_per_scenario,
        min_cliffs_delta_for_failure=cfg.min_cliffs_delta_for_failure,
    )
    for scenario in cfg.scenarios:
        row = ScenarioSignificance(scenario=scenario)
        candidate_runs = discover_runs(Path(cfg.candidate_root) / scenario)
        baseline_runs = discover_runs(Path(cfg.baseline_root) / scenario)
        if not candidate_runs or not baseline_runs:
            # No baseline to compare against: informational skip.
            row.informational_only = True
            gate.scenarios.append(row)
            continue
        try:
            _, _, _, cand_pooled = _scenario_metrics(candidate_runs)
            _, _, _, base_pooled = _scenario_metrics(baseline_runs)
        except (OSError, ValueError, json.JSONDecodeError) as exc:
            row.passed = False
            row.failure_reason = f"unreadable run artifacts: {exc}"
            gate.passed = False
            gate.scenarios.append(row)
            continue
        candidate = [v for run in cand_pooled for v in run]
        baseline = [v for run in base_pooled for v in run]
        row.candidate_n = len(candidate)
        row.baseline_n = len(baseline)
        row.candidate_ttft_p95 = quantile(candidate, 0.95)
        row.baseline_ttft_p95 = quantile(baseline, 0.95)
        if row.baseline_ttft_p95 > 0:
            row.ttft_regression_pct = (
                (row.candidate_ttft_p95 - row.baseline_ttft_p95)
                / row.baseline_ttft_p95
                * 100.0
            )
        row.minimum_samples_reached = (
            row.candidate_n >= cfg.min_samples_per_scenario
            and row.baseline_n >= cfg.min_samples_per_scenario
        )
        row.mann_whitney_p_value = mann_whitney_p_value(candidate, baseline)
        row.bootstrap_delta_ci95 = bootstrap_delta_ci(
            candidate,
            baseline,
            0.95,
            cfg.bootstrap_iterations,
            cfg.bootstrap_seed,
        )
        row.cliffs_delta = cliffs_delta(candidate, baseline)
        row.practical_effect_pass = (
            abs(row.cliffs_delta) >= cfg.min_cliffs_delta_for_failure
        )
        if same_source:
            row.informational_only = True
            gate.scenarios.append(row)
            continue
        if not row.minimum_samples_reached:
            row.informational_only = True
            row.failure_reason = (
                f"insufficient samples (candidate={row.candidate_n}, "
                f"baseline={row.baseline_n}, required={cfg.min_samples_per_scenario})"
            )
            gate.scenarios.append(row)
            continue
        ci_low, ci_high = row.bootstrap_delta_ci95
        is_regression = (
            row.ttft_regression_pct > cfg.regression_pct_limit
            and row.mann_whitney_p_value < cfg.significance_alpha
            and ci_low > 0
        )
        if is_regression and row.practical_effect_pass:
            row.passed = False
            row.failure_reason = (
                f"ttft regression {row.ttft_regression_pct:.4f}% exceeds "
                f"{cfg.regression_pct_limit:.4f}% with "
                f"p={row.mann_whitney_p_value:.6f} "
                f"CI95[{ci_low:.4f}, {ci_high:.4f}] and Cliff's delta "
                f"{row.cliffs_delta:.4f}"
            )
            gate.passed = False
        elif is_regression:
            row.failure_reason = (
                f"statistical regression detected but |Cliff's delta| "
                f"{abs(row.cliffs_delta):.4f} < "
                f"{cfg.min_cliffs_delta_for_failure:.4f} practical threshold"
            )
        gate.scenarios.append(row)
    return gate
