"""Statistical primitives for the M5 release gates.

Reference: ``pkg/releasegate/gate.go:816-946`` — Mann-Whitney U with tie
correction and normal approximation (continuity-corrected), Cliff's
delta, and a seeded bootstrap CI for quantile deltas.  Pure functions,
deterministic under a fixed seed.
"""

from __future__ import annotations

import math
import random

from tpuslo.slo.calculator import quantile


def mean(values: list[float]) -> float:
    return sum(values) / len(values) if values else 0.0


def stddev(values: list[float]) -> float:
    """Population standard deviation."""
    if len(values) < 2:
        return 0.0
    m = mean(values)
    return math.sqrt(sum((v - m) ** 2 for v in values) / len(values))


def coefficient_of_variance_pct(values: list[float]) -> float:
    m = mean(values)
    if m == 0:
        return 0.0
    return (stddev(values) / abs(m)) * 100.0


def normal_cdf(z: float) -> float:
    return 0.5 * (1.0 + math.erf(z / math.sqrt(2.0)))


def mann_whitney_p_value(x: list[float], y: list[float]) -> float:
    """Two-sided Mann-Whitney U p-value (normal approximation).

    Ties get average ranks with the variance tie-correction term; the
    z statistic is continuity-corrected by 0.5.
    """
    nx, ny = len(x), len(y)
    if nx == 0 or ny == 0:
        return 1.0

    points = sorted(
        [(v, 0) for v in x] + [(v, 1) for v in y], key=lambda p: p[0]
    )
    ranks = [0.0] * len(points)
    tie_sum = 0.0
    i = 0
    while i < len(points):
        j = i + 1
        while j < len(points) and points[j][0] == points[i][0]:
            j += 1
        avg_rank = (i + 1 + j) / 2.0
        for k in range(i, j):
            ranks[k] = avg_rank
        t = j - i
        if t > 1:
            tie_sum += t**3 - t
        i = j

    rank_x = sum(rank for rank, (_, group) in zip(ranks, points) if group == 0)
    u1 = rank_x - nx * (nx + 1) / 2.0
    u2 = nx * ny - u1
    u = min(u1, u2)

    n = nx + ny
    mean_u = nx * ny / 2.0
    variance_u = (nx * ny / 12.0) * ((n + 1.0) - tie_sum / (n * (n - 1.0)))
    if variance_u <= 0:
        return 1.0

    z = u - mean_u
    z = (z - 0.5) / math.sqrt(variance_u) if z > 0 else (z + 0.5) / math.sqrt(variance_u)
    p = 2.0 * (1.0 - normal_cdf(abs(z)))
    return min(max(p, 0.0), 1.0)


def cliffs_delta(x: list[float], y: list[float]) -> float:
    """Cliff's delta effect size in [-1, 1]."""
    if not x or not y:
        return 0.0
    greater = sum(1 for xv in x for yv in y if xv > yv)
    lower = sum(1 for xv in x for yv in y if xv < yv)
    return (greater - lower) / (len(x) * len(y))


def bootstrap_delta_ci(
    candidate: list[float],
    baseline: list[float],
    quant: float,
    iterations: int,
    seed: int,
) -> tuple[float, float]:
    """Seeded bootstrap CI95 for quantile(candidate) - quantile(baseline)."""
    if not candidate or not baseline or iterations < 10:
        return 0.0, 0.0
    rng = random.Random(seed)
    deltas = []
    for _ in range(iterations):
        cand = [candidate[rng.randrange(len(candidate))] for _ in candidate]
        base = [baseline[rng.randrange(len(baseline))] for _ in baseline]
        deltas.append(quantile(cand, quant) - quantile(base, quant))
    deltas.sort()
    low_idx = max(0, math.floor(0.025 * (len(deltas) - 1)))
    high_idx = min(len(deltas) - 1, math.ceil(0.975 * (len(deltas) - 1)))
    return deltas[low_idx], deltas[high_idx]
