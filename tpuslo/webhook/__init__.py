from tpuslo.webhook.exporter import (
    FORMAT_GENERIC,
    FORMAT_OPSGENIE,
    FORMAT_PAGERDUTY,
    Exporter,
    WebhookError,
    compute_hmac,
    verify_hmac,
)
from tpuslo.webhook.opsgenie import build_opsgenie_payload
from tpuslo.webhook.pagerduty import build_pagerduty_payload

__all__ = [
    "FORMAT_GENERIC",
    "FORMAT_OPSGENIE",
    "FORMAT_PAGERDUTY",
    "Exporter",
    "WebhookError",
    "build_opsgenie_payload",
    "build_pagerduty_payload",
    "compute_hmac",
    "verify_hmac",
]
