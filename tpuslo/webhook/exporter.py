"""HMAC-signed incident webhook delivery with retry/backoff.

Reference: ``pkg/webhook/exporter.go:63-140`` — exponential backoff over
3 attempts, 4xx non-retryable, ``X-Webhook-Signature: sha256=<hex>``.
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import json
import time
import urllib.error
import urllib.request
from typing import Callable

from tpuslo.schema import IncidentAttribution
from tpuslo.webhook.opsgenie import build_opsgenie_payload
from tpuslo.webhook.pagerduty import build_pagerduty_payload

FORMAT_GENERIC = "generic"
FORMAT_PAGERDUTY = "pagerduty"
FORMAT_OPSGENIE = "opsgenie"

USER_AGENT = "tpuslo/webhook"


class WebhookError(RuntimeError):
    def __init__(self, message: str, retryable: bool = True):
        super().__init__(message)
        self.retryable = retryable


def compute_hmac(payload: bytes, secret: str) -> str:
    mac = hmac_mod.new(secret.encode(), payload, hashlib.sha256)
    return "sha256=" + mac.hexdigest()


def verify_hmac(payload: bytes, secret: str, signature: str) -> bool:
    return hmac_mod.compare_digest(compute_hmac(payload, secret), signature)


class Exporter:
    """Delivers incident attributions to an HTTP webhook endpoint."""

    def __init__(
        self,
        url: str,
        secret: str = "",
        format: str = FORMAT_GENERIC,
        timeout_ms: int = 5000,
        max_retry: int = 3,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.url = url
        self.secret = secret
        self.format = format or FORMAT_GENERIC
        self.timeout_s = (timeout_ms if timeout_ms > 0 else 5000) / 1000.0
        self.max_retry = max_retry
        self._sleep = sleep

    def build_payload(self, attr: IncidentAttribution) -> bytes:
        if self.format == FORMAT_PAGERDUTY:
            return build_pagerduty_payload(attr)
        if self.format == FORMAT_OPSGENIE:
            return build_opsgenie_payload(attr)
        return json.dumps(attr.to_dict()).encode()

    def send(self, attr: IncidentAttribution) -> None:
        """Deliver one attribution; raises WebhookError on final failure."""
        payload = self.build_payload(attr)
        last_error: WebhookError | None = None
        for attempt in range(self.max_retry):
            if attempt > 0:
                self._sleep(float(1 << (attempt - 1)))
            try:
                self._post(payload)
                return
            except WebhookError as exc:
                last_error = exc
                if not exc.retryable:
                    raise
        raise WebhookError(
            f"webhook delivery failed after {self.max_retry} attempts: {last_error}"
        )

    def _post(self, payload: bytes) -> None:
        headers = {
            "Content-Type": "application/json",
            "User-Agent": USER_AGENT,
        }
        if self.secret:
            headers["X-Webhook-Signature"] = compute_hmac(payload, self.secret)
        req = urllib.request.Request(
            self.url, data=payload, headers=headers, method="POST"
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                resp.read()
                status = resp.status
        except urllib.error.HTTPError as exc:
            status = exc.code
        except urllib.error.URLError as exc:
            raise WebhookError(f"http post failed: {exc.reason}") from exc
        if status >= 500:
            raise WebhookError(f"server error: HTTP {status}")
        if status >= 400:
            raise WebhookError(f"client error: HTTP {status}", retryable=False)
