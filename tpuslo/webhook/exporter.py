"""HMAC-signed incident webhook delivery with retry/backoff.

Reference: ``pkg/webhook/exporter.go:63-140`` — exponential backoff over
3 attempts, 4xx non-retryable, ``X-Webhook-Signature: sha256=<hex>``.
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import http.client
import json
import random
import time
import urllib.error
import urllib.request
from typing import Callable

from tpuslo.delivery import full_jitter_delay
from tpuslo.schema import IncidentAttribution
from tpuslo.webhook.opsgenie import build_opsgenie_payload
from tpuslo.webhook.pagerduty import build_pagerduty_payload

FORMAT_GENERIC = "generic"
FORMAT_PAGERDUTY = "pagerduty"
FORMAT_OPSGENIE = "opsgenie"

USER_AGENT = "tpuslo/webhook"


class WebhookError(RuntimeError):
    def __init__(self, message: str, retryable: bool = True):
        super().__init__(message)
        self.retryable = retryable


def compute_hmac(payload: bytes, secret: str) -> str:
    mac = hmac_mod.new(secret.encode(), payload, hashlib.sha256)
    return "sha256=" + mac.hexdigest()


def verify_hmac(payload: bytes, secret: str, signature: str) -> bool:
    return hmac_mod.compare_digest(compute_hmac(payload, secret), signature)


class Exporter:
    """Delivers incident attributions to an HTTP webhook endpoint."""

    def __init__(
        self,
        url: str,
        secret: str = "",
        format: str = FORMAT_GENERIC,
        timeout_ms: int = 5000,
        max_retry: int = 3,
        base_delay_s: float = 1.0,
        max_delay_s: float = 8.0,
        sleep: Callable[[float], None] = time.sleep,
        rng: Callable[[], float] = random.random,
    ):
        self.url = url
        self.secret = secret
        self.format = format or FORMAT_GENERIC
        self.timeout_s = (timeout_ms if timeout_ms > 0 else 5000) / 1000.0
        self.max_retry = max_retry
        self.base_delay_s = base_delay_s
        self.max_delay_s = max_delay_s
        self._sleep = sleep
        self._rng = rng

    def build_payload(self, attr: IncidentAttribution) -> bytes:
        if self.format == FORMAT_PAGERDUTY:
            return build_pagerduty_payload(attr)
        if self.format == FORMAT_OPSGENIE:
            return build_opsgenie_payload(attr)
        return json.dumps(attr.to_dict()).encode()

    def send(self, attr: IncidentAttribution) -> None:
        """Deliver one attribution; raises WebhookError on final failure."""
        payload = self.build_payload(attr)
        last_error: WebhookError | None = None
        for attempt in range(self.max_retry):
            if attempt > 0:
                # Full jitter with a hard cap: a hung endpoint already
                # consumed timeout_s per attempt, so unjittered 1-2-4s
                # sleeps both synchronize retry storms across agents and
                # stack unbounded delay onto the caller.
                self._sleep(
                    full_jitter_delay(
                        attempt - 1, self.base_delay_s, self.max_delay_s,
                        self._rng,
                    )
                )
            try:
                self._post(payload)
                return
            except WebhookError as exc:
                last_error = exc
                if not exc.retryable:
                    raise
        raise WebhookError(
            f"webhook delivery failed after {self.max_retry} attempts: {last_error}"
        )

    def post_payload(self, payload: bytes) -> None:
        """Single-shot signed POST, no retries — the delivery channel
        owns backoff/spooling when the webhook routes through it."""
        self._post(payload)

    def _post(self, payload: bytes) -> None:
        headers = {
            "Content-Type": "application/json",
            "User-Agent": USER_AGENT,
        }
        if self.secret:
            headers["X-Webhook-Signature"] = compute_hmac(payload, self.secret)
        req = urllib.request.Request(
            self.url, data=payload, headers=headers, method="POST"
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                resp.read()
                status = resp.status
        except urllib.error.HTTPError as exc:
            status = exc.code
        except TimeoutError as exc:
            # A hang consumes the full timeout budget; it is explicitly
            # retryable (the endpoint may just be overloaded).
            raise WebhookError(
                f"timed out after {self.timeout_s:.1f}s", retryable=True
            ) from exc
        except urllib.error.URLError as exc:
            if isinstance(exc.reason, TimeoutError):
                raise WebhookError(
                    f"timed out after {self.timeout_s:.1f}s", retryable=True
                ) from exc
            raise WebhookError(f"http post failed: {exc.reason}") from exc
        except (http.client.HTTPException, OSError) as exc:
            # Dropped mid-exchange (BadStatusLine / RemoteDisconnected):
            # an endpoint outage, retryable like any 5xx.
            raise WebhookError(f"http post failed: {exc!r}") from exc
        if status >= 500:
            raise WebhookError(f"server error: HTTP {status}")
        if status in (408, 429):
            # Rate limiting / request timeout: retryable by definition.
            raise WebhookError(f"throttled: HTTP {status}", retryable=True)
        if status >= 400:
            raise WebhookError(f"client error: HTTP {status}", retryable=False)
