"""PagerDuty Events API v2 payload builder.

Reference: ``pkg/webhook/pagerduty.go:29-61`` — severity escalates to
``critical`` at confidence ≥ 0.8.  The burn engine adds a second
escalation path: an incident that fires while a fast-burn page is
active (or whose SLO impact burns at page rate) is ``critical``
regardless of attribution confidence — budget exhaustion outranks
classifier certainty.
"""

from __future__ import annotations

import json

from tpuslo.schema import IncidentAttribution

#: Burn rate at which severity escalates regardless of confidence —
#: the fast-burn page threshold (SRE 1h+5m rule).
FAST_BURN_ESCALATION = 14.4


def _fast_burning(attr: IncidentAttribution) -> bool:
    if attr.slo_impact.burn_rate >= FAST_BURN_ESCALATION:
        return True
    for entry in (attr.slo_burn or {}).get("alerting", []):
        if entry.get("state") == "fast_burn":
            return True
    return False


def build_pagerduty_payload(attr: IncidentAttribution) -> bytes:
    severity = (
        "critical"
        if attr.confidence >= 0.8 or _fast_burning(attr)
        else "warning"
    )
    evidence = "; ".join(f"{e.signal}={e.value}" for e in attr.evidence)
    burn_rate = attr.slo_impact.burn_rate
    payload = {
        "routing_key": "",
        "event_action": "trigger",
        "payload": {
            "summary": (
                f"[{attr.service}] {attr.predicted_fault_domain} fault detected "
                f"(confidence={attr.confidence:.2f})"
            ),
            "source": f"{attr.cluster}/{attr.service}",
            "severity": severity,
            "timestamp": attr.timestamp.strftime("%Y-%m-%dT%H:%M:%S.000+0000"),
            "component": attr.service,
            "group": attr.cluster,
            "custom_details": {
                "incident_id": attr.incident_id,
                "fault_domain": attr.predicted_fault_domain,
                "confidence": f"{attr.confidence:.4f}",
                "evidence": evidence,
                "burn_rate": f"{burn_rate:.2f}",
            },
        },
    }
    if attr.slo_burn:
        payload["payload"]["custom_details"]["burning_budgets"] = [
            f"{entry.get('tenant', '?')}/{entry.get('objective', '?')}"
            f"={entry.get('state', '?')}"
            for entry in attr.slo_burn.get("alerting", [])
        ]
    return json.dumps(payload).encode()
