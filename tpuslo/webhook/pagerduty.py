"""PagerDuty Events API v2 payload builder.

Reference: ``pkg/webhook/pagerduty.go:29-61`` — severity escalates to
``critical`` at confidence ≥ 0.8.
"""

from __future__ import annotations

import json

from tpuslo.schema import IncidentAttribution


def build_pagerduty_payload(attr: IncidentAttribution) -> bytes:
    severity = "critical" if attr.confidence >= 0.8 else "warning"
    evidence = "; ".join(f"{e.signal}={e.value}" for e in attr.evidence)
    burn_rate = attr.slo_impact.burn_rate
    payload = {
        "routing_key": "",
        "event_action": "trigger",
        "payload": {
            "summary": (
                f"[{attr.service}] {attr.predicted_fault_domain} fault detected "
                f"(confidence={attr.confidence:.2f})"
            ),
            "source": f"{attr.cluster}/{attr.service}",
            "severity": severity,
            "timestamp": attr.timestamp.strftime("%Y-%m-%dT%H:%M:%S.000+0000"),
            "component": attr.service,
            "group": attr.cluster,
            "custom_details": {
                "incident_id": attr.incident_id,
                "fault_domain": attr.predicted_fault_domain,
                "confidence": f"{attr.confidence:.4f}",
                "evidence": evidence,
                "burn_rate": f"{burn_rate:.2f}",
            },
        },
    }
    return json.dumps(payload).encode()
