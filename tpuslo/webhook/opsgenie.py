"""Opsgenie Alert API payload builder.

Reference: ``pkg/webhook/opsgenie.go:24-58`` — P2 at confidence ≥ 0.8,
P1 at burn rate ≥ 3.0.
"""

from __future__ import annotations

import json

from tpuslo.schema import IncidentAttribution


def build_opsgenie_payload(attr: IncidentAttribution) -> bytes:
    priority = "P3"
    if attr.confidence >= 0.8:
        priority = "P2"
    burn_rate = attr.slo_impact.burn_rate
    if burn_rate >= 3.0:
        priority = "P1"
    # Burn-engine escalation: an active fast-burn page outranks the
    # confidence tiers — the budget is draining now.
    if any(
        entry.get("state") == "fast_burn"
        for entry in (attr.slo_burn or {}).get("alerting", [])
    ):
        priority = "P1"
    evidence = "; ".join(f"{e.signal}={e.value}" for e in attr.evidence)
    payload = {
        "message": f"[{attr.service}] {attr.predicted_fault_domain} fault detected",
        "alias": attr.incident_id,
        "description": (
            f"Fault domain {attr.predicted_fault_domain} attributed with "
            f"confidence {attr.confidence:.4f}. Evidence: {evidence}"
        ),
        "priority": priority,
        "source": f"{attr.cluster}/{attr.service}",
        "tags": ["tpuslo", attr.predicted_fault_domain],
        "details": {
            "incident_id": attr.incident_id,
            "fault_domain": attr.predicted_fault_domain,
            "confidence": f"{attr.confidence:.4f}",
            "burn_rate": f"{burn_rate:.2f}",
        },
        "entity": attr.service,
    }
    if attr.slo_burn:
        payload["details"]["burning_budgets"] = "; ".join(
            f"{entry.get('tenant', '?')}/{entry.get('objective', '?')}"
            f"={entry.get('state', '?')}"
            for entry in attr.slo_burn.get("alerting", [])
        )
    return json.dumps(payload).encode()
